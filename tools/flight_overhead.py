#!/usr/bin/env python3
"""Measure flight-recorder overhead at the flagship shapes
(docs/OBSERVABILITY.md §"Recorder overhead").

Interleaved off/on repeats (off, on, off, on, ...) with one warmup per
variant first, reporting the min wall of each — sequential measurement
is dominated by machine-load drift (the PR 2 overhead table's caveat).

    JAX_PLATFORMS=cpu python tools/flight_overhead.py [--repeats 5] \
        [--window 8] [--configs raft-100k,pbft-100k-bcast]
"""
from __future__ import annotations

import argparse
import dataclasses
import pathlib
import sys
import time

_REPO = pathlib.Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--window", type=int, default=8)
    ap.add_argument("--configs", default="raft-100k,pbft-100k-bcast")
    args = ap.parse_args(argv)

    from benchmarks.run_benchmarks import CONFIGS
    from consensus_tpu.network import simulator

    for name in args.configs.split(","):
        off = CONFIGS[name]
        on = dataclasses.replace(off, telemetry_window=args.window)
        variants = {"off": (off, {}), "on": (on, {"telemetry": True})}
        walls: dict[str, list[float]] = {"off": [], "on": []}
        for key, (cfg, kw) in variants.items():  # compile + warm both
            simulator.run(cfg, warmup=True, **kw)
        for rep in range(args.repeats):
            for key, (cfg, kw) in variants.items():
                t0 = time.perf_counter()
                simulator.run(cfg, warmup=False, **kw)
                walls[key].append(time.perf_counter() - t0)
            print(f"  {name} rep {rep}: off={walls['off'][-1]:.3f}s "
                  f"on={walls['on'][-1]:.3f}s", file=sys.stderr)
        off_s, on_s = min(walls["off"]), min(walls["on"])
        print(f"{name}: off={off_s * 1e3:.1f} ms  "
              f"on(W={args.window})={on_s * 1e3:.1f} ms  "
              f"delta={100 * (on_s - off_s) / off_s:+.1f} %")
    return 0


if __name__ == "__main__":
    sys.exit(main())
