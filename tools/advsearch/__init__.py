"""Coverage-guided adversary search over the fault-knob space.

PR 10 built the attack primitives (crash/slot-miss/delay/targeted
streams) and PR 9 the judge (flight-recorder timelines); this package
closes the loop mechanically, the way 2601.00273's hand-derived RAFT
vulnerability taxonomy suggests a fuzzer should: a host-side search
loop (seeded counter-RNG sampling + evolutionary mutation + a
behavior-coverage map) over the adversary knob space, batching each
generation's candidates onto the grouped-sweep axis as ONE compiled
XLA program per (protocol, static shape) via
:func:`consensus_tpu.network.runner.run_knob_batch`, with fitness read
off the PR 9 timeline metrics (availability floor, stall ratio,
recovery rounds, never-recovered, DPoS LIB-stall).

Counterexamples the search surfaces ("findings") auto-distill into
named scenarios in the PR 10 format — Config overrides +
TimelineBounds, registered in ``consensus_tpu/scenarios`` via the
committed ``discovered.json`` catalog — and every catalog entry is
confirmed by a C++ oracle replay at small N before it enters.

    python -m tools.advsearch spaces
    python -m tools.advsearch search --space dpos-delivery --seed 7 \\
        --generations 8 --population 16 --state-dir out/
    python -m tools.advsearch distill --state-dir out/ --finding 0 \\
        --name my-discovered-attack
    python -m tools.advsearch smoke

Everything replays exactly from one ``--seed``: candidate sampling,
mutation, and per-lane trajectory seeds all draw from the registered
``STREAM_SEARCH`` counter-RNG stream (core/rng.py), and the per-
generation state file makes an interrupted search resume to the same
findings (docs/RESILIENCE.md §8).
"""
from .search import (SPACES, FINDING_FIELDS, SearchState, Space,  # noqa: F401
                     run_search, distill, load_state)
