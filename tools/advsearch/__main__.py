"""CLI for the coverage-guided adversary search (tools/advsearch).

    python -m tools.advsearch spaces
    python -m tools.advsearch search --space NAME --seed S \\
        --generations G --population P --state-dir DIR [--resume]
        [--findings-out findings.json] [--trace-out t.jsonl]
    python -m tools.advsearch distill --state-dir DIR --finding K \\
        --name NAME [--catalog PATH]
    python -m tools.advsearch report --state-dir DIR [--out PATH]
    python -m tools.advsearch smoke [--trace-out t.jsonl]

`report` writes a search state's findings — in particular §A.3
attack-space (TPU-only, unmirrored) findings, which can never be
oracle-confirmed and so can never enter the distilled catalog — to the
standalone attack-findings artifact (default
benchmarks/parts/attack_findings.json), OUTSIDE
scenarios/discovered.json: an attack search ends in a committed
report, not a distill refusal.

`search` runs on whatever JAX backend is up (the smoke gate pins
JAX_PLATFORMS=cpu); one generation = one compiled-program dispatch per
(protocol, shape) — wired as `dispatch` spans into --trace-out, which
is how the smoke subcommand PROVES the no-per-candidate-recompile
contract (span count == generation count). `distill` turns a recorded
finding into a named scenario in consensus_tpu/scenarios/
discovered.json after re-verifying its bounds end-to-end and its
C++ oracle replay.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys


def _log(msg: str) -> None:
    print(f"advsearch: {msg}", file=sys.stderr, flush=True)


def _write_findings(path, st) -> None:
    doc = {"version": 1, "space": st.space,
           "search_seed": st.search_seed,
           "generations": st.generations_done,
           "findings": st.findings}
    pathlib.Path(path).write_text(json.dumps(doc, indent=2,
                                             sort_keys=True))
    _log(f"{len(st.findings)} findings written to {path}")


def cmd_spaces(_args) -> int:
    from .search import SPACES
    for name, sp in sorted(SPACES.items()):
        knobs = ", ".join(f"{k.field}[{k.lo},{k.hi}]" for k in sp.knobs)
        mirror = "" if sp.mirrored else "  [TPU-only: not distillable]"
        print(f"{name}: {sp.base.protocol}, N={sp.base.n_nodes}, "
              f"{sp.base.n_rounds} rounds; knobs {knobs}{mirror}")
        print(f"  {sp.description}")
    return 0


def cmd_search(args) -> int:
    from .search import SPACES, run_search
    try:
        space = SPACES[args.space]
    except KeyError:
        raise SystemExit(f"advsearch: unknown space {args.space!r} "
                         f"(known: {sorted(SPACES)})")
    st = run_search(space, search_seed=args.seed,
                    generations=args.generations,
                    population=args.population,
                    state_dir=args.state_dir or None,
                    resume=args.resume,
                    budget_weight=args.budget_weight,
                    confirm=not args.no_confirm, log=_log)
    if args.findings_out:
        _write_findings(args.findings_out, st)
    best = max(st.last_eval, key=lambda e: e["fitness"]) \
        if st.last_eval else None
    print(json.dumps({
        "space": st.space, "search_seed": st.search_seed,
        "generations": st.generations_done,
        "population": st.population,
        "coverage_cells": len(st.coverage),
        "findings": len(st.findings),
        "best": None if best is None else
        {k: best[k] for k in ("knobs", "budget", "severity", "fitness")},
    }))
    return 0


def cmd_distill(args) -> int:
    from consensus_tpu import scenarios as scen

    from .search import distill, write_catalog
    # Reload by recorded identity: the state file names its own space/
    # seed/population, so distill needs only the directory.
    st = _load_state_by_identity(args.state_dir)
    if not st.findings:
        raise SystemExit("advsearch: the search recorded no findings — "
                         "nothing to distill")
    try:
        entry = distill(st, args.finding, args.name,
                        description=args.description)
    except ValueError as exc:
        raise SystemExit(f"advsearch: {exc}")
    catalog = args.catalog or str(
        pathlib.Path(scen.__file__).with_name("discovered.json"))
    write_catalog(entry, catalog)
    _log(f"scenario {args.name!r} entered the catalog at {catalog} "
         f"(oracle digest {entry['finding']['oracle']['digest'][:16]}…); "
         f"run it with: consensus-sim --scenario {args.name}")
    print(json.dumps(entry["scenario"]))
    return 0


DEFAULT_REPORT = "benchmarks/parts/attack_findings.json"
DEFAULT_BUDGETS = "benchmarks/parts/search_budgets.json"


def cmd_promote(args) -> int:
    from consensus_tpu import scenarios as scen

    from .search import promote
    catalog = args.catalog or str(
        pathlib.Path(scen.__file__).with_name("discovered.json"))
    seeds = tuple(int(x) for x in args.seeds.split(",") if x.strip())
    try:
        rec = promote(args.name, catalog, seeds=seeds,
                      n_sweeps=args.sweeps, log=_log)
    except ValueError as exc:
        raise SystemExit(f"advsearch: {exc}")
    _log(f"scenario {args.name!r} PROMOTED: bounds held on all "
         f"{len(seeds)} fresh seeds — tools/check.py's scenario layer "
         "now runs it as a CI smoke")
    print(json.dumps({"name": args.name, "promoted": rec}))
    return 0


def cmd_budget(args) -> int:
    from .search import budget_path
    out = args.out or str(
        pathlib.Path(__file__).resolve().parents[2] / DEFAULT_BUDGETS)
    p = pathlib.Path(out)
    doc = {"version": 1, "rows": []}
    if p.exists():
        doc = json.loads(p.read_text())
    rows = {(r["space"], r["search_seed"]): r
            for r in doc.get("rows", [])}
    for sd in args.state_dir:
        bp = budget_path(sd)
        if not bp.exists():
            raise SystemExit(
                f"advsearch: no search_budget.json in {sd} — the "
                "sidecar is written per generation by `search "
                "--state-dir`; run a search there first")
        row = json.loads(bp.read_text())
        rows[(row["space"], row["search_seed"])] = row
    doc["rows"] = sorted(rows.values(),
                         key=lambda r: (r["space"], r["search_seed"]))
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(p)
    _log(f"{len(args.state_dir)} search budget(s) folded into {out} "
         f"({len(doc['rows'])} rows total); tools/ledger.py ingests "
         "them as adv-search LEDGER rows")
    print(json.dumps({"rows": len(doc["rows"]), "out": out}))
    return 0


def _load_state_by_identity(state_dir):
    """Reload a state file by its own recorded identity (space/seed/
    population) — shared by distill and report."""
    from .search import SPACES, load_state
    doc = json.loads(
        (pathlib.Path(state_dir) / "search_state.json").read_text())
    st = load_state(state_dir, SPACES[doc["space"]], doc["search_seed"],
                    doc["population"])
    if st is None:
        raise SystemExit(f"advsearch: no search state in {state_dir}")
    return st


def cmd_report(args) -> int:
    from tools.validate_trace import validate_finding_doc

    from .search import SPACES, write_attack_report
    st = _load_state_by_identity(args.state_dir)
    if not st.findings:
        raise SystemExit("advsearch: the search recorded no findings — "
                         "nothing to report")
    out = args.out or str(
        pathlib.Path(__file__).resolve().parents[2] / DEFAULT_REPORT)
    # The entry's findings obey the same schema the findings artifact
    # does — reject a drifted state file rather than commit it.
    errs = validate_finding_doc("report", {
        "version": 1, "space": st.space, "search_seed": st.search_seed,
        "generations": st.generations_done, "findings": st.findings})
    if errs:
        for e in errs:
            _log(f"FAIL: {e}")
        return 1
    entry = write_attack_report(st, out)
    sp = SPACES[st.space]
    kind = ("oracle-mirrored" if sp.mirrored
            else "TPU-only, unmirrored — outside the distilled catalog "
                 "by design")
    _log(f"{len(st.findings)} findings from space {st.space!r} "
         f"({kind}) reported to {out}")
    print(json.dumps({"space": entry["space"],
                      "search_seed": entry["search_seed"],
                      "mirrored": entry["mirrored"],
                      "findings": len(entry["findings"]),
                      "out": out}))
    return 0


DEFAULT_XPROTO = "benchmarks/parts/cross_protocol.json"


def cmd_crossproto(args) -> int:
    """The shared-fault degradation ladder over all six engines
    (search.cross_protocol_ladder): one compiled program per engine,
    the drop-rate rungs as knob lanes, artifact committed so
    docs/RESILIENCE.md §8 can cite which protocol degrades first."""
    from .search import cross_protocol_ladder
    doc = cross_protocol_ladder(args.seed, log=_log)
    out = args.out or str(
        pathlib.Path(__file__).resolve().parents[2] / DEFAULT_XPROTO)
    p = pathlib.Path(out)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(p)
    _log(f"cross-protocol ladder written to {out}; degrades first: "
         f"{doc['degrades_first'][0]}")
    print(json.dumps({"degrades_first": doc["degrades_first"],
                      "out": out}))
    return 0


# The fixed smoke budget: tiny, seeded, CPU-friendly — the `make
# advsearch-smoke` gate (tools/check.py) and the tier-1 mirror test
# reuse these numbers verbatim so the two cannot drift.
SMOKE = dict(space="dpos-delivery", seed=2026, generations=2,
             population=6)


def cmd_smoke(args) -> int:
    """A bounded end-to-end search that ASSERTS the one-program-per-
    generation contract on its own trace: exactly `generations`
    dispatch spans (and at least one compile under them), then a clean
    findings schema. Exit nonzero on any violation — a tripwire, not a
    demo."""
    import tempfile

    from consensus_tpu.obs import trace as obs_trace

    from .search import SPACES, run_search
    trace_path = args.trace_out or str(
        pathlib.Path(tempfile.mkdtemp(prefix="advsmoke")) / "t.jsonl")
    obs_trace.configure(trace_path)
    try:
        st = run_search(SPACES[SMOKE["space"]],
                        search_seed=SMOKE["seed"],
                        generations=SMOKE["generations"],
                        population=SMOKE["population"],
                        confirm=False, log=_log)
    finally:
        obs_trace.close()
    spans = [json.loads(line) for line in
             pathlib.Path(trace_path).read_text().splitlines()[1:]]
    dispatches = [s for s in spans
                  if s.get("type") == "span" and s["name"] == "dispatch"]
    if len(dispatches) != SMOKE["generations"]:
        _log(f"FAIL: {len(dispatches)} dispatch spans for "
             f"{SMOKE['generations']} generations — candidates did not "
             "share the generation program")
        return 1
    for d in dispatches:
        if d["attrs"].get("n_candidates") != SMOKE["population"]:
            _log(f"FAIL: dispatch span carries n_candidates="
                 f"{d['attrs'].get('n_candidates')}, expected the full "
                 f"population {SMOKE['population']}")
            return 1
    from tools.validate_trace import validate_finding_doc
    errs = validate_finding_doc("smoke", {
        "version": 1, "space": st.space, "search_seed": st.search_seed,
        "generations": st.generations_done, "findings": st.findings})
    for e in errs:
        _log(f"FAIL: {e}")
    if errs:
        return 1
    _log(f"smoke ok: {SMOKE['generations']} generations == "
         f"{len(dispatches)} dispatch spans, {len(st.coverage)} "
         f"coverage cells, {len(st.findings)} findings (trace: "
         f"{trace_path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.advsearch",
        description="Coverage-guided adversary search over the fault-"
                    "knob space (docs/RESILIENCE.md §8).")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("spaces", help="list the searchable knob spaces")

    s = sub.add_parser("search", help="run (or resume) a search")
    s.add_argument("--space", required=True)
    s.add_argument("--seed", type=int, default=0,
                   help="search seed — every sample/mutation/eval seed "
                        "derives from it (STREAM_SEARCH), so runs "
                        "replay exactly")
    s.add_argument("--generations", type=int, default=8)
    s.add_argument("--population", type=int, default=16,
                   help="candidates per generation == vmap lanes of "
                        "the one compiled generation program")
    s.add_argument("--state-dir", default="",
                   help="resumable search state (search_state.json, "
                        "written atomically per generation)")
    s.add_argument("--resume", action="store_true",
                   help="continue from --state-dir's last completed "
                        "generation (identity-checked: a state file "
                        "from a different space/seed/population is "
                        "refused, not silently restarted)")
    s.add_argument("--budget-weight", type=float, default=0.5,
                   help="fitness = severity - weight * knob budget: "
                        "higher weights hunt damage at LOW rates")
    s.add_argument("--no-confirm", action="store_true",
                   help="skip the per-finding C++ oracle replay "
                        "(findings record oracle.confirmed = null; "
                        "distill will re-run it)")
    s.add_argument("--findings-out", default="",
                   help="write the findings artifact (schema-checked "
                        "by tools/validate_trace.py --finding)")
    s.add_argument("--trace-out", default="",
                   help="span JSONL (one `dispatch` span per "
                        "generation — the no-recompile witness)")

    d = sub.add_parser("distill",
                       help="turn a recorded finding into a named "
                            "scenario in the discovered catalog")
    d.add_argument("--state-dir", required=True)
    d.add_argument("--finding", type=int, default=0,
                   help="index into the state's findings list")
    d.add_argument("--name", required=True,
                   help="scenario name (collisions with the hand-built "
                        "library are rejected)")
    d.add_argument("--description", default="",
                   help="override the auto-generated description")
    d.add_argument("--catalog", default="",
                   help="catalog JSON path (default: the package's "
                        "consensus_tpu/scenarios/discovered.json)")

    x = sub.add_parser("crossproto",
                       help="run the shared-fault degradation ladder "
                            "over all six engines and commit the "
                            "comparison artifact (RESILIENCE §8)")
    x.add_argument("--seed", type=int, default=0)
    x.add_argument("--out", default="",
                   help=f"artifact path (default {DEFAULT_XPROTO})")

    r = sub.add_parser("report",
                       help="write a search state's findings to the "
                            "standalone attack-findings artifact — the "
                            "§A.3 (TPU-only, unmirrored) route that "
                            "cannot pass through the oracle-confirmed "
                            "distilled catalog")
    r.add_argument("--state-dir", required=True)
    r.add_argument("--out", default="",
                   help=f"report JSON path (default <repo>/"
                        f"{DEFAULT_REPORT}; entries keyed by "
                        "(space, search_seed), atomic replace)")

    m = sub.add_parser("smoke",
                       help="fixed tiny-budget search + one-program-"
                            "per-generation self-check (the `make "
                            "advsearch-smoke` gate)")
    m.add_argument("--trace-out", default="")

    p = sub.add_parser("promote",
                       help="re-run a distilled catalog scenario across "
                            "K fresh seeds at its tuned shape; mark it "
                            "promoted (a `make check` scenario smoke) "
                            "only if the bounds hold on EVERY seed")
    p.add_argument("--name", required=True,
                   help="catalog entry to promote (discovered.json)")
    p.add_argument("--seeds", default="11,23,37",
                   help="comma-separated fresh seeds the bounds must "
                        "hold on (all of them, or no promotion)")
    p.add_argument("--sweeps", type=int, default=2,
                   help="n_sweeps per promotion run")
    p.add_argument("--catalog", default="",
                   help="catalog JSON path (default: the package's "
                        "consensus_tpu/scenarios/discovered.json)")

    b = sub.add_parser("budget",
                       help="fold per-search cost sidecars "
                            "(search_budget.json, written next to the "
                            "search state) into the committed "
                            "search-budgets artifact tools/ledger.py "
                            "ingests as adv-search rows")
    b.add_argument("--state-dir", action="append", required=True,
                   help="search state dir to fold (repeatable; rows "
                        "keyed by (space, search_seed), atomic replace)")
    b.add_argument("--out", default="",
                   help=f"budgets JSON path (default <repo>/"
                        f"{DEFAULT_BUDGETS})")

    args = ap.parse_args(argv)
    if args.cmd == "search" and args.resume and not args.state_dir:
        ap.error("--resume needs --state-dir (there is no state to "
                 "resume without one)")
    return {"spaces": cmd_spaces, "search": cmd_search,
            "distill": cmd_distill, "report": cmd_report,
            "smoke": cmd_smoke, "promote": cmd_promote,
            "budget": cmd_budget,
            "crossproto": cmd_crossproto}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
