"""The advsearch engine: knob spaces, the generation loop, findings.

Determinism contract: every stochastic choice — fresh-sample values,
parent/knob picks, mutation deltas, per-lane trajectory seeds — is a
pure counter-RNG draw from ``STREAM_SEARCH`` keyed
``(generation, subdraw, index)`` under the one ``--seed``, so the same
seed replays the identical generation sequence, candidate-for-
candidate, and converges to the identical findings
(tests/test_advsearch.py). No wall clock, no ``random`` module.

One compiled program per generation per (protocol, shape): a
generation's candidates are vmap lanes of
:func:`consensus_tpu.network.runner.run_knob_batch` — knob cutoffs are
traced operands (core/knobs.KnobView), so only the first generation of
a space ever compiles; the trace's ``dispatch`` span count equals the
generation count (the smoke gate counts them).
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any

import numpy as np

from consensus_tpu.core import rng
from consensus_tpu.core.config import Config
from consensus_tpu.core.knobs import KNOB_COLUMNS

# Searchable rate knobs: Config float field -> its KNOB_COLUMNS cutoff.
RATE_CUTOFFS = {
    "drop_rate": "drop_cutoff",
    "partition_rate": "partition_cutoff",
    "churn_rate": "churn_cutoff",
    "crash_prob": "crash_cutoff",
    "recover_prob": "recover_cutoff",
    "miss_rate": "miss_cutoff",
    "suppress_rate": "suppress_cutoff",
    "attack_rate": "attack_cutoff",
    # SPEC §9b vote-certificate byzantine knobs (pbft/hotstuff switch
    # models): forged combines from byzantine aggregators, byzantine
    # replicas lying to their switch vertex.
    "agg_poison_rate": "agg_poison_cutoff",
    "byz_uplink_rate": "byz_uplink_cutoff",
    # SPEC §B per-node view-synchronizer timer skew (pbft/hotstuff).
    "desync_rate": "desync_cutoff",
}

# STREAM_SEARCH subdraw selectors (c0); c1 packs (candidate, knob) as
# candidate * _IDX_STRIDE + knob_index where both are needed.
_SUB_FRESH, _SUB_PARENT, _SUB_KNOB, _SUB_MUT, _SUB_SEED, _SUB_MODE = range(6)
_IDX_STRIDE = 64

# One finding = exactly these keys (the validate_trace --finding
# tripwire mirrors this tuple as FINDING_FIELDS — lint-synced both ways
# by tools/lint check `registry`, like the telemetry counters).
FINDING_FIELDS = ("schema", "space", "protocol", "generation",
                  "candidate", "eval_seed", "knobs", "budget", "severity",
                  "fitness", "metrics", "coverage_key", "oracle")


@dataclasses.dataclass(frozen=True)
class KnobRange:
    field: str   # Config float field (RATE_CUTOFFS key)
    lo: float
    hi: float


@dataclasses.dataclass(frozen=True)
class Space:
    """One searchable fault space: a gate-representative base config
    (static shape + every searched adversary's gate ON — see
    core/knobs.KnobView) plus the knob ranges the search varies.
    ``base.n_sweeps`` is ignored (the lane axis is sized per
    generation); ``base.telemetry_window`` must be > 0 (fitness reads
    the flight series). ``mirrored`` says whether every searched knob
    is implemented by the C++ oracle — findings from unmirrored spaces
    (SPEC §A.3 targeted attacks) cannot be oracle-confirmed and are
    refused by :func:`distill`."""
    name: str
    description: str
    base: Config
    knobs: tuple[KnobRange, ...]
    mirrored: bool = True

    def __post_init__(self):
        if self.base.telemetry_window <= 0:
            raise ValueError(f"space {self.name!r}: base needs "
                             "telemetry_window > 0 (fitness reads the "
                             "flight recorder)")
        for k in self.knobs:
            if k.field not in RATE_CUTOFFS:
                raise ValueError(f"space {self.name!r}: {k.field!r} is "
                                 f"not a searchable rate knob "
                                 f"({sorted(RATE_CUTOFFS)})")
            if not 0.0 <= k.lo < k.hi <= 1.0:
                raise ValueError(f"space {self.name!r}: {k.field} range "
                                 f"[{k.lo}, {k.hi}] must satisfy "
                                 "0 <= lo < hi <= 1")
            rep = getattr(self.base, k.field)
            if rep <= 0.0 and k.field != "recover_prob":
                raise ValueError(
                    f"space {self.name!r}: base.{k.field} = {rep} gates "
                    "the searched adversary OFF — the base must be "
                    "gate-representative (core/knobs.KnobView)")


# The curated spaces. Shapes stay small (N <= 2k keeps oracle replays
# seconds-class) but are sized so the COMMIT SUPPLY outlives the run
# (log capacity / max_entries >= n_rounds where the protocol consumes
# them): a log that exhausts mid-run caps availability for every
# candidate alike and drowns the fitness signal in a shape artifact.
# Static axes (max_delay_rounds depth, attack kind, max_crashed cap)
# are fixed per space — they select the compiled program, the traced
# knobs select the lane.
_ADV = dict(telemetry_window=4, n_rounds=96, seed=0)
SPACES: dict[str, Space] = {s.name: s for s in (
    Space(
        name="dpos-delivery",
        description="DPoS slot misses composed with heavy lossy/delayed "
                    "delivery and churn (crash machinery OFF — the "
                    "hand-built rolling-producer-outage owns that axis): "
                    "hunting LIB stalls at miss_rate well below 1/2. "
                    "Shape per the ROADMAP reshape: a SMALL producer set "
                    "(K = 3 ⇒ the LIB threshold T = 2K/3+1 = 3 equals "
                    "K, so ONE producer going stale at the head stalls "
                    "irreversibility) over LONG suffix windows "
                    "(epoch_len 48 pins the same set for half the run, "
                    "so a gappy producer cannot be rotated out from "
                    "under its own gap) — the old K = 6 / epoch 16 "
                    "shape needed two simultaneously-stale producers "
                    "and never dropped lib_ratio below ~0.85.",
        base=Config(protocol="dpos", n_nodes=24, log_capacity=96,
                    n_candidates=12, n_producers=3, epoch_len=48,
                    drop_rate=0.3, miss_rate=0.1, max_delay_rounds=4,
                    churn_rate=0.01, suppress_rate=0.1,
                    suppress_window=48, **_ADV),
        knobs=(KnobRange("miss_rate", 0.05, 0.50),
               KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("churn_rate", 0.0, 0.10),
               # SPEC §A.4: the correlated (window-keyed) suppression
               # stream the §8 negative iid result asked for — the
               # window spans the whole epoch (48), so one draw
               # removes a producer from the suffix wholesale.
               KnobRange("suppress_rate", 0.0, 0.60))),
    Space(
        name="raft-elections",
        description="Raft liveness under composed loss/partition/churn/"
                    "crash with bounded delayed retransmissions.",
        base=Config(protocol="raft", n_nodes=7, log_capacity=128,
                    max_entries=96, drop_rate=0.3, partition_rate=0.1,
                    churn_rate=0.02, crash_prob=0.1, recover_prob=0.3,
                    max_crashed=3, max_delay_rounds=4, **_ADV),
        knobs=(KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("partition_rate", 0.0, 0.40),
               KnobRange("churn_rate", 0.0, 0.15),
               KnobRange("crash_prob", 0.0, 0.30),
               KnobRange("recover_prob", 0.05, 0.50))),
    Space(
        name="pbft-quorum",
        description="PBFT view-change/quorum suppression under crash "
                    "churn, partitions and loss.",
        base=Config(protocol="pbft", f=2, n_nodes=7, log_capacity=96,
                    drop_rate=0.3, partition_rate=0.1, churn_rate=0.02,
                    crash_prob=0.1, recover_prob=0.3, max_crashed=2,
                    max_delay_rounds=4, **_ADV),
        knobs=(KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("partition_rate", 0.0, 0.40),
               KnobRange("churn_rate", 0.0, 0.15),
               KnobRange("crash_prob", 0.0, 0.30),
               KnobRange("recover_prob", 0.05, 0.50))),
    Space(
        name="paxos-slots",
        description="Paxos learning stalls under composed loss/"
                    "partition/churn/crash.",
        base=Config(protocol="paxos", n_nodes=9, log_capacity=96,
                    drop_rate=0.3, partition_rate=0.1, churn_rate=0.02,
                    crash_prob=0.1, recover_prob=0.3, max_crashed=3,
                    max_delay_rounds=4, **_ADV),
        knobs=(KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("partition_rate", 0.0, 0.40),
               KnobRange("churn_rate", 0.0, 0.15),
               KnobRange("crash_prob", 0.0, 0.30),
               KnobRange("recover_prob", 0.05, 0.50))),
    Space(
        name="hotstuff-views",
        description="Chained-HotStuff view-timeout storms (SPEC §7b): "
                    "loss/partition/churn-driven QC starvation under "
                    "bounded §A.2 delayed retransmissions, at a SHORT "
                    "pacemaker timeout (view_timeout 4 and "
                    "max_delay_rounds 4 are the static axes) — hunting "
                    "knob compositions where failed views cascade "
                    "faster than the consecutive-view 3-chain can "
                    "re-form, so blocks keep certifying but chain "
                    "commits stall (chain_commit_lag, availability "
                    "dips the hand-built chained-commit-stall scenario "
                    "never composes with partitions).",
        base=Config(protocol="hotstuff", f=2, n_nodes=7,
                    log_capacity=96, view_timeout=4, drop_rate=0.3,
                    partition_rate=0.1, churn_rate=0.02,
                    max_delay_rounds=4, **_ADV),
        knobs=(KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("partition_rate", 0.0, 0.40),
               KnobRange("churn_rate", 0.0, 0.15))),
    Space(
        name="pbft-cert-poison",
        description="SPEC §9b poisoned vote certificates (pbft over the "
                    "switch fabric): 2 equivocating replicas lie to "
                    "their aggregator vertex with byz_uplink_rate while "
                    "1 of the 2 aggregators serves forged full-support "
                    "combines with agg_poison_rate, under light drops — "
                    "hunting compositions where a forged certificate "
                    "crosses the commit quorum and the §7c safety "
                    "counters fire (forked_qc / conflict_commits at "
                    "HONEST nodes), not merely a liveness dip.",
        base=Config(protocol="pbft", f=2, n_nodes=7, log_capacity=96,
                    net_model="switch", n_aggregators=2, agg_byz=1,
                    n_byzantine=2, byz_mode="equivocate",
                    agg_poison_rate=0.3, byz_uplink_rate=0.2,
                    drop_rate=0.1, **_ADV),
        knobs=(KnobRange("agg_poison_rate", 0.05, 0.95),
               KnobRange("byz_uplink_rate", 0.05, 0.95),
               KnobRange("drop_rate", 0.0, 0.40))),
    Space(
        name="hotstuff-forked-qc",
        description="SPEC §7c x §9b: an equivocating hotstuff leader "
                    "(dual block variants, per-value QC tallies) over a "
                    "half-poisoned switch fabric — the byzantine "
                    "aggregator inflates BOTH variants' tallies toward "
                    "full segment support, so the search hunts the "
                    "poison/uplink/drop composition that forges a "
                    "forked QC (two certificates at one height) or "
                    "conflicting honest commits, at a short pacemaker "
                    "timeout.",
        base=Config(protocol="hotstuff", f=2, n_nodes=7,
                    log_capacity=96, view_timeout=4, net_model="switch",
                    n_aggregators=2, agg_byz=1, n_byzantine=2,
                    byz_mode="equivocate", agg_poison_rate=0.3,
                    byz_uplink_rate=0.2, drop_rate=0.1, **_ADV),
        knobs=(KnobRange("agg_poison_rate", 0.05, 0.95),
               KnobRange("byz_uplink_rate", 0.05, 0.95),
               KnobRange("drop_rate", 0.0, 0.40))),
    Space(
        name="hotstuff-view-desync",
        description="SPEC §B view desync on chained HotStuff: "
                    "STREAM_DESYNC timer skew (max_skew_rounds 4 is the "
                    "static axis) fires premature local view changes "
                    "while drops keep the highest-QC gossip from healing "
                    "the spread — hunting the desync/drop/churn "
                    "composition where per-node views diverge faster "
                    "than catch-up converges them, at the short "
                    "pacemaker timeout. The tuned view-desync-storm "
                    "scenario is one point of this space; the search "
                    "asks how little skew still starves commits.",
        base=Config(protocol="hotstuff", f=2, n_nodes=7,
                    log_capacity=96, view_timeout=4, desync_rate=0.15,
                    max_skew_rounds=4, drop_rate=0.25, churn_rate=0.02,
                    **_ADV),
        knobs=(KnobRange("desync_rate", 0.02, 0.60),
               KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("churn_rate", 0.0, 0.15))),
    Space(
        name="hotstuff-forked-qc-1k",
        description="The hotstuff-forked-qc §7c x §9b composition at "
                    "big N (N = 1024, f = 341, 16 aggregators ⇒ 64-node "
                    "segments): one poisoned tail aggregator now forges "
                    "a full 64-vote segment per serve — does the silent "
                    "QC fork that needs only ~2f+1 = 683 tallied votes "
                    "get EASIER as segment width grows, or does the "
                    "honest-majority mass of the other 15 segments "
                    "drown the forgery? Findings (or the negative) "
                    "recorded in docs/RESILIENCE.md §8.",
        base=Config(protocol="hotstuff", f=341, n_nodes=1024,
                    log_capacity=96, view_timeout=4, net_model="switch",
                    n_aggregators=16, agg_byz=1, n_byzantine=341,
                    byz_mode="equivocate", agg_poison_rate=0.3,
                    byz_uplink_rate=0.2, drop_rate=0.1, **_ADV),
        knobs=(KnobRange("agg_poison_rate", 0.05, 0.95),
               KnobRange("byz_uplink_rate", 0.05, 0.95),
               KnobRange("drop_rate", 0.0, 0.40))),
    Space(
        name="pbft-quorum-1k",
        description="The pbft-quorum composition at the SPEC §6b big-N "
                    "broadcast fault model (N = 1024, f = 341): "
                    "per-sender broadcast drops, partitions, churn and "
                    "§6c crash waves at a four-digit population — does "
                    "the N = 7 space's compound quorum starvation "
                    "survive the law of large numbers, or does the "
                    "f-ladder's slack absorb it? Oracle replays stay "
                    "seconds-class (docs/RESILIENCE.md §8).",
        base=Config(protocol="pbft", f=341, n_nodes=1024,
                    fault_model="bcast", log_capacity=96, drop_rate=0.3,
                    partition_rate=0.1, churn_rate=0.02, crash_prob=0.1,
                    recover_prob=0.3, max_crashed=64,
                    max_delay_rounds=2, **_ADV),
        knobs=(KnobRange("drop_rate", 0.05, 0.60),
               KnobRange("partition_rate", 0.0, 0.40),
               KnobRange("churn_rate", 0.0, 0.15),
               KnobRange("crash_prob", 0.0, 0.30),
               KnobRange("recover_prob", 0.05, 0.50))),
    Space(
        name="raft-attack-elect",
        description="SPEC §A.3 repeated election disruption: how low "
                    "an attack_rate still denies liveness. TPU-only "
                    "(the oracle does not mirror targeted attacks) — "
                    "findings cannot enter the distilled catalog.",
        base=Config(protocol="raft", n_nodes=7, log_capacity=128,
                    max_entries=96, drop_rate=0.05, attack="elect",
                    attack_rate=0.9, **_ADV),
        knobs=(KnobRange("attack_rate", 0.2, 1.0),
               KnobRange("drop_rate", 0.0, 0.30)),
        mirrored=False),
)}


# --- counter-RNG helpers ----------------------------------------------------

def _u01(seed: int, gen: int, sub: int, idx: int) -> float:
    return float(rng.random_u32_np(seed, rng.STREAM_SEARCH,
                                   np.uint32(gen), np.uint32(sub),
                                   np.uint32(idx))) / 2.0 ** 32


def _rate(v: float) -> float:
    # 4-decimal knob values: short scenario overrides, identical
    # cutoffs between the lane encoding and a distilled Config replay.
    return round(v, 4)


def eval_seed(search_seed: int, gen: int, cand: int) -> int:
    """Per-(generation, candidate) trajectory seed — recorded in each
    finding so a replay is exact."""
    return int(rng.random_u32_np(search_seed, rng.STREAM_SEARCH,
                                 np.uint32(gen), np.uint32(_SUB_SEED),
                                 np.uint32(cand)))


# --- candidates and generations ---------------------------------------------

def _fresh(space: Space, seed: int, gen: int, cand: int) -> dict[str, float]:
    out = {}
    for ki, k in enumerate(space.knobs):
        u = _u01(seed, gen, _SUB_FRESH, cand * _IDX_STRIDE + ki)
        out[k.field] = _rate(k.lo + u * (k.hi - k.lo))
    return out


def _mutate(space: Space, seed: int, gen: int, cand: int,
            parent: dict[str, float]) -> dict[str, float]:
    ki = int(_u01(seed, gen, _SUB_KNOB, cand) * len(space.knobs))
    ki = min(ki, len(space.knobs) - 1)
    k = space.knobs[ki]
    u = _u01(seed, gen, _SUB_MUT, cand * _IDX_STRIDE + ki)
    step = (2.0 * u - 1.0) * 0.3 * (k.hi - k.lo)
    child = dict(parent)
    child[k.field] = _rate(min(k.hi, max(k.lo, parent[k.field] + step)))
    return child


def next_population(space: Space, seed: int, gen: int, population: int,
                    prev_eval: list[dict] | None,
                    fresh_frac: float = 0.25) -> list[dict[str, float]]:
    """Generation ``gen``'s candidate knob dicts — a pure function of
    (space, seed, gen, previous generation's evaluation), which is what
    makes a SIGKILLed search recompute the interrupted generation
    exactly on resume.

    Gen 0 is all fresh samples. Later generations keep the elite
    quartile (by fitness, ties broken candidate-index-ascending) plus
    every candidate that opened a NEW coverage cell last generation,
    then fill with mutations of elite parents and ``fresh_frac`` fresh
    samples.
    """
    if gen == 0 or not prev_eval:
        return [_fresh(space, seed, gen, c) for c in range(population)]
    ranked = sorted(prev_eval, key=lambda e: (-e["fitness"],
                                              e["candidate"]))
    n_elite = max(1, population // 4)
    elites = ranked[:n_elite]
    novel = [e for e in prev_eval
             if e.get("novel") and e not in elites]
    keep = (elites + novel)[:max(1, population // 2)]
    pop = [dict(e["knobs"]) for e in keep]
    for c in range(len(pop), population):
        if _u01(seed, gen, _SUB_MODE, c) < fresh_frac:
            pop.append(_fresh(space, seed, gen, c))
        else:
            pick = int(_u01(seed, gen, _SUB_PARENT, c) * len(keep))
            parent = keep[min(pick, len(keep) - 1)]["knobs"]
            pop.append(_mutate(space, seed, gen, c, parent))
    return pop


def knob_row(space: Space, knobs: dict[str, float]) -> list[int]:
    """A candidate's u32 kmat row (KNOB_COLUMNS order): the base
    config's cutoffs with the searched knobs' cutoffs substituted —
    exactly what ``dataclasses.replace(base, **knobs)`` would derive,
    so a finding's replay config is cutoff-identical to its lane."""
    cfg = dataclasses.replace(space.base, **knobs)
    return [int(getattr(cfg, name)) for name in KNOB_COLUMNS]


# --- fitness ----------------------------------------------------------------

def budget_of(space: Space, knobs: dict[str, float]) -> float:
    """Normalized attack budget in [0, 1]: mean knob position within
    its range (recover_prob inverted — LOW recovery is the expensive
    direction). Severity per unit budget is the search's 'surprise'
    signal: damage at low rates is what the hand-built library misses."""
    parts = []
    for k in space.knobs:
        x = (knobs[k.field] - k.lo) / (k.hi - k.lo)
        parts.append(1.0 - x if k.field == "recover_prob" else x)
    return round(sum(parts) / len(parts), 6)


def severity_of(metrics: dict[str, Any]) -> float:
    """Scalar liveness damage from one lane's fitness signals
    (obs/timeline.lane_fitness [+ lib_ratio for dpos]). A SAFETY
    violation (SPEC §7c forked QC / conflicting commits at honest
    nodes) dominates every liveness term: agreement is the invariant,
    availability merely the service level."""
    sev = (1.0 - metrics["availability"]) + 0.5 * metrics["stall_ratio"]
    if metrics["never_recovered"]:
        sev += 1.0
    lib = metrics.get("lib_ratio")
    if lib is not None:
        sev += 1.0 - lib
    if metrics.get("safety_violations"):
        sev += 3.0
    return round(sev, 6)


def coverage_key(metrics: dict[str, Any]) -> str:
    """Behavior-coverage cell: deciles of availability / stall ratio /
    LIB ratio plus the never-recovered flag. A candidate landing in an
    unseen cell is NOVEL — it survives into the next generation even
    with mediocre fitness, which is what makes the search
    coverage-guided rather than pure hill-climbing."""
    dec = lambda x: min(9, int(x * 10))  # noqa: E731
    lib = metrics.get("lib_ratio")
    viol = metrics.get("safety_violations")
    return "a{}s{}n{}l{}v{}".format(
        dec(metrics["availability"]), dec(metrics["stall_ratio"]),
        int(metrics["never_recovered"]),
        "-" if lib is None else dec(lib),
        # Safety cell: absent counters (non-BFT engines) vs clean vs
        # violated — a first safety break always opens a new cell.
        "-" if viol is None else min(9, viol))


# --- search state -----------------------------------------------------------

STATE_VERSION = 1


@dataclasses.dataclass
class SearchState:
    space: str
    search_seed: int
    population: int
    # Fitness/threshold parameters are search IDENTITY too:
    # budget_weight shapes every generation's elite selection, the
    # thresholds decide what becomes a finding — resuming under
    # different values would splice two searches no single run can
    # reproduce (load_state refuses the mismatch).
    params: dict = dataclasses.field(default_factory=dict)
    generations_done: int = 0
    coverage: dict = dataclasses.field(default_factory=dict)
    findings: list = dataclasses.field(default_factory=list)
    last_eval: list = dataclasses.field(default_factory=list)
    history: list = dataclasses.field(default_factory=list)

    def to_doc(self) -> dict:
        sp = SPACES[self.space]
        return {"version": STATE_VERSION, "space": self.space,
                "search_seed": self.search_seed,
                "population": self.population, "params": self.params,
                "base_config": json.loads(sp.base.to_json()),
                "knobs": [[k.field, k.lo, k.hi] for k in sp.knobs],
                "generations_done": self.generations_done,
                "coverage": self.coverage, "findings": self.findings,
                "last_eval": self.last_eval, "history": self.history}


def state_path(state_dir) -> pathlib.Path:
    return pathlib.Path(state_dir) / "search_state.json"


def save_state(state_dir, st: SearchState) -> None:
    """Atomic per-generation state write (tmp + rename), the search's
    analog of the runner's group manifest: a SIGKILL at any instant
    leaves the last completed generation durably recorded."""
    p = state_path(state_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(st.to_doc(), indent=2, sort_keys=True))
    tmp.replace(p)


BUDGET_VERSION = 1


def budget_path(state_dir) -> pathlib.Path:
    return pathlib.Path(state_dir) / "search_budget.json"


def budget_doc(st: SearchState, wall_s: float) -> dict:
    """One search's COST record: generation/evaluation totals plus wall
    time. Lives in a sidecar OUTSIDE search_state.json on purpose — the
    state file is part of the determinism contract (same seed ⇒
    byte-identical state, tests/test_advsearch.py compares `to_doc()`
    across fresh runs), and wall clock is exactly the thing that can
    never be deterministic. `python -m tools.advsearch budget` folds
    sidecars into benchmarks/parts/search_budgets.json, which
    tools/ledger.py ingests as `adv-search` LEDGER rows."""
    return {"version": BUDGET_VERSION, "space": st.space,
            "search_seed": st.search_seed, "population": st.population,
            "generations": st.generations_done,
            "evals": st.generations_done * st.population,
            "findings": len(st.findings),
            "coverage_cells": len(st.coverage),
            "wall_s": round(float(wall_s), 3)}


def save_budget(state_dir, st: SearchState, wall_s: float) -> None:
    p = budget_path(state_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(budget_doc(st, wall_s), indent=2,
                              sort_keys=True))
    tmp.replace(p)


def load_budget_wall(state_dir, st: SearchState) -> float:
    """Accumulated wall seconds a RESUMED search should continue from —
    0 when no sidecar exists or it belongs to a different search."""
    p = budget_path(state_dir)
    if not p.exists():
        return 0.0
    doc = json.loads(p.read_text())
    if (doc.get("space"), doc.get("search_seed")) != (st.space,
                                                      st.search_seed):
        return 0.0
    return float(doc.get("wall_s", 0.0))


def load_state(state_dir, space: Space, search_seed: int,
               population: int,
               params: dict | None = None) -> SearchState | None:
    """The resumable state for exactly (space, seed, population,
    fitness params) — or None when absent. A state file for a
    DIFFERENT search identity is an error, not a silent restart:
    resuming it would splice two unrelated searches' populations.
    ``params=None`` accepts whatever the state recorded (read-only
    consumers like ``distill``, which never advance the search)."""
    p = state_path(state_dir)
    if not p.exists():
        return None
    doc = json.loads(p.read_text())
    if doc.get("version") != STATE_VERSION:
        raise ValueError(f"{p}: state version {doc.get('version')!r} != "
                         f"{STATE_VERSION}")
    ident = {"space": space.name, "search_seed": search_seed,
             "population": population,
             "base_config": json.loads(space.base.to_json()),
             "knobs": [[k.field, k.lo, k.hi] for k in space.knobs]}
    if params is not None:
        ident["params"] = params
    got = {k: doc.get(k) for k in ident}
    if got != ident:
        diff = [k for k in ident if got[k] != ident[k]]
        raise ValueError(
            f"{p}: existing search state belongs to a different search "
            f"({', '.join(diff)} differ) — pass a fresh --state-dir or "
            "the original space/seed/population/fitness parameters")
    return SearchState(space=space.name, search_seed=search_seed,
                       population=population, params=doc.get("params", {}),
                       generations_done=doc["generations_done"],
                       coverage=doc["coverage"], findings=doc["findings"],
                       last_eval=doc["last_eval"], history=doc["history"])


# --- the generation loop ----------------------------------------------------

def _lane_metrics(space: Space, out: dict, flight: dict) -> list[dict]:
    from consensus_tpu.obs import timeline as obs_timeline
    tl = obs_timeline.from_flight_dict(flight)
    mets = obs_timeline.lane_fitness(tl)
    if space.base.protocol == "dpos":
        from consensus_tpu.engines.dpos import lib_index
        lib = np.asarray(lib_index(out["chain_p"], out["chain_len"],
                                   space.base.n_candidates,
                                   space.base.n_producers), np.int64)
        head = np.asarray(out["chain_len"], np.int64)
        for b, m in enumerate(mets):
            m["lib_ratio"] = round(
                float((lib[b] + 1).mean())
                / max(1.0, float(head[b].mean())), 6)
    return mets


def _dispatch(cfg, eng, seeds, kmat, *, generation: int, retries: int = 2,
              sleep=None):
    """One generation dispatch under bounded transient-retry — the
    supervisor's failure taxonomy (network/supervisor.is_transient),
    minus resume (a generation is atomic; its inputs replay exactly)."""
    import time as _time

    from consensus_tpu.network import runner, supervisor
    sleep = _time.sleep if sleep is None else sleep
    for attempt in range(retries + 1):
        try:
            return runner.run_knob_batch(cfg, eng, seeds, kmat,
                                         generation=generation)
        except Exception as exc:  # noqa: BLE001 — classified below
            if attempt >= retries or not supervisor.is_transient(exc):
                raise
            sleep(0.5 * 2 ** attempt)
    raise AssertionError("unreachable")


def run_search(space: Space, *, search_seed: int, generations: int,
               population: int, state_dir=None, resume: bool = False,
               budget_weight: float = 0.5, max_budget: float = 0.85,
               max_availability: float = 0.7, max_lib_ratio: float = 0.5,
               confirm: bool = True, log=None) -> SearchState:
    """Run (or resume) a search; returns the final state.

    A FINDING is a candidate whose lane shows real liveness damage —
    ``availability <= max_availability``, or never-recovered, or (DPoS)
    ``lib_ratio <= max_lib_ratio`` — at attack budget
    ``<= max_budget`` (full-throttle knobs stalling a protocol is not
    news). With ``confirm`` (mirrored spaces only), each finding's
    trajectory is immediately replayed on the C++ oracle and the
    decided-log digests byte-compared — ``finding["oracle"]`` records
    ``{"confirmed": true, "digest": ...}``; unmirrored spaces record
    ``{"confirmed": null, "reason": "tpu-only"}``.
    """
    import dataclasses as _dc

    from consensus_tpu.network import simulator

    log = log or (lambda *_: None)
    params = {"budget_weight": budget_weight, "max_budget": max_budget,
              "max_availability": max_availability,
              "max_lib_ratio": max_lib_ratio, "confirm": bool(confirm)}
    st = None
    if state_dir is not None and resume:
        st = load_state(state_dir, space, search_seed, population,
                        params=params)
        if st is not None:
            log(f"resuming at generation {st.generations_done} "
                f"({len(st.findings)} findings so far)")
    if st is None:
        st = SearchState(space=space.name, search_seed=search_seed,
                         population=population, params=params)
    import time as _time
    wall0 = (load_budget_wall(state_dir, st)
             if state_dir is not None else 0.0)
    t0 = _time.perf_counter()

    base = _dc.replace(space.base, n_sweeps=population)
    eng = simulator.engine_def(base)
    for gen in range(st.generations_done, generations):
        pop = next_population(space, search_seed, gen, population,
                              st.last_eval or None)
        seeds = np.array([eval_seed(search_seed, gen, c)
                          for c in range(population)], np.uint32)
        kmat = np.array([knob_row(space, kn) for kn in pop], np.uint32)
        out, flight = _dispatch(base, eng, seeds, kmat, generation=gen)
        mets = _lane_metrics(space, out, flight)

        evals, new_cells = [], 0
        for c, (kn, m) in enumerate(zip(pop, mets)):
            bud = budget_of(space, kn)
            sev = severity_of(m)
            fit = round(sev - budget_weight * bud, 6)
            key = coverage_key(m)
            novel = key not in st.coverage
            if novel:
                new_cells += 1
                st.coverage[key] = {"generation": gen, "candidate": c,
                                    "knobs": kn, "severity": sev}
            rec = {"candidate": c, "knobs": kn, "budget": bud,
                   "severity": sev, "fitness": fit, "novel": novel,
                   "metrics": m}
            evals.append(rec)
            hurt = (m["availability"] <= max_availability
                    or m["never_recovered"]
                    or (m.get("lib_ratio") is not None
                        and m["lib_ratio"] <= max_lib_ratio)
                    # A safety break is ALWAYS a finding, whatever the
                    # liveness numbers look like (SPEC §7c).
                    or bool(m.get("safety_violations")))
            # One finding per coverage cell: `novel` bounds the archive
            # by the behavior map (and with it the oracle-replay cost),
            # and keeps the findings DIVERSE — thousands of near-copies
            # of one stall are one discovery, not thousands.
            if hurt and bud <= max_budget and novel:
                finding = {
                    "schema": 1, "space": space.name,
                    "protocol": space.base.protocol, "generation": gen,
                    "candidate": c, "eval_seed": int(seeds[c]),
                    "knobs": kn, "budget": bud, "severity": sev,
                    "fitness": fit, "metrics": m, "coverage_key": key,
                    "oracle": _confirm(space, kn, int(seeds[c]))
                    if confirm else {"confirmed": None,
                                     "reason": "skipped"},
                }
                st.findings.append(finding)
        st.last_eval = evals
        st.generations_done = gen + 1
        best = max(evals, key=lambda e: e["fitness"])
        st.history.append({"generation": gen,
                           "best_fitness": best["fitness"],
                           "best_severity": best["severity"],
                           "new_cells": new_cells,
                           "findings_total": len(st.findings)})
        log(f"gen {gen}: best fitness {best['fitness']:.3f} "
            f"(severity {best['severity']:.3f} at budget "
            f"{best['budget']:.2f}), {new_cells} new coverage cells, "
            f"{len(st.findings)} findings total")
        if state_dir is not None:
            save_state(state_dir, st)
            save_budget(state_dir, st, wall0 + _time.perf_counter() - t0)
    return st


def replay_config(space: Space, knobs: dict[str, float],
                  seed: int) -> Config:
    """The exact single-trajectory Config a finding's lane simulated —
    what the oracle replay and a distilled scenario re-run execute."""
    return dataclasses.replace(space.base, n_sweeps=1, seed=seed,
                               **knobs)


def _confirm(space: Space, knobs: dict[str, float], seed: int) -> dict:
    """Oracle replay of one finding at its own (small) shape: run the
    trajectory on both engines and byte-compare decided-log digests.
    The flight recorder is digest-neutral, so it is dropped for both
    sides (Config rejects it on engine='cpu')."""
    import dataclasses as _dc

    from consensus_tpu.network import simulator
    if not space.mirrored:
        return {"confirmed": None, "reason": "tpu-only"}
    if space.base.n_nodes > 2048:
        return {"confirmed": None, "reason": "n_nodes > 2048"}
    cfg = _dc.replace(replay_config(space, knobs, seed),
                      telemetry_window=0)
    tpu = simulator.run(cfg, warmup=False)
    cpu = simulator.run(_dc.replace(cfg, engine="cpu"), warmup=False)
    ok = tpu.payload == cpu.payload
    return {"confirmed": bool(ok), "digest": tpu.digest,
            **({} if ok else {"oracle_digest": cpu.digest})}


# --- cross-protocol degradation ladder --------------------------------------
#
# The "which protocol degrades first" satellite (docs/RESILIENCE.md
# §8): the SAME shared-fault ladder — drop_rate rungs, everything else
# at a light common baseline — run over ALL six engines at a common
# small shape (7 nodes, 96 rounds), one compiled program per engine
# with the rungs as knob lanes. Not a search: a fixed, seeded probe
# whose artifact records the first rung where each protocol's
# availability falls through the floor.

_XPROTO = dict(telemetry_window=4, n_rounds=96, seed=0,
               drop_rate=0.3, churn_rate=0.02)
XPROTO_BASES: dict[str, Config] = {
    "raft": Config(protocol="raft", n_nodes=7, log_capacity=128,
                   max_entries=96, **_XPROTO),
    "pbft": Config(protocol="pbft", f=2, n_nodes=7, log_capacity=96,
                   **_XPROTO),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=2,
                         n_nodes=7, log_capacity=96, **_XPROTO),
    "paxos": Config(protocol="paxos", n_nodes=7, log_capacity=96,
                    **_XPROTO),
    "dpos": Config(protocol="dpos", n_nodes=7, n_candidates=6,
                   n_producers=3, log_capacity=96, **_XPROTO),
    "hotstuff": Config(protocol="hotstuff", f=2, n_nodes=7,
                       log_capacity=96, **_XPROTO),
}
XPROTO_LADDER = (0.05, 0.15, 0.25, 0.35, 0.45, 0.55, 0.65, 0.75)
XPROTO_FLOOR = 0.5  # availability at/below this rung = "degraded"


def cross_protocol_ladder(search_seed: int, *, ladder=XPROTO_LADDER,
                          floor: float = XPROTO_FLOOR, log=None) -> dict:
    """Run the shared drop-rate ladder across every engine; returns the
    JSON-ready comparison document. Rung r of every protocol sees the
    same drop_rate and the same per-rung trajectory seed, so the
    ordering of first-degraded rungs is a protocol property, not a
    seed artifact."""
    from consensus_tpu.network import simulator

    log = log or (lambda *_: None)
    seeds = np.array([eval_seed(search_seed, 0, r)
                      for r in range(len(ladder))], np.uint32)
    protocols: dict[str, dict] = {}
    for name, base in sorted(XPROTO_BASES.items()):
        cfg = dataclasses.replace(base, n_sweeps=len(ladder))
        kmat = np.array(
            [[int(getattr(dataclasses.replace(base, drop_rate=rate), col))
              for col in KNOB_COLUMNS] for rate in ladder], np.uint32)
        eng = simulator.engine_def(cfg)
        out, flight = _dispatch(cfg, eng, seeds, kmat, generation=0)
        from consensus_tpu.obs import timeline as obs_timeline
        mets = obs_timeline.lane_fitness(
            obs_timeline.from_flight_dict(flight))
        avail = [m["availability"] for m in mets]
        first = next((r for r, a in enumerate(avail) if a <= floor), None)
        protocols[name] = {
            "availability": avail,
            "never_recovered": [m["never_recovered"] for m in mets],
            "first_degraded_rung": first,
            "first_degraded_rate": None if first is None
            else ladder[first],
        }
        log(f"{name}: availability {avail} "
            f"(first <= {floor} at rung {first})")
    order = sorted(protocols,
                   key=lambda n: (protocols[n]["first_degraded_rung"]
                                  if protocols[n]["first_degraded_rung"]
                                  is not None else len(ladder)))
    return {"version": 1, "search_seed": search_seed,
            "ladder": list(ladder), "floor": floor,
            "shape": {"n_nodes": 7, "n_rounds": 96,
                      "churn_rate": _XPROTO["churn_rate"]},
            "protocols": protocols, "degrades_first": order}


# --- §A.3 attack-space reports ----------------------------------------------
#
# Findings from UNMIRRORED spaces (the SPEC §A.3 targeted attacks are
# TPU-engine-only — the C++ oracle deliberately does not mirror them)
# can never be oracle-confirmed, so they can never enter the distilled
# scenario catalog (scenarios/discovered.json). They are still results:
# the report path below writes them to a separate artifact OUTSIDE the
# catalog — same finding schema (FINDING_FIELDS, validate_trace
# --finding checks it), explicit unconfirmed-oracle provenance — so an
# attack-space search ends in a committed report, not a refusal.

ATTACK_REPORT_VERSION = 1


def attack_report_doc(st: SearchState) -> dict:
    """One search state's findings as a standalone §A.3 report entry.
    Works for any space; the subcommand routes unmirrored spaces here
    because distill() must refuse them."""
    sp = SPACES[st.space]
    return {"space": st.space, "protocol": sp.base.protocol,
            "mirrored": sp.mirrored, "search_seed": st.search_seed,
            "population": st.population,
            "generations": st.generations_done,
            "base_config": json.loads(sp.base.to_json()),
            "knobs": [[k.field, k.lo, k.hi] for k in sp.knobs],
            "coverage_cells": len(st.coverage),
            "findings": st.findings}


def write_attack_report(st: SearchState, path) -> dict:
    """Append (or replace, keyed by (space, search_seed)) one report
    entry in the attack-findings artifact. Atomic, sorted — the same
    write discipline as the discovered catalog. Returns the entry."""
    entry = attack_report_doc(st)
    p = pathlib.Path(path)
    doc = {"version": ATTACK_REPORT_VERSION, "reports": []}
    if p.exists():
        doc = json.loads(p.read_text())
        if doc.get("version") != ATTACK_REPORT_VERSION:
            raise ValueError(f"{p}: report version "
                             f"{doc.get('version')!r} != "
                             f"{ATTACK_REPORT_VERSION}")
    key = (entry["space"], entry["search_seed"])
    doc["reports"] = [e for e in doc["reports"]
                      if (e["space"], e["search_seed"]) != key] + [entry]
    doc["reports"].sort(key=lambda e: (e["space"], e["search_seed"]))
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(p)
    return entry


# --- distillation -----------------------------------------------------------

def _bounds_from_metrics(m: dict[str, Any]) -> dict[str, Any]:
    """TimelineBounds for a distilled scenario, with slack around the
    observed lane so the assertion is a stable liveness SHAPE, not an
    exact-replay tripwire: the dip bound sits well above the observed
    availability, the floor well below, and never-recovered findings
    assert stalls instead of bounded recovery."""
    avail = m["availability"]
    # The slack widths absorb seed-to-seed variance (the finding's lane
    # is ONE trajectory; the scenario asserts a shape across fresh
    # seeds) while keeping the dip claim far from the healthy ~1.0.
    b: dict[str, Any] = {
        "max_availability": round(min(0.99, avail + 0.4), 3),
        "min_availability": round(max(0.02, avail - 0.3), 3),
    }
    if m["never_recovered"] and avail <= 0.02:
        # A total-collapse finding: the claim IS "commits die and stay
        # dead" — a liveness floor would contradict it on any fresh
        # seed that reproduces the collapse.
        del b["min_availability"]
    if m["stall_windows"] > 0:
        b["min_stall_windows"] = max(1, m["stall_windows"] // 3)
    if not m["never_recovered"] and m["recovery_rounds"] is not None:
        b["max_recovery_rounds"] = int(m["recovery_rounds"] * 4)
    if m.get("lib_ratio") is not None:
        b["max_lib_ratio"] = round(min(0.95, m["lib_ratio"] + 0.2), 3)
    if m.get("safety_violations"):
        # A SAFETY finding asserts the invariant break itself, not just
        # its liveness shadow: the distilled scenario must reproduce at
        # least one violated window (TimelineBounds.min_counters totals
        # the flight counter across sweeps), and each specific
        # violation kind the lane showed must re-appear.
        mc: dict[str, int] = {"safety_violations": 1}
        if m.get("forked_qc"):
            mc["forked_qc"] = 1
        if m.get("conflict_commits"):
            mc["conflict_commits"] = 1
        b["min_counters"] = mc
        if avail >= 0.99:
            # A SILENT safety finding: the lane never dipped, so the
            # scenario's claim is "liveness looks healthy while the
            # invariant breaks" — asserting an availability dip would
            # contradict the finding itself.
            del b["max_availability"]
    return b


# Shape fields a scenario's `tuned` reference records, per protocol —
# the same fields the hand-built library pins.
_TUNED_FIELDS = {
    "raft": ("n_nodes", "n_rounds", "log_capacity", "max_entries"),
    "pbft": ("n_nodes", "f", "n_rounds", "log_capacity"),
    "paxos": ("n_nodes", "n_rounds", "log_capacity"),
    "dpos": ("n_nodes", "n_rounds", "log_capacity", "n_candidates",
             "n_producers"),
    "hotstuff": ("n_nodes", "f", "n_rounds", "log_capacity",
                 "view_timeout"),
}


def distill(st: SearchState, finding_index: int, name: str,
            description: str = "") -> dict:
    """One finding -> a catalog entry: scenario overrides (the knob
    floats plus the space's static adversary axes), TimelineBounds with
    slack, the tuned shape, and the embedded finding record. The entry
    is only returned after (1) the scenario PASSES its own bounds in a
    fresh end-to-end run and (2) the oracle replay is confirmed —
    nothing unverified enters the catalog.
    """
    import dataclasses as _dc

    from consensus_tpu import scenarios as scen
    from consensus_tpu.network import simulator

    space = SPACES[st.space]
    try:
        f = st.findings[finding_index]
    except IndexError:
        raise ValueError(f"finding index {finding_index} out of range "
                         f"(state holds {len(st.findings)})") from None
    if not space.mirrored:
        raise ValueError(
            f"space {space.name!r} searches TPU-only knobs (SPEC §A.3 "
            "targeted attacks) — its findings cannot be oracle-"
            "confirmed, so they cannot enter the distilled catalog; "
            "report them instead: `python -m tools.advsearch report "
            "--state-dir ...` writes them to the attack-findings "
            "artifact outside scenarios/discovered.json")
    oracle = f["oracle"]
    if oracle.get("confirmed") is None:
        oracle = _confirm(space, f["knobs"], f["eval_seed"])
    if not oracle.get("confirmed"):
        raise ValueError(f"finding {finding_index}: oracle replay did "
                         f"not confirm ({oracle}) — refusing to distill")

    overrides = dict(sorted(f["knobs"].items()))
    # Static adversary axes of the space that shaped the lane (a
    # scenario override list must reproduce the attack, not just the
    # searched knobs).
    base = space.base
    if base.max_delay_rounds:
        overrides["max_delay_rounds"] = base.max_delay_rounds
    if base.max_crashed and "crash_prob" in overrides:
        overrides["max_crashed"] = base.max_crashed
    # SPEC §9/§9b/§6 statics: the switch topology, the byzantine census
    # and the fault granularity shape the attack but are not searchable
    # rates — a distilled scenario must carry them or its replay runs a
    # different fabric than the finding's lane.
    if base.net_model == "switch":
        overrides["net_model"] = "switch"
        overrides["n_aggregators"] = base.n_aggregators
        if base.agg_byz:
            overrides["agg_byz"] = base.agg_byz
        for k in ("agg_fail_rate", "agg_stale_rate"):
            if getattr(base, k) > 0:
                overrides[k] = getattr(base, k)
        if base.agg_stale_rate > 0:
            overrides["agg_max_stale"] = base.agg_max_stale
    if base.n_byzantine:
        overrides["n_byzantine"] = base.n_byzantine
        overrides["byz_mode"] = base.byz_mode
    if base.fault_model != "edge":
        overrides["fault_model"] = base.fault_model
    for k in RATE_CUTOFFS:
        if k == "attack_rate" and base.attack == "none":
            continue  # a bare attack_rate is rejected by Config
        if k == "recover_prob":
            if "crash_prob" in overrides and k not in overrides:
                overrides[k] = getattr(base, k)
        elif k not in overrides and getattr(base, k) > 0:
            overrides[k] = getattr(base, k)

    if not description:
        m = f["metrics"]
        bits = [f"{k}={v}" for k, v in sorted(f["knobs"].items())]
        description = (
            f"advsearch-discovered ({space.name}, seed "
            f"{st.search_seed}, gen {f['generation']}): "
            f"{', '.join(bits)} -> availability "
            f"{m['availability']:.3f}, {m['stall_windows']} stall "
            f"windows" + (f", LIB ratio {m['lib_ratio']:.3f}"
                          if m.get("lib_ratio") is not None else "")
            + ". Confirmed by a C++ oracle replay.")

    scenario = {
        "name": name, "description": description,
        "protocol": base.protocol, "overrides": overrides,
        "bounds": _bounds_from_metrics(f["metrics"]),
        "window": base.telemetry_window, "min_rounds": 64,
        "tuned": {k: getattr(base, k)
                  for k in _TUNED_FIELDS[base.protocol]},
    }
    entry = {"scenario": scenario,
             "finding": {**{k: f[k] for k in FINDING_FIELDS
                            if k != "oracle"}, "oracle": oracle}}

    # Verify end-to-end before it can enter the catalog: build the
    # Scenario object, apply it to the tuned shape, run, judge.
    s = scen.Scenario(
        name=name, description=description, protocol=base.protocol,
        overrides=overrides,
        bounds=scen.TimelineBounds(**scenario["bounds"]),
        window=scenario["window"], min_rounds=scenario["min_rounds"],
        tuned=scenario["tuned"])
    shape = _dc.replace(
        Config(protocol=base.protocol, engine="tpu",
               **{k: v for k, v in scenario["tuned"].items()}),
        n_sweeps=2, seed=base.seed)
    res = simulator.run(scen.apply(shape, s), warmup=False,
                        telemetry=True, stats={})
    verdict = scen.evaluate(s, res)
    if not verdict["passed"]:
        raise ValueError(
            f"distilled scenario {name!r} FAILED its own bounds on a "
            f"fresh run at the tuned shape: {verdict['checks']} — not "
            "entering the catalog")
    entry["scenario"]["verified_availability"] = verdict["availability"]
    return entry


def promote(name: str, catalog_path, *, seeds: tuple[int, ...],
            n_sweeps: int = 2, log=None) -> dict:
    """The auto-promotion gate between 'distilled' and 'CI tripwire':
    re-run catalog entry ``name`` at its tuned shape across K FRESH
    seeds and admit it to the ``make check`` scenario smokes (the
    entry gains a ``promoted`` record tools/check.py reads) only when
    the TimelineBounds hold on EVERY seed. Distillation verifies one
    fresh run; promotion is the stability bar — a scenario that gates
    CI must not be a single-seed fluke. Any failing seed raises (with
    the failed checks) and leaves the catalog untouched."""
    import dataclasses as _dc

    from consensus_tpu import scenarios as scen
    from consensus_tpu.network import simulator

    log = log or (lambda *_: None)
    if not seeds:
        raise ValueError("promote needs at least one fresh seed")
    p = pathlib.Path(catalog_path)
    doc = json.loads(p.read_text())
    by_name = {e["scenario"]["name"]: e for e in doc.get("scenarios", [])}
    if name not in by_name:
        raise ValueError(f"no catalog entry {name!r} in {p} "
                         f"(known: {sorted(by_name)})")
    entry = by_name[name]
    sd = entry["scenario"]
    s = scen.Scenario(
        name=sd["name"], description=sd["description"],
        protocol=sd["protocol"], overrides=dict(sd["overrides"]),
        bounds=scen.TimelineBounds(**sd["bounds"]),
        window=int(sd["window"]), min_rounds=int(sd["min_rounds"]),
        tuned=dict(sd["tuned"]))
    runs = []
    for seed in seeds:
        shape = _dc.replace(
            Config(protocol=s.protocol, engine="tpu", **dict(s.tuned)),
            n_sweeps=n_sweeps, seed=int(seed))
        res = simulator.run(scen.apply(shape, s), warmup=False,
                            telemetry=True, stats={})
        verdict = scen.evaluate(s, res)
        runs.append({"seed": int(seed), "passed": verdict["passed"],
                     "availability": verdict["availability"]})
        log(f"seed {seed}: {'PASS' if verdict['passed'] else 'FAIL'} "
            f"(availability {verdict['availability']:.3f})")
        if not verdict["passed"]:
            bad = {k: c for k, c in verdict["checks"].items()
                   if not c["ok"]}
            raise ValueError(
                f"scenario {name!r} FAILED its bounds at fresh seed "
                f"{seed}: {bad} — not promoting (the catalog entry is "
                "unchanged; it stays distilled-but-not-CI-gating)")
    sd["promoted"] = {"seeds": [int(x) for x in seeds],
                      "n_sweeps": n_sweeps, "runs": runs}
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(p)
    return sd["promoted"]


def write_catalog(entry: dict, catalog_path) -> None:
    """Append (or replace by name) one distilled entry in the catalog
    JSON the scenario library loads (consensus_tpu/scenarios/
    discovered.json). Atomic, sorted by name."""
    from consensus_tpu import scenarios as scen
    p = pathlib.Path(catalog_path)
    doc = {"version": 1, "scenarios": []}
    if p.exists():
        doc = json.loads(p.read_text())
    name = entry["scenario"]["name"]
    if name in scen.SCENARIOS and name not in {
            e["scenario"]["name"] for e in doc["scenarios"]}:
        raise ValueError(f"scenario name {name!r} collides with the "
                         "hand-built library — pick another --name")
    doc["scenarios"] = [e for e in doc["scenarios"]
                        if e["scenario"]["name"] != name] + [entry]
    doc["scenarios"].sort(key=lambda e: e["scenario"]["name"])
    tmp = p.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(p)
