#!/usr/bin/env python3
"""Cross-run perf ledger: one time series over every committed measurement.

    python tools/ledger.py [--repo ROOT] [--out benchmarks/LEDGER.json]
                           [--check] [--quiet]
    make ledger                 # the same thing, with --check

The repo's perf history is scattered: driver captures (``BENCH_r*.json``,
one per growth round, stdout-scraped), multi-chip dry runs
(``MULTICHIP_r*.json``), the merged on-chip benchmark artifact
(``benchmarks/RESULTS.json`` with embedded bandwidth floors + metrics
snapshots), and sweep-service completed-job reports
(``benchmarks/parts/service_jobs.json``, published by a sweepd daemon —
docs/SERVICE.md). This tool folds them — plus the compiled cost model's
roofline predictions (``benchmarks/parts/costcards/``) — into ONE
``benchmarks/LEDGER.json``:

  * a normalized row per measurement (``ROW_FIELDS``, exactly those
    keys — schema-checked by ``tools/validate_trace.py --ledger`` and
    lint-synced against its ``LEDGER_ROW_FIELDS`` registry);
  * per-config ``measured_vs_predicted`` ratios (measured steps/s over
    the cost card's roofline prediction — an efficiency figure, NOT
    bounded by 1: predictions come from the CPU-backend lowering of the
    TPU program, see tools/costmodel);
  * ``stale_timing`` markers propagated from RESULTS rows into ledger
    rows (``run_benchmarks.warn_stale``'s data, no longer only a
    startup stderr line);
  * a noise-banded regression verdict per (config, platform-class)
    series — ``--check`` exits nonzero when any series' latest
    measurement falls more than ``NOISE_BAND`` below its prior best.

Deliberately stdlib-only and import-free of the framework, like
``tools/validate_trace.py``: CI can run it without jax.
"""
from __future__ import annotations

import argparse
import glob
import json
import pathlib
import re
import sys
from typing import Any

LEDGER_VERSION = 1

# Relative drop below a series' prior best that counts as a regression.
# Sized above the measured run-to-run jitter of the committed rows
# (repeat-scan timing brought raft-5node under ±5%; the flagship rows
# repeat within a few percent) but below any real regression worth a
# red build (the PR 8 sort-diet classes move 2-3x).
NOISE_BAND = 0.15

# One ledger row = exactly these keys (nulls where a source has no
# value). Mirrored import-free in tools/validate_trace.py
# (LEDGER_ROW_FIELDS) and lint-synced both ways like the telemetry
# counter registry.
ROW_FIELDS = ("source", "kind", "name", "seq", "timestamp", "platform",
              "engine", "steps_per_sec", "wall_s", "steps", "digest",
              "stale", "predicted_steps_per_sec", "measured_vs_predicted",
              "hbm_peak_frac_floor", "ok", "notes",
              # adv-search budget rows only (null on every other kind):
              # generation-loop and candidate-evaluation totals for one
              # search (tools/advsearch `budget` artifact).
              "generations", "evals")

# RESULTS row name -> cost-card name where they differ (the padded
# one-program f-ladder row is costed by the fsweep card).
CARD_FOR = {"pbft-fsweep-one-program": "pbft-100k-bcast-fsweep"}

# bench.py's metric string: "raft-{N}node-{R}round[-cap{A}] ..." —
# shapes matching a benchmark-suite config normalize onto its
# RESULTS/cost-card name so driver captures and benchmark-suite
# captures form ONE series (and the driver row inherits the config's
# roofline prediction). The shapes mirror run_benchmarks.CONFIGS —
# duplicated here because this tool stays import-free of the framework
# (importing CONFIGS pulls jax).
_BENCH_METRIC_RE = re.compile(
    r"^(?P<proto>[a-z]+)-(?P<nodes>\d+)node-(?P<rounds>\d+)round"
    r"(?:-cap(?P<cap>\d+))?.*\[(?P<plat>[^\]]+)\]")
_BENCH_SHAPE_NAMES = {
    ("raft", 100_000, 64, 8): "raft-100k",
    ("raft", 1024, 1024, 0): "raft-1kx1k",
}


def _row(**kw: Any) -> dict[str, Any]:
    row = {k: None for k in ROW_FIELDS}
    row.update(kw)
    assert set(row) == set(ROW_FIELDS), f"row keys drifted: {sorted(row)}"
    return row


def _load_cards(repo: pathlib.Path) -> dict[str, dict]:
    cards = {}
    for path in sorted((repo / "benchmarks" / "parts"
                        / "costcards").glob("*.json")):
        try:
            doc = json.loads(path.read_text())
        except ValueError:
            continue
        cards[doc.get("name", path.stem)] = doc
    return cards


def _predicted(cards: dict[str, dict], name: str) -> float | None:
    card = cards.get(CARD_FOR.get(name, name))
    if card is None:
        return None
    try:
        return float(card["roofline"]["predicted_steps_per_sec"])
    except (KeyError, TypeError, ValueError):
        return None


def _ratio(measured, predicted) -> float | None:
    if measured and predicted:
        return round(measured / predicted, 4)
    return None


def results_rows(repo: pathlib.Path, cards: dict[str, dict]) -> list[dict]:
    """Rows from benchmarks/RESULTS.json: one per engine entry. TPU rows
    get a roofline prediction + ratio; oracle rows are their own series
    (a single-core C++ baseline has no device roofline). ``stale`` is
    the row's ``stale_timing`` marker — the same datum
    ``run_benchmarks.warn_stale`` prints at startup, now a queryable
    column."""
    path = repo / "benchmarks" / "RESULTS.json"
    if not path.exists():
        return []
    try:
        doc = json.loads(path.read_text())
    except ValueError:
        return []
    ts = doc.get("timestamp")
    out = []
    for r in doc.get("rows", []):
        name, stale = r.get("name", "?"), r.get("stale_timing")
        for key, kind in (("tpu", "results-tpu"), ("oracle",
                                                   "results-oracle")):
            e = r.get(key)
            if not isinstance(e, dict):
                continue
            sps = e.get("steps_per_sec")
            pred = _predicted(cards, name) if key == "tpu" else None
            bw = e.get("bandwidth") or {}
            notes = []
            if e.get("metrics"):
                notes.append("embedded-metrics-snapshot")
            if e.get("timing"):
                notes.append(e["timing"])
            out.append(_row(
                source="benchmarks/RESULTS.json", kind=kind, name=name,
                timestamp=ts,
                platform=("cpu-oracle" if key == "oracle"
                          else doc.get("platform")),
                engine=e.get("engine"), steps_per_sec=sps,
                wall_s=e.get("wall_s"), steps=e.get("steps"),
                digest=e.get("digest"),
                stale=stale if key == "tpu" else None,
                predicted_steps_per_sec=pred,
                measured_vs_predicted=_ratio(sps, pred),
                hbm_peak_frac_floor=bw.get("hbm_peak_frac_floor"),
                ok=bool(sps), notes=", ".join(notes) or None))
    return out


def _bench_name(metric: str) -> tuple[str, str]:
    """(series name, platform) from a bench.py metric string; the
    flagship shape maps onto the RESULTS/cost-card name."""
    m = _BENCH_METRIC_RE.match(metric or "")
    if not m:
        return (metric or "?", "?")
    shape = (m.group("proto"), int(m.group("nodes")), int(m.group("rounds")),
             int(m.group("cap") or 0))
    name = _BENCH_SHAPE_NAMES.get(shape, metric.split(" ")[0])
    return name, m.group("plat")


def bench_rows(repo: pathlib.Path, cards: dict[str, dict]) -> list[dict]:
    """Rows from the driver's per-round BENCH_r*.json captures. New
    captures carry bench.py's machine-parseable ``trajectory`` block
    (config echo, wall, steps, timestamp); older ones only the one-line
    metric/value pair; failed rounds (rc != 0 or an ``error`` field)
    become ok=false rows so the history keeps its holes visible."""
    out = []
    for fname in sorted(glob.glob(str(repo / "BENCH_r*.json"))):
        path = pathlib.Path(fname)
        try:
            doc = json.loads(path.read_text())
        except ValueError:
            continue
        parsed = doc.get("parsed") or {}
        traj = parsed.get("trajectory") or {}
        if traj:
            # Structured rows carry the shape directly — no scraping.
            shape = (traj.get("protocol"), traj.get("nodes"),
                     traj.get("rounds"), traj.get("max_active"))
            name = _BENCH_SHAPE_NAMES.get(shape, (
                f"{shape[0]}-{shape[1]}node-{shape[2]}round"
                + (f"-cap{shape[3]}" if shape[3] else "")))
            plat = _bench_name(parsed.get("metric", ""))[1]
            if plat == "?":
                plat = traj.get("platform", "?")
        else:
            name, plat = _bench_name(parsed.get("metric", ""))
        sps = parsed.get("value") or None
        ok = doc.get("rc") == 0 and bool(sps) and "error" not in parsed
        pred = _predicted(cards, name) if _plat_class(plat) == "tpu" \
            else None
        notes = []
        if parsed.get("error"):
            notes.append(str(parsed["error"])[:120])
        elif not parsed:
            notes.append("no parseable benchmark line (rc="
                         f"{doc.get('rc')})")
        if not traj:
            # Pre-trajectory captures (and rounds whose bench.py died
            # before emitting the block) have no config echo / wall /
            # steps — mark the hole explicitly instead of leaving the
            # row indistinguishable from a thin-but-healthy one.
            notes.append("no-trajectory")
        out.append(_row(
            source=path.name, kind="driver-bench", name=name,
            seq=doc.get("n"), timestamp=traj.get("timestamp"),
            platform=plat if plat != "?" else None,
            engine="tpu", steps_per_sec=sps, wall_s=traj.get("wall_s"),
            steps=traj.get("steps"), digest=None, stale=None,
            predicted_steps_per_sec=pred,
            measured_vs_predicted=_ratio(sps, pred),
            hbm_peak_frac_floor=None, ok=ok,
            notes=", ".join(notes) or None))
    return out


def service_rows(repo: pathlib.Path, cards: dict[str, dict]) -> list[dict]:
    """Rows from a published sweepd completed-job report
    (``benchmarks/parts/service_jobs.json``, written by
    ``python -m consensus_tpu.service --publish``; row schema =
    consensus_tpu/service/jobs.py JOB_REPORT_FIELDS, checked by
    ``tools/validate_trace.py --service-jobs``). Each finished job is
    one measurement: done jobs carry their decided-log digest and
    throughput; failed jobs stay visible as ok=false rows like failed
    driver rounds. Batched jobs note their shared-program batch so a
    throughput reader knows the wall clock covered the whole batch."""
    path = repo / "benchmarks" / "parts" / "service_jobs.json"
    if not path.exists():
        return []
    try:
        doc = json.loads(path.read_text())
    except ValueError:
        return []
    out = []
    for r in doc.get("rows", []):
        name = r.get("name") or "?"
        plat = r.get("platform")
        sps = r.get("steps_per_sec") or None
        pred = _predicted(cards, name) if _plat_class(plat) == "tpu" \
            else None
        notes = []
        if r.get("batch"):
            notes.append(f"batched:{'+'.join(r['batch'])}")
        if r.get("cache_hit"):
            notes.append("exec-cache-hit")
        if r.get("scenario_passed") is not None:
            notes.append(f"scenario_passed={r['scenario_passed']}")
        if r.get("error"):
            notes.append(str(r["error"])[:120])
        out.append(_row(
            source="benchmarks/parts/service_jobs.json",
            kind="service-job", name=name, seq=None,
            timestamp=r.get("finished_unix"), platform=plat,
            engine=r.get("engine"), steps_per_sec=sps,
            wall_s=r.get("wall_s"), steps=r.get("steps"),
            digest=r.get("digest"), stale=None,
            predicted_steps_per_sec=pred,
            measured_vs_predicted=_ratio(sps, pred),
            hbm_peak_frac_floor=None,
            ok=r.get("status") == "done" and bool(sps),
            notes=", ".join(notes) or None))
    return out


def search_rows(repo: pathlib.Path) -> list[dict]:
    """Rows from the committed adversary-search budget artifact
    (``benchmarks/parts/search_budgets.json``, folded from per-search
    ``search_budget.json`` sidecars by ``python -m tools.advsearch
    budget``). One row per (space, search seed): how many generations
    and candidate evaluations the search spent, for how much wall, and
    what it bought (findings / coverage cells, in ``notes``). Search
    cost has no steps/s series — the rows are a spend ledger, not a
    throughput series, so they never drive a regression verdict."""
    path = repo / "benchmarks" / "parts" / "search_budgets.json"
    if not path.exists():
        return []
    try:
        doc = json.loads(path.read_text())
    except ValueError:
        return []
    out = []
    for r in doc.get("rows", []):
        out.append(_row(
            source="benchmarks/parts/search_budgets.json",
            kind="adv-search",
            name=f"advsearch-{r.get('space', '?')}",
            seq=r.get("search_seed"), engine="tpu",
            wall_s=r.get("wall_s"),
            generations=r.get("generations"), evals=r.get("evals"),
            ok=bool(r.get("generations")),
            notes=(f"population={r.get('population')}, "
                   f"findings={r.get('findings')}, "
                   f"coverage_cells={r.get('coverage_cells')}")))
    return out


def multichip_rows(repo: pathlib.Path) -> list[dict]:
    out = []
    for fname in sorted(glob.glob(str(repo / "MULTICHIP_r*.json"))):
        path = pathlib.Path(fname)
        try:
            doc = json.loads(path.read_text())
        except ValueError:
            continue
        m = re.search(r"r(\d+)", path.stem)
        seq = int(m.group(1)) if m else None
        out.append(_row(
            source=path.name, kind="multichip-dryrun",
            name=f"dryrun-multichip-{doc.get('n_devices', '?')}dev",
            seq=seq, engine="tpu",
            ok=bool(doc.get("ok")) and not doc.get("skipped"),
            notes="skipped" if doc.get("skipped") else None))
    return out


def _plat_class(platform: str | None) -> str:
    """Series bucket: a single-core oracle baseline, a real-accelerator
    capture, and a CPU-backend fallback are three different instruments
    — comparing across them manufactures fake regressions."""
    p = platform or ""
    if p == "cpu-oracle":
        return "oracle"
    return "tpu" if p.startswith(("tpu", "axon")) else "cpu"


def _point_order(row: dict) -> tuple:
    """Chronological sort key for one series' points: timestamp when a
    row carries one (RESULTS, trajectory-era BENCH rows), else the
    driver round number. Rows without either sort first — concatenation
    order is NOT chronology (bench_rows precede the RESULTS artifact in
    the row list, so a fresh driver capture would otherwise never be
    the 'latest' point and a regression in it could never fire)."""
    return (row["timestamp"] or 0.0, row["seq"] or 0)


def build_series(rows: list[dict]) -> dict[str, dict]:
    """Per-(name, platform-class) measurement series + noise-banded
    verdict: points ordered chronologically (:func:`_point_order`), the
    LATEST compared against the best EARLIER one. A series with a
    single (non-stale) point verdicts ``new`` — shielded from both
    regression directions until a second measurement exists."""
    groups: dict[str, list[dict]] = {}
    for row in rows:
        # ok=false rows (failed rounds, degenerate nothing-committed
        # runs) stay visible in the row list but must not drive a
        # verdict: a meaningless value as 'latest' reds a healthy tree,
        # as 'best prior' flags every later healthy run.
        if row["kind"] == "multichip-dryrun" or not row["steps_per_sec"] \
                or not row["ok"]:
            continue
        key = f"{row['name']}@{_plat_class(row['platform'])}"
        groups.setdefault(key, []).append(row)
    out = {}
    for key, grp in sorted(groups.items()):
        grp = sorted(grp, key=_point_order)
        pts = [{"source": r["source"], "seq": r["seq"],
                "steps_per_sec": r["steps_per_sec"],
                "stale": r["stale"]} for r in grp]
        latest = grp[-1]
        # Stale-marked points are known-bad timings in BOTH directions:
        # not a red 'latest' (below) and not the baseline either — a
        # pre-fix measurement that overstated steps/s must not verdict
        # the first fresh correct measurement a regression.
        prior = [r for r in grp[:-1] if not r["stale"]]
        entry: dict[str, Any] = {"n_points": len(grp), "points": pts,
                                 "latest": latest["steps_per_sec"]}
        if not prior:
            # A series whose only (non-stale) point is the latest one is
            # NEW: it can neither regress nor serve as evidence that
            # anything else did — the first RESULTS/cost-card rows of a
            # freshly landed config (e.g. hotstuff-100k) get a neutral
            # verdict instead of faking either direction.
            entry.update(verdict="new", best_prior=None, ratio=None)
        else:
            best = max(r["steps_per_sec"] for r in prior)
            ratio = latest["steps_per_sec"] / best
            entry.update(
                best_prior=best, ratio=round(ratio, 4),
                verdict=("regression" if ratio < 1.0 - NOISE_BAND
                         else "ok"))
            if entry["verdict"] == "regression" and latest["stale"]:
                # A stale-marked latest point is a known-bad timing, not
                # fresh evidence — surfaced, never a red build.
                entry["verdict"] = "stale-latest"
        out[key] = entry
    return out


def build(repo: pathlib.Path) -> dict[str, Any]:
    cards = _load_cards(repo)
    rows = (bench_rows(repo, cards) + multichip_rows(repo)
            + results_rows(repo, cards) + service_rows(repo, cards)
            + search_rows(repo))
    series = build_series(rows)
    regressions = sorted(k for k, s in series.items()
                         if s["verdict"] == "regression")
    stale = [{"name": r["name"], "source": r["source"], "note": r["stale"]}
             for r in rows if r["stale"]]
    return {
        "version": LEDGER_VERSION,
        # Deterministic provenance (NOT a wall clock: the ledger is a
        # committed artifact and identical inputs must regenerate the
        # identical bytes, like the fingerprints and cost cards).
        "newest_input_unix": max((r["timestamp"] for r in rows
                                  if r["timestamp"]), default=None),
        "noise_band": NOISE_BAND,
        "n_cost_cards": len(cards),
        "rows": rows,
        "series": series,
        "regressions": regressions,
        "stale_rows": stale,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fold BENCH/MULTICHIP/RESULTS captures + cost-card "
                    "predictions into benchmarks/LEDGER.json.")
    ap.add_argument("--repo", default=str(pathlib.Path(__file__).
                                          resolve().parents[1]))
    ap.add_argument("--out", default="",
                    help="output path (default <repo>/benchmarks/"
                         "LEDGER.json)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any series regressed past "
                         "the noise band")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    repo = pathlib.Path(args.repo)
    doc = build(repo)
    out = pathlib.Path(args.out) if args.out else \
        repo / "benchmarks" / "LEDGER.json"
    out.write_text(json.dumps(doc, indent=2) + "\n")

    def log(msg: str) -> None:
        if not args.quiet:
            print(f"ledger: {msg}", file=sys.stderr, flush=True)

    log(f"{len(doc['rows'])} rows, {len(doc['series'])} series, "
        f"{doc['n_cost_cards']} cost cards -> {out}")
    # A BENCH_r*.json capture that contributes no MEASURED row (failed
    # round, unparseable JSON, or no benchmark line) is invisible to
    # every series verdict — the bench trajectory silently ends there
    # unless someone hand-cross-references the raw capture. Say so.
    measured = {r["source"] for r in doc["rows"]
                if r["kind"] == "driver-bench" and r["ok"]
                and r["steps_per_sec"]}
    for fname in sorted(glob.glob(str(repo / "BENCH_r*.json"))):
        name = pathlib.Path(fname).name
        if name not in measured:
            log(f"WARN {name}: capture present but no measured row "
                "references it — this round is invisible to the "
                "series verdicts (failed round or unparseable "
                "benchmark line; inspect the raw capture)")
    for s in doc["stale_rows"]:
        log(f"STALE {s['name']} ({s['source']}): {s['note']}")
    for key, s in doc["series"].items():
        if s["verdict"] != "new":
            log(f"{key}: latest {s['latest'] / 1e6:.2f}M vs best prior "
                f"{s['best_prior'] / 1e6:.2f}M ({s['ratio']:.2f}x) "
                f"-> {s['verdict']}")
    if doc["regressions"]:
        log(f"REGRESSIONS: {', '.join(doc['regressions'])}")
        return 1 if args.check else 0
    log("no regressions past the noise band")
    return 0


if __name__ == "__main__":
    sys.exit(main())
