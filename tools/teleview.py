#!/usr/bin/env python3
"""teleview — render a flight-recorder timeline (docs/OBSERVABILITY.md
§"Flight recorder").

    python -m tools.teleview --metrics metrics.json
    python -m tools.teleview --checkpoint ck.npz --json
    python -m tools.teleview --metrics metrics.json --prom derived.prom

Loads the windowed telemetry series + protocol latency histograms a
``--telemetry-window`` run left behind (the ``"flight"`` block of a
``--metrics-out`` snapshot, or a recorder-on checkpoint's trailing
leaves), derives the liveness metrics (commit throughput per window,
stall windows, availability ratio, recovery time after fault onset,
latency percentiles — :mod:`consensus_tpu.obs.timeline`), and prints a
text summary (default) or the derived-metrics JSON (``--json``).
``--prom`` additionally writes the derived gauges in Prometheus text
format, so a scrape carries the timeline verdicts.

The metrics-JSON path imports numpy + the obs package only (no jax);
the checkpoint path resolves engine counter names and pays the jax
import.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.teleview",
        description="Timeline analysis of flight-recorder series "
                    "(windowed telemetry + latency histograms).")
    ap.add_argument("--metrics", default="",
                    help="a --metrics-out JSON snapshot with a 'flight' "
                         "block (the run must have used "
                         "--telemetry-window)")
    ap.add_argument("--checkpoint", default="",
                    help="a recorder-on checkpoint .npz (the ring rides "
                         "the snapshot; imports jax to resolve names)")
    ap.add_argument("--json", action="store_true",
                    help="print the derived-metrics JSON instead of the "
                         "text summary")
    ap.add_argument("--prom", default="",
                    help="also write the derived gauges as Prometheus "
                         "text to this path")
    args = ap.parse_args(argv)
    if bool(args.metrics) == bool(args.checkpoint):
        ap.error("pass exactly one of --metrics / --checkpoint")

    from consensus_tpu.obs import timeline
    try:
        tl = (timeline.from_metrics_json(args.metrics) if args.metrics
              else timeline.from_checkpoint(args.checkpoint))
    except (OSError, ValueError, KeyError) as exc:
        print(f"teleview: {exc}", file=sys.stderr)
        return 1
    derived = timeline.derive(tl)
    if args.prom:
        from consensus_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.Registry()
        timeline.export_metrics(derived, registry=reg)
        pathlib.Path(args.prom).write_text(reg.to_prometheus())
    if args.json:
        print(json.dumps(derived, indent=2, sort_keys=True))
    else:
        print(timeline.render_text(tl, derived))
    return 0


if __name__ == "__main__":
    sys.exit(main())
