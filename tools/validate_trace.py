#!/usr/bin/env python3
"""Schema validator for observability artifacts (docs/OBSERVABILITY.md).

    python tools/validate_trace.py --trace run.trace.jsonl \
                                   --metrics metrics.json \
                                   [--report run.run_report.json]

Exits nonzero (with one line per violation on stderr) when any file
drifts from the documented schema — the CI tripwire that keeps the
trace/metrics formats stable for downstream consumers (the benchmark
embedding, the driver's BENCH parts).

Deliberately stdlib-only and import-free of the framework: the tier-1
test runs it as a subprocess and must not pay a jax import.
"""
from __future__ import annotations

import argparse
import json
import math
import sys

TRACE_VERSION = 1
METRICS_VERSION = 1

# Every on-device protocol telemetry counter any engine may report
# (docs/OBSERVABILITY.md §"Telemetry"); a CLI report's `telemetry` keys
# must come from this set — an unknown name means the engines and this
# tripwire have drifted. Duplicated here by design: this tool must stay
# import-free of the framework (no jax at CI time).
TELEMETRY_COUNTERS = frozenset({
    # raft (dense + sparse)
    "leader_elections", "append_accepted", "append_rejected",
    "entries_committed",
    # raft targeted attacks (SPEC §A.3): attack-active rounds
    "attack_rounds",
    # pbft (edge + bcast)
    "prepare_quorums", "prepare_missed", "commit_quorums", "commit_missed",
    "commits_adopted", "view_changes",
    # paxos
    "promises", "nacks", "accepts", "proposals_decided", "values_learned",
    # dpos
    "blocks_appended", "missed_appends", "producer_rotations", "churn_slots",
    # dpos per-producer slot faults (SPEC §A.1)
    "missed_slots",
    # dpos correlated producer suppression (SPEC §A.4)
    "suppressed_slots",
    # hotstuff (SPEC §7b; view_changes is shared with pbft above)
    "qc_formed", "blocks_committed", "commits_learned",
    "proposals_delivered", "votes_counted",
    # crash-recover adversary (SPEC §6c, every engine)
    "crashes", "recoveries", "nodes_down",
    # in-network vote aggregation (SPEC §9, every switch-capable engine)
    "agg_down_rounds", "stale_serves",
    # poisoned aggregation (SPEC §9b, pbft/hotstuff switch models)
    "poisoned_serves",
    # vote-certificate safety invariants (SPEC §7c, BFT engines)
    "forked_qc", "conflict_commits", "safety_violations",
    # per-node view synchronizer (SPEC §B, pbft/hotstuff)
    "view_spread_max", "desync_rounds", "sync_msgs_delivered",
})

# Every flight-recorder protocol-latency histogram any engine may record
# (docs/OBSERVABILITY.md §"Flight recorder"; the *_LATENCY tuples
# registered as EngineDef.latency_names — lint-synced both ways like
# TELEMETRY_COUNTERS).
LATENCY_HISTOGRAMS = frozenset({
    # raft (dense + sparse)
    "election_wait_rounds", "commit_lag_rounds",
    # pbft (edge + bcast); view_change_wait_rounds shared with hotstuff
    "view_change_wait_rounds", "slot_commit_rounds",
    # paxos
    "rounds_to_learn",
    # dpos
    "chain_lag_rounds",
    # hotstuff (SPEC §7b): chained-pipeline depth head - committed
    "chain_commit_lag_rounds",
})

# Flight-recorder bucket semantics (ops/flight.py): bucket 0 holds
# observations <= 0, bucket i covers [2^(i-1), 2^i), last is overflow.
N_LATENCY_BUCKETS = 16
LATENCY_BUCKET_LO = [0] + [2 ** i for i in range(N_LATENCY_BUCKETS - 1)]

# The CLI report's `flight` summary block — exactly these keys, like
# CHECKPOINT_IO_FIELDS (the full windowed series lives in the
# --metrics-out artifact's "flight" block, not the one-line report).
FLIGHT_REPORT_FIELDS = frozenset({
    "window_rounds", "n_windows", "availability", "stall_windows",
    "latency",
})

# The CLI report's `scenario` verdict block (a --scenario run's
# timeline-assertion outcome, consensus_tpu/scenarios) — exactly these
# keys; per-check entries carry {ok, value, bound}.
SCENARIO_REPORT_FIELDS = frozenset({
    "name", "passed", "availability", "checks",
})
SCENARIO_CHECK_FIELDS = frozenset({"ok", "value", "bound"})

# Every span/event name a framework emitter may write (the
# docs/OBSERVABILITY.md span inventory). Traces may also carry
# caller-defined names (validate_trace stays name-agnostic for them);
# --expect-spans asserts specific REGISTERED spans actually appear —
# the async-checkpointing tripwire (`ckpt_snapshot`/`ckpt_write` are
# the background writer's pull/write stages).
SPAN_NAMES = frozenset({
    "dispatch", "checkpoint_save", "checkpoint_load",
    "ckpt_snapshot", "ckpt_write",
    "warmup", "supervised_attempt", "oracle_fallback", "oracle_run",
    "pbft_fsweep", "service_batch",
})
EVENT_NAMES = frozenset({
    "attempt_failed", "backoff", "checkpoint_write_failed",
})

# The CLI report's `checkpoint_io` block (async checkpoint pipeline):
# counts/bytes plus the blocking-vs-hidden wall split. Exactly these
# keys — a missing OR unknown key means the runner's accounting and
# this tripwire have drifted.
CHECKPOINT_IO_FIELDS = frozenset({
    "saves", "save_s", "save_hidden_s", "pull_s", "write_s",
    "bytes_written", "loads", "load_s", "bytes_read",
})
_CHECKPOINT_IO_INTS = frozenset({"saves", "loads", "bytes_written",
                                 "bytes_read"})

# One adversary-search finding = exactly these keys (tools/advsearch/
# search.py FINDING_FIELDS — lint-synced both ways like the telemetry
# counters): the coverage-guided search's counterexample record,
# written by `python -m tools.advsearch search --findings-out` and
# embedded per entry in the discovered-scenario catalog
# (consensus_tpu/scenarios/discovered.json).
FINDING_FIELDS = frozenset({
    "schema", "space", "protocol", "generation", "candidate",
    "eval_seed", "knobs", "budget", "severity", "fitness", "metrics",
    "coverage_key", "oracle",
})
_FINDING_METRIC_KEYS = frozenset({
    "availability", "stall_windows", "stall_ratio", "fault_onset_window",
    "recovery_rounds", "never_recovered", "commit_rate", "lib_ratio",
    # SPEC §7c safety-invariant totals (BFT vote engines only)
    "forked_qc", "conflict_commits", "safety_violations",
})

# Cost-card top-level keys (tools/costmodel/model.py CARD_FIELDS —
# lint-synced both ways like the telemetry counters): the Observatory's
# per-config compiled cost summary, committed under
# benchmarks/parts/costcards/ and drift-gated by `make check`'s
# costcheck layer (docs/OBSERVABILITY.md §"Observatory").
COST_CARD_FIELDS = frozenset({
    "schema", "name", "engine", "chunk_rounds", "toolchain", "config",
    "cost", "roofline", "collectives",
})
_COST_SUBFIELDS = frozenset({
    "flops_per_round", "bytes_per_round", "arithmetic_intensity",
    "steps_per_round", "bytes_per_step", "transcendentals_per_round",
})
_ROOFLINE_SUBFIELDS = frozenset({
    "hbm_peak_gbps", "peak_flops", "bound", "predicted_round_s",
    "predicted_steps_per_sec",
})

# One benchmarks/LEDGER.json row = exactly these keys (tools/ledger.py
# ROW_FIELDS — lint-synced both ways). Nulls are legal where a source
# has no value; the KEYS may not drift.
LEDGER_ROW_FIELDS = frozenset({
    "source", "kind", "name", "seq", "timestamp", "platform", "engine",
    "steps_per_sec", "wall_s", "steps", "digest", "stale",
    "predicted_steps_per_sec", "measured_vs_predicted",
    "hbm_peak_frac_floor", "ok", "notes",
    # adv-search budget rows only (null elsewhere): generation loop +
    # candidate-evaluation totals for one search (tools/advsearch).
    "generations", "evals",
})
_LEDGER_KINDS = frozenset({"results-tpu", "results-oracle", "driver-bench",
                           "multichip-dryrun", "service-job", "adv-search"})

# One sweep-service completed-job report row = exactly these keys
# (consensus_tpu/service/jobs.py JOB_REPORT_FIELDS — lint-synced both
# ways like the telemetry counters): the artifact a sweepd daemon
# publishes (``--publish benchmarks/parts/service_jobs.json``) and
# tools/ledger.py folds into LEDGER.json as ``service-job`` rows.
SERVICE_JOB_FIELDS = frozenset({
    "schema", "id", "name", "protocol", "engine", "platform", "n_nodes",
    "n_rounds", "n_sweeps", "submitted_unix", "finished_unix", "wall_s",
    "steps", "steps_per_sec", "digest", "status", "batch", "cache_hit",
    "scenario_passed", "error",
})
_SERVICE_JOB_STATES = frozenset({"done", "failed"})
# "new" = a single-point series (first measurement of a fresh config —
# shielded from both regression directions); "single-point" is the
# pre-rename alias, still accepted so committed LEDGER.json artifacts
# from older trees validate.
_LEDGER_VERDICTS = frozenset({"ok", "regression", "new", "single-point",
                              "stale-latest"})

_SCALAR = (bool, int, float, str, type(None))


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def validate_trace(path) -> list:
    """Return a list of violation strings (empty = valid JSONL trace)."""
    errs = []
    try:
        lines = open(path).read().splitlines()
    except OSError as exc:
        return [f"{path}: unreadable: {exc}"]
    if not lines:
        return [f"{path}: empty trace (expected at least a meta line)"]
    recs = []
    for i, line in enumerate(lines, 1):
        try:
            rec = json.loads(line)
        except ValueError as exc:
            errs.append(f"{path}:{i}: not JSON: {exc}")
            continue
        if not isinstance(rec, dict):
            errs.append(f"{path}:{i}: record is not an object")
            continue
        recs.append((i, rec))
    if not recs:
        return errs
    i0, meta = recs[0]
    if meta.get("type") != "meta":
        errs.append(f"{path}:{i0}: first record must be meta, "
                    f"got {meta.get('type')!r}")
    else:
        if meta.get("version") != TRACE_VERSION:
            errs.append(f"{path}:{i0}: meta.version {meta.get('version')!r} "
                        f"!= {TRACE_VERSION}")
        for key in ("clock", "t0_s", "unix_t0", "pid"):
            if key not in meta:
                errs.append(f"{path}:{i0}: meta missing {key!r}")
    last_seq = -1
    for i, rec in recs[1:]:
        typ = rec.get("type")
        if typ not in ("span", "event"):
            errs.append(f"{path}:{i}: unknown type {typ!r}")
            continue
        if not isinstance(rec.get("name"), str) or not rec["name"]:
            errs.append(f"{path}:{i}: missing/empty name")
        if not _num(rec.get("t_s")) or rec["t_s"] < 0:
            errs.append(f"{path}:{i}: t_s must be a finite number >= 0")
        if typ == "span" and (not _num(rec.get("dur_s"))
                              or rec["dur_s"] < 0):
            errs.append(f"{path}:{i}: span dur_s must be a finite "
                        "number >= 0")
        seq = rec.get("seq")
        if not isinstance(seq, int) or isinstance(seq, bool):
            errs.append(f"{path}:{i}: seq must be an int")
        elif seq <= last_seq:
            errs.append(f"{path}:{i}: seq {seq} not strictly increasing "
                        f"(prev {last_seq})")
        else:
            last_seq = seq
        attrs = rec.get("attrs")
        if not isinstance(attrs, dict):
            errs.append(f"{path}:{i}: attrs must be an object")
        else:
            for k, v in attrs.items():
                if not isinstance(v, _SCALAR):
                    errs.append(f"{path}:{i}: attr {k!r} is not a "
                                f"JSON scalar ({type(v).__name__})")
    return errs


def _validate_expected(path, names: list, typ: str, registry, flag) -> list:
    """Assert each name (a) belongs to ``registry`` — an unregistered
    expectation means the caller and this tripwire drifted — and (b)
    actually appears as a ``typ`` record in the trace at ``path``."""
    errs = [f"{flag}: {n!r} is not a registered {typ} name"
            for n in names if n not in registry]
    try:
        lines = open(path).read().splitlines()
    except OSError as exc:
        return errs + [f"{path}: unreadable: {exc}"]
    seen = set()
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # validate_trace reports malformed lines
        if isinstance(rec, dict) and rec.get("type") == typ:
            seen.add(rec.get("name"))
    for n in names:
        if n in registry and n not in seen:
            errs.append(f"{path}: expected {typ} {n!r} not found in trace")
    return errs


def validate_expected_spans(path, names: list) -> list:
    """Registered spans that MUST appear in the trace. Used to prove an
    async-checkpointing run really overlapped its IO: a trace lacking
    ``ckpt_snapshot``/``ckpt_write`` spans silently fell back to sync
    saves."""
    return _validate_expected(path, names, "span", SPAN_NAMES,
                              "--expect-spans")


def validate_expected_events(path, names: list) -> list:
    """Registered events that MUST appear in the trace — e.g.
    ``attempt_failed`` in a supervised-retry run's trace, or
    ``checkpoint_write_failed`` when asserting a writer error was
    mirrored and not silently dropped."""
    return _validate_expected(path, names, "event", EVENT_NAMES,
                              "--expect-events")


def _validate_histogram(name: str, d: dict) -> list:
    errs = []
    bounds, counts = d.get("bounds"), d.get("counts")
    if not isinstance(bounds, list) or not isinstance(counts, list):
        return [f"histogram {name}: bounds/counts must be lists"]
    if sorted(set(bounds)) != bounds or not all(_num(b) for b in bounds):
        errs.append(f"histogram {name}: bounds not strictly increasing "
                    "numbers")
    if len(counts) != len(bounds) + 1:
        errs.append(f"histogram {name}: len(counts) {len(counts)} != "
                    f"len(bounds)+1 {len(bounds) + 1}")
    if not all(isinstance(c, int) and c >= 0 for c in counts):
        errs.append(f"histogram {name}: counts must be ints >= 0")
    elif d.get("count") != sum(counts):
        errs.append(f"histogram {name}: count {d.get('count')} != "
                    f"sum(counts) {sum(counts)}")
    if not _num(d.get("sum")):
        errs.append(f"histogram {name}: sum must be a finite number")
    return errs


def _int_rows(name: str, v, n_cols: int, n_rows: int | None) -> list:
    """``v`` must be a list of equal-length rows of ints >= 0 —
    ``n_cols`` wide, ``n_rows`` tall when known (None = any)."""
    if not isinstance(v, list) or not v \
            or not all(isinstance(row, list) for row in v):
        return [f"{name}: must be a non-empty list of rows"]
    errs = []
    if n_rows is not None and len(v) != n_rows:
        errs.append(f"{name}: {len(v)} rows != n_sweeps {n_rows}")
    for row in v:
        if len(row) != n_cols:
            errs.append(f"{name}: row of width {len(row)} != {n_cols}")
            break
        if not all(isinstance(c, int) and not isinstance(c, bool)
                   and c >= 0 for c in row):
            errs.append(f"{name}: entries must be ints >= 0")
            break
    return errs


def validate_flight(path, fl) -> list:
    """Schema checks for the flight-recorder block of a --metrics-out
    snapshot (docs/OBSERVABILITY.md §"Flight recorder"): window/bucket
    geometry, and counter/histogram names against the known-name
    registries (drift between the engines and this tripwire fails)."""
    if not isinstance(fl, dict):
        return [f"{path}: 'flight' must be an object"]
    errs = []
    for key in ("engine", "window_rounds", "n_windows", "n_rounds",
                "bucket_lo", "windows", "latency"):
        if key not in fl:
            errs.append(f"{path}: flight missing key {key!r}")
    for key in ("window_rounds", "n_windows", "n_rounds"):
        v = fl.get(key)
        if key in fl and (not isinstance(v, int) or isinstance(v, bool)
                          or v < 1):
            errs.append(f"{path}: flight.{key} must be an int >= 1")
    W, nw, nr = (fl.get(k) for k in ("window_rounds", "n_windows",
                                     "n_rounds"))
    if all(isinstance(x, int) and x >= 1 for x in (W, nw, nr)) \
            and nw != -(-nr // W):
        errs.append(f"{path}: flight.n_windows {nw} != "
                    f"ceil(n_rounds/window_rounds) = {-(-nr // W)}")
    if "bucket_lo" in fl and fl["bucket_lo"] != LATENCY_BUCKET_LO:
        errs.append(f"{path}: flight.bucket_lo != the power-of-two edges "
                    f"{LATENCY_BUCKET_LO} (ops/flight.py semantics)")
    n_sweeps = None
    windows = fl.get("windows")
    if windows is not None and not isinstance(windows, dict):
        errs.append(f"{path}: flight.windows must be an object")
        windows = None
    if isinstance(windows, dict):
        for name, v in sorted(windows.items()):
            if name not in TELEMETRY_COUNTERS:
                errs.append(f"{path}: flight window counter {name!r} is "
                            "not in the known-name registry (engines and "
                            "validator drifted?)")
            sub = _int_rows(f"flight.windows.{name}", v,
                            nw if isinstance(nw, int) else 0, n_sweeps)
            errs += [f"{path}: {e}" for e in sub]
            if not sub and n_sweeps is None:
                n_sweeps = len(v)
    latency = fl.get("latency")
    if latency is not None and not isinstance(latency, dict):
        errs.append(f"{path}: flight.latency must be an object")
        latency = None
    if isinstance(latency, dict):
        for name, v in sorted(latency.items()):
            if name not in LATENCY_HISTOGRAMS:
                errs.append(f"{path}: flight latency histogram {name!r} "
                            "is not in the known-name registry (engines "
                            "and validator drifted?)")
            errs += [f"{path}: {e}"
                     for e in _int_rows(f"flight.latency.{name}", v,
                                        N_LATENCY_BUCKETS, n_sweeps)]
    return errs


def validate_metrics(path) -> list:
    """Return a list of violation strings (empty = valid snapshot)."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    if doc.get("version") != METRICS_VERSION:
        errs.append(f"{path}: version {doc.get('version')!r} != "
                    f"{METRICS_VERSION}")
    metrics = doc.get("metrics")
    if not isinstance(metrics, dict):
        return errs + [f"{path}: 'metrics' must be an object"]
    for name, d in metrics.items():
        if not isinstance(d, dict):
            errs.append(f"{path}: metric {name!r} must be an object")
            continue
        typ = d.get("type")
        if typ == "counter":
            if not _num(d.get("value")) or d["value"] < 0:
                errs.append(f"{path}: counter {name} value must be >= 0")
        elif typ == "gauge":
            if not _num(d.get("value")):
                errs.append(f"{path}: gauge {name} value must be a number")
        elif typ == "histogram":
            errs += [f"{path}: {e}" for e in _validate_histogram(name, d)]
        elif typ == "info":
            labels = d.get("labels")
            if not isinstance(labels, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in labels.items()):
                errs.append(f"{path}: info {name} labels must be a "
                            "str->str object")
        elif typ == "labeled_gauge":
            series = d.get("series")
            if not isinstance(series, list):
                errs.append(f"{path}: labeled_gauge {name} series must "
                            "be a list")
            else:
                for k, child in enumerate(series):
                    labels = (child.get("labels")
                              if isinstance(child, dict) else None)
                    if not isinstance(labels, dict) or not labels \
                            or not all(isinstance(a, str)
                                       and isinstance(b, str)
                                       for a, b in labels.items()) \
                            or not _num(child.get("value")):
                        errs.append(
                            f"{path}: labeled_gauge {name} series[{k}] "
                            "must carry a non-empty str->str labels "
                            "object and a numeric value")
        else:
            errs.append(f"{path}: metric {name!r} has unknown type {typ!r}")
    if "flight" in doc:
        errs += validate_flight(path, doc["flight"])
    return errs


def validate_report(path) -> list:
    """Light checks for a supervised RunReport dump."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    errs = []
    attempts = doc.get("attempts")
    if not isinstance(attempts, list):
        errs.append(f"{path}: 'attempts' must be a list")
        attempts = []
    if doc.get("n_attempts") != len(attempts):
        errs.append(f"{path}: n_attempts {doc.get('n_attempts')!r} != "
                    f"len(attempts) {len(attempts)}")
    for k, a in enumerate(attempts):
        if not _num(a.get("wall_s")) or a["wall_s"] < 0:
            errs.append(f"{path}: attempts[{k}].wall_s must be >= 0")
        if not isinstance(a.get("start_round"), int):
            errs.append(f"{path}: attempts[{k}].start_round must be an int")
    for key in ("resumed_from_round", "fallback_used", "deadline_exceeded"):
        if key not in doc:
            errs.append(f"{path}: missing key {key!r}")
    return errs


def validate_cli_report(path) -> list:
    """Checks for the CLI's one-line JSON run report (saved stdout),
    including the telemetry counter-name registry."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    for key in ("protocol", "engine", "digest", "steps", "wall_s",
                "payload_bytes"):
        if key not in doc:
            errs.append(f"{path}: missing key {key!r}")
    io = doc.get("checkpoint_io")
    if io is not None:
        if not isinstance(io, dict):
            errs.append(f"{path}: 'checkpoint_io' must be an object")
        else:
            for key in sorted(CHECKPOINT_IO_FIELDS - set(io)):
                errs.append(f"{path}: checkpoint_io missing key {key!r}")
            for key in sorted(set(io) - CHECKPOINT_IO_FIELDS):
                errs.append(f"{path}: checkpoint_io key {key!r} is not in "
                            "the known-field registry (runner accounting "
                            "and validator drifted?)")
            for key, v in io.items():
                if key in _CHECKPOINT_IO_INTS:
                    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                        errs.append(f"{path}: checkpoint_io {key} must be "
                                    "an int >= 0")
                elif key in CHECKPOINT_IO_FIELDS:
                    if not _num(v) or v < 0:
                        errs.append(f"{path}: checkpoint_io {key} must be "
                                    "a finite number >= 0")
    fl = doc.get("flight")
    if fl is not None:
        if not isinstance(fl, dict):
            errs.append(f"{path}: 'flight' must be an object")
        else:
            for key in sorted(FLIGHT_REPORT_FIELDS - set(fl)):
                errs.append(f"{path}: flight missing key {key!r}")
            for key in sorted(set(fl) - FLIGHT_REPORT_FIELDS):
                errs.append(f"{path}: flight key {key!r} is not in the "
                            "known-field registry (CLI report and "
                            "validator drifted?)")
            for key, lo in (("window_rounds", 1), ("n_windows", 1),
                            ("stall_windows", 0)):
                v = fl.get(key)
                if key in fl and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < lo):
                    errs.append(f"{path}: flight.{key} must be an "
                                f"int >= {lo}")
            av = fl.get("availability")
            if "availability" in fl and (not _num(av)
                                         or not 0.0 <= av <= 1.0):
                errs.append(f"{path}: flight.availability must be a "
                            "number in [0, 1]")
            lat = fl.get("latency")
            if isinstance(lat, dict):
                for name, v in sorted(lat.items()):
                    if name not in LATENCY_HISTOGRAMS:
                        errs.append(f"{path}: flight latency histogram "
                                    f"{name!r} is not in the known-name "
                                    "registry (engines and validator "
                                    "drifted?)")
                    if not (isinstance(v, list)
                            and len(v) == N_LATENCY_BUCKETS
                            and all(isinstance(c, int)
                                    and not isinstance(c, bool)
                                    and c >= 0 for c in v)):
                        errs.append(f"{path}: flight.latency.{name} must "
                                    f"be {N_LATENCY_BUCKETS} ints >= 0")
            elif "latency" in fl:
                errs.append(f"{path}: flight.latency must be an object")
    sc = doc.get("scenario")
    if sc is not None:
        if not isinstance(sc, dict):
            errs.append(f"{path}: 'scenario' must be an object")
        else:
            for key in sorted(SCENARIO_REPORT_FIELDS - set(sc)):
                errs.append(f"{path}: scenario missing key {key!r}")
            for key in sorted(set(sc) - SCENARIO_REPORT_FIELDS):
                errs.append(f"{path}: scenario key {key!r} is not in the "
                            "known-field registry (CLI report and "
                            "validator drifted?)")
            if "passed" in sc and not isinstance(sc["passed"], bool):
                errs.append(f"{path}: scenario.passed must be a bool")
            checks = sc.get("checks")
            if checks is not None and not isinstance(checks, dict):
                errs.append(f"{path}: scenario.checks must be an object")
            elif isinstance(checks, dict):
                for cname, c in sorted(checks.items()):
                    if not isinstance(c, dict) \
                            or set(c) != SCENARIO_CHECK_FIELDS \
                            or not isinstance(c.get("ok"), bool):
                        errs.append(
                            f"{path}: scenario check {cname!r} must be an "
                            "object with exactly {ok: bool, value, bound}")
    tel = doc.get("telemetry")
    if tel is None:
        return errs
    if not isinstance(tel, dict):
        return errs + [f"{path}: 'telemetry' must be an object"]
    for name, v in tel.items():
        if name not in TELEMETRY_COUNTERS:
            errs.append(f"{path}: telemetry counter {name!r} is not in the "
                        "known-name registry (engines and validator "
                        "drifted?)")
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{path}: telemetry {name} must be an int >= 0")
    return errs


def validate_finding_doc(path, doc) -> list:
    """Schema checks for an already-loaded findings artifact (the
    `--finding` file, or the `finding` block of a discovered-scenario
    catalog entry wraps one element of its ``findings`` list)."""
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    if doc.get("version") != 1:
        errs.append(f"{path}: version {doc.get('version')!r} != 1")
    for key in ("space", "search_seed", "generations"):
        if key not in doc:
            errs.append(f"{path}: missing key {key!r}")
    findings = doc.get("findings")
    if not isinstance(findings, list):
        return errs + [f"{path}: 'findings' must be a list"]
    for i, f in enumerate(findings):
        if not isinstance(f, dict):
            errs.append(f"{path}: findings[{i}] must be an object")
            continue
        for key in sorted(FINDING_FIELDS - set(f)):
            errs.append(f"{path}: findings[{i}] missing key {key!r}")
        for key in sorted(set(f) - FINDING_FIELDS):
            errs.append(f"{path}: findings[{i}] key {key!r} is not in "
                        "the known-field registry (advsearch and "
                        "validator drifted?)")
        knobs = f.get("knobs")
        if not isinstance(knobs, dict) or not knobs or not all(
                isinstance(k, str) and _num(v) and 0.0 <= v <= 1.0
                for k, v in knobs.items()):
            errs.append(f"{path}: findings[{i}].knobs must be a "
                        "non-empty str -> rate-in-[0,1] object")
        for key in ("budget", "severity"):
            v = f.get(key)
            if key in f and (not _num(v) or v < 0):
                errs.append(f"{path}: findings[{i}].{key} must be a "
                            "finite number >= 0")
        m = f.get("metrics")
        if not isinstance(m, dict):
            errs.append(f"{path}: findings[{i}].metrics must be an "
                        "object")
        else:
            for key in sorted(set(m) - _FINDING_METRIC_KEYS):
                errs.append(f"{path}: findings[{i}].metrics key "
                            f"{key!r} is not a known fitness signal")
            av = m.get("availability")
            if not _num(av) or not 0.0 <= av <= 1.0:
                errs.append(f"{path}: findings[{i}].metrics."
                            "availability must be in [0, 1]")
        orc = f.get("oracle")
        if not isinstance(orc, dict) or "confirmed" not in orc \
                or not isinstance(orc["confirmed"], (bool, type(None))):
            errs.append(f"{path}: findings[{i}].oracle must be an "
                        "object with confirmed: bool|null")
    return errs


def validate_finding(path) -> list:
    """Schema checks for a findings artifact file
    (`python -m tools.advsearch search --findings-out`)."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    return validate_finding_doc(path, doc)


def validate_costcard(path) -> list:
    """Schema checks for one committed cost card
    (docs/OBSERVABILITY.md §"Observatory"): exactly the registered
    top-level keys, internally consistent cost/roofline blocks."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    for key in sorted(COST_CARD_FIELDS - set(doc)):
        errs.append(f"{path}: cost card missing key {key!r}")
    for key in sorted(set(doc) - COST_CARD_FIELDS):
        errs.append(f"{path}: cost card key {key!r} is not in the "
                    "known-field registry (costmodel and validator "
                    "drifted?)")
    cost = doc.get("cost")
    if isinstance(cost, dict):
        for key in sorted(_COST_SUBFIELDS - set(cost)):
            errs.append(f"{path}: cost missing key {key!r}")
        for key in ("flops_per_round", "bytes_per_round",
                    "steps_per_round"):
            v = cost.get(key)
            if key in cost and (not _num(v) or v <= 0):
                errs.append(f"{path}: cost.{key} must be a number > 0")
        ai, fl, by = (cost.get(k) for k in ("arithmetic_intensity",
                                            "flops_per_round",
                                            "bytes_per_round"))
        if all(_num(x) for x in (ai, fl, by)) and by > 0 \
                and abs(ai - fl / by) > 1e-6 * max(1.0, abs(ai)):
            errs.append(f"{path}: cost.arithmetic_intensity {ai} != "
                        f"flops/bytes {fl / by}")
    elif "cost" in doc:
        errs.append(f"{path}: 'cost' must be an object")
    roof = doc.get("roofline")
    if isinstance(roof, dict):
        for key in sorted(_ROOFLINE_SUBFIELDS - set(roof)):
            errs.append(f"{path}: roofline missing key {key!r}")
        if "bound" in roof and roof["bound"] not in ("bandwidth",
                                                     "compute"):
            errs.append(f"{path}: roofline.bound must be 'bandwidth' or "
                        f"'compute', got {roof.get('bound')!r}")
        v = roof.get("predicted_steps_per_sec")
        if "predicted_steps_per_sec" in roof and (not _num(v) or v <= 0):
            errs.append(f"{path}: roofline.predicted_steps_per_sec must "
                        "be a number > 0")
    elif "roofline" in doc:
        errs.append(f"{path}: 'roofline' must be an object")
    if doc.get("schema") != 1:
        errs.append(f"{path}: schema {doc.get('schema')!r} != 1")
    return errs


def validate_service_jobs(path) -> list:
    """Schema checks for a sweepd completed-job report artifact
    (``{"version": 1, "rows": [...]}``, rows exactly the
    SERVICE_JOB_FIELDS keys — the file ``tools/ledger.py`` ingests as
    ``service-job`` rows when published under benchmarks/parts/)."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    if doc.get("version") != 1:
        errs.append(f"{path}: version {doc.get('version')!r} != 1")
    rows = doc.get("rows")
    if not isinstance(rows, list):
        return errs + [f"{path}: 'rows' must be a list"]
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            errs.append(f"{path}: rows[{i}] must be an object")
            continue
        for key in sorted(SERVICE_JOB_FIELDS - set(r)):
            errs.append(f"{path}: rows[{i}] missing key {key!r}")
        for key in sorted(set(r) - SERVICE_JOB_FIELDS):
            errs.append(f"{path}: rows[{i}] key {key!r} is not in the "
                        "known-field registry (service and validator "
                        "drifted?)")
        if r.get("schema") != 1:
            errs.append(f"{path}: rows[{i}].schema "
                        f"{r.get('schema')!r} != 1")
        if r.get("status") not in _SERVICE_JOB_STATES:
            errs.append(f"{path}: rows[{i}].status {r.get('status')!r} "
                        f"not in {sorted(_SERVICE_JOB_STATES)} (only "
                        "finished jobs are reportable)")
        if r.get("status") == "done":
            d = r.get("digest")
            if not isinstance(d, str) or len(d) != 64:
                errs.append(f"{path}: rows[{i}]: a done job must carry "
                            "its 64-hex decided-log digest")
            for key in ("wall_s", "steps_per_sec"):
                if not _num(r.get(key)) or r[key] < 0:
                    errs.append(f"{path}: rows[{i}].{key} must be a "
                                "finite number >= 0 on a done job")
        elif not r.get("error"):
            errs.append(f"{path}: rows[{i}]: a failed job must carry "
                        "its error")
        b = r.get("batch")
        if b is not None and (not isinstance(b, list) or not all(
                isinstance(x, str) for x in b)):
            errs.append(f"{path}: rows[{i}].batch must be null or a "
                        "list of job ids")
    return errs


def validate_ledger(path) -> list:
    """Schema checks for benchmarks/LEDGER.json (tools/ledger.py): row
    keys against the registry, series verdicts from the known set, and
    the measured-vs-predicted contract (every results-tpu row carries a
    prediction + ratio)."""
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable/not JSON: {exc}"]
    if not isinstance(doc, dict):
        return [f"{path}: top level must be an object"]
    errs = []
    if doc.get("version") != 1:
        errs.append(f"{path}: version {doc.get('version')!r} != 1")
    rows = doc.get("rows")
    if not isinstance(rows, list):
        return errs + [f"{path}: 'rows' must be a list"]
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            errs.append(f"{path}: rows[{i}] must be an object")
            continue
        for key in sorted(LEDGER_ROW_FIELDS - set(r)):
            errs.append(f"{path}: rows[{i}] missing key {key!r}")
        for key in sorted(set(r) - LEDGER_ROW_FIELDS):
            errs.append(f"{path}: rows[{i}] key {key!r} is not in the "
                        "known-field registry (ledger and validator "
                        "drifted?)")
        if r.get("kind") not in _LEDGER_KINDS:
            errs.append(f"{path}: rows[{i}].kind {r.get('kind')!r} not in "
                        f"{sorted(_LEDGER_KINDS)}")
        for key in ("steps_per_sec", "wall_s", "predicted_steps_per_sec",
                    "measured_vs_predicted"):
            v = r.get(key)
            if v is not None and key in r and (not _num(v) or v < 0):
                errs.append(f"{path}: rows[{i}].{key} must be null or a "
                            "number >= 0")
        if r.get("kind") == "results-tpu" and r.get("steps_per_sec"):
            # The Observatory acceptance contract: every measured
            # RESULTS row is judged against the cost model.
            for key in ("predicted_steps_per_sec",
                        "measured_vs_predicted"):
                if not _num(r.get(key)) or r[key] <= 0:
                    errs.append(f"{path}: rows[{i}] ({r.get('name')}): "
                                f"results-tpu row has no {key} — cost "
                                "card missing or unmatched")
    series = doc.get("series")
    if series is not None and not isinstance(series, dict):
        errs.append(f"{path}: 'series' must be an object")
    elif isinstance(series, dict):
        for key, s in sorted(series.items()):
            if not isinstance(s, dict) \
                    or s.get("verdict") not in _LEDGER_VERDICTS:
                errs.append(f"{path}: series {key!r} verdict "
                            f"{s.get('verdict') if isinstance(s, dict) else s!r} "
                            f"not in {sorted(_LEDGER_VERDICTS)}")
    if not isinstance(doc.get("regressions"), list):
        errs.append(f"{path}: 'regressions' must be a list")
    if not isinstance(doc.get("stale_rows"), list):
        errs.append(f"{path}: 'stale_rows' must be a list")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Validate trace JSONL / metrics JSON / RunReport "
                    "files against the docs/OBSERVABILITY.md schema.")
    ap.add_argument("--trace", default="", help="span/event JSONL file")
    ap.add_argument("--metrics", default="", help="metrics snapshot JSON")
    ap.add_argument("--report", default="", help="RunReport JSON")
    ap.add_argument("--cli-report", default="",
                    help="the CLI's one-line JSON run report (saved "
                         "stdout); telemetry counter names and "
                         "checkpoint_io fields are checked against the "
                         "known-name registries")
    ap.add_argument("--finding", default="",
                    help="an adversary-search findings artifact "
                         "(tools/advsearch --findings-out); finding "
                         "fields are checked against the known-field "
                         "registry")
    ap.add_argument("--costcard", action="append", default=[],
                    help="a committed cost card "
                         "(benchmarks/parts/costcards/*.json; "
                         "repeatable)")
    ap.add_argument("--ledger", default="",
                    help="the cross-run perf ledger "
                         "(benchmarks/LEDGER.json)")
    ap.add_argument("--service-jobs", default="",
                    help="a sweepd completed-job report artifact "
                         "(the daemon's job_reports.json / --publish "
                         "file); row fields are checked against the "
                         "known-field registry")
    ap.add_argument("--expect-spans", default="",
                    help="comma-separated registered span names that MUST "
                         "appear in --trace (e.g. 'ckpt_snapshot,"
                         "ckpt_write' to prove a run checkpointed "
                         "asynchronously)")
    ap.add_argument("--expect-events", default="",
                    help="comma-separated registered event names that MUST "
                         "appear in --trace (e.g. 'attempt_failed' for a "
                         "supervised-retry trace)")
    args = ap.parse_args(argv)
    if not (args.trace or args.metrics or args.report or args.cli_report
            or args.costcard or args.ledger or args.finding
            or args.service_jobs):
        ap.error("nothing to validate: pass --trace/--metrics/--report/"
                 "--cli-report/--costcard/--ledger/--finding/"
                 "--service-jobs")
    if (args.expect_spans or args.expect_events) and not args.trace:
        ap.error("--expect-spans/--expect-events need --trace (they assert "
                 "presence in that file)")

    def _split(spec):
        return [n.strip() for n in spec.split(",") if n.strip()]

    errs = []
    if args.trace:
        errs += validate_trace(args.trace)
        if args.expect_spans:
            errs += validate_expected_spans(args.trace,
                                            _split(args.expect_spans))
        if args.expect_events:
            errs += validate_expected_events(args.trace,
                                             _split(args.expect_events))
    if args.metrics:
        errs += validate_metrics(args.metrics)
    if args.report:
        errs += validate_report(args.report)
    if args.cli_report:
        errs += validate_cli_report(args.cli_report)
    for card in args.costcard:
        errs += validate_costcard(card)
    if args.ledger:
        errs += validate_ledger(args.ledger)
    if args.finding:
        errs += validate_finding(args.finding)
    if args.service_jobs:
        errs += validate_service_jobs(args.service_jobs)
    for e in errs:
        print(f"validate_trace: {e}", file=sys.stderr)
    if errs:
        print(f"validate_trace: FAILED ({len(errs)} violations)",
              file=sys.stderr)
        return 1
    print("validate_trace: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
