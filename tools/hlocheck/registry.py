"""The (engine × flagship shape × mesh) configs hlocheck lowers.

Flagship shapes come straight from ``benchmarks/run_benchmarks.CONFIGS``
(one source of truth — a benchmark shape change re-fingerprints
automatically), plus two canonical non-flagship targets:

  * ``raft-1k-cap8`` — the §3b capped engine at the mesh-divisible
    population ``tests/test_mesh_collectives.py`` established, where the
    STRICT all-reduce-family claim holds. Checked under both (2, 4) and
    (1, 8) meshes: reshaping the mesh must not change any verdict.
  * ``pbft-1k-dense`` — the dense §6 engine (no flagship config of its
    own; the 100k row is the §6b bcast engine), so its sort budget and
    donation are still pinned.

Variant axes per target:

  * ``single``  — no mesh: the exact program the benchmarks dispatch.
    All five contracts enforced, budgets included.
  * ``sweep8``  — sweep-only (8,) mesh: must compile to ZERO
    collectives (sweeps are independent simulators). Registered
    wherever 8 divides the flagship sweep count.
  * node-sharded variants — only for engines whose PROGRAM_CONTRACT
    claims one (docs/STATIC_ANALYSIS.md "compiled-program layer"):
    raft-sparse at "strict" (canonical shape) and "bounded" (flagship
    100k, where distributed sorts legally add all-to-all but stay
    O(N)); dpos at "zero".
"""
from __future__ import annotations

import dataclasses
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parents[2]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from benchmarks.run_benchmarks import CONFIGS as FLAGSHIP_CONFIGS  # noqa: E402
from consensus_tpu.core.config import Config  # noqa: E402

FINGERPRINT_DIR = _REPO / "benchmarks" / "parts" / "fingerprints"

ADV = dict(drop_rate=0.01, churn_rate=0.001)


@dataclasses.dataclass(frozen=True)
class Variant:
    key: str
    mesh_shape: tuple[int, ...] | None
    mode: str | None        # collective mode (None = single device)
    axis: str | None = None  # "sweep" | "node" for meshed variants


@dataclasses.dataclass(frozen=True)
class Target:
    name: str
    cfg: Config
    variants: tuple[Variant, ...]
    # Per-target EngineContract field overrides (e.g. the -switch
    # targets' TIGHTENED sort/cumsum ceilings: the SPEC §9 switch round
    # replaces the pbft-bcast sorted-space machinery with segment
    # reduces, so its budget pins to 0/0 while the engine's flat
    # declaration keeps its own ceiling). Budgets may only TIGHTEN
    # here — tools/hlocheck/__main__ applies them via
    # dataclasses.replace and refuses a loosening override.
    contract_override: dict | None = None
    # Non-None = an f-LADDER target: lower the one-program padded sweep
    # (engines/pbft_sweep.fsweep_lower over these rungs) instead of the
    # chunked round loop. A ladder is ONE dispatch — no cross-dispatch
    # carry exists, so its donation contract sees zero carry leaves by
    # construction (tools/hlocheck/__main__).
    fsweep: tuple[int, ...] | None = None
    # True = lower the FLIGHT-RECORDER-ON program (cfg.telemetry_window
    # must be > 0): the telemetry accumulator + window ring + latency
    # histograms ride the scan and count as three extra donated leaves.
    # Pins that the recorder does not reintroduce sort/cumsum-class ops
    # against the engine's (lowered) budgets.
    flight: bool = False


SINGLE = Variant("single", None, None)
SWEEP8 = Variant("sweep8", (8,), "zero", "sweep")

# The canonical capped-raft shape of tests/test_mesh_collectives.py —
# the population where the strict family claim is established.
CAPPED_1K = Config(protocol="raft", n_nodes=1024, n_rounds=8, n_sweeps=2,
                   log_capacity=32, max_entries=24, max_active=8, seed=6,
                   **ADV)

PBFT_1K_DENSE = Config(protocol="pbft", f=341, n_nodes=1024, n_rounds=32,
                       n_sweeps=2, log_capacity=16, seed=3, **ADV)

# The canonical hotstuff shape at the mesh-divisible population
# (N = 3·341+1 = 1024): where the engine's "bounded" node-sharded claim
# is established — the [N] per-node leaves shard, the vote count is one
# psum, and every collective stays O(N) metadata (there is no [N, S]
# carry leaf to gather). Checked under both (2, 4) and (1, 8) meshes
# like raft-1k-cap8.
HOTSTUFF_1K = Config(protocol="hotstuff", f=341, n_nodes=1024,
                     n_rounds=32, n_sweeps=2, log_capacity=32, seed=9,
                     **ADV)

# The one-program §6b f-ladder at the flagship population: rungs pad to
# N_pad = 3·33333+1 = 100k, the pbft-100k-bcast shape — so the program
# that serves `--fault-model bcast --f-sweep ...` (the lifted carve-out,
# VERDICT weak #5) is contract-pinned at trace time like every other
# flagship program. Base config mirrors the CLI's (`args_to_config`
# with the ladder's rates); engines/pbft_sweep.fsweep_lower swaps in
# the padded shape and the per-(rung, sweep) lane axis.
FSWEEP_BCAST_FS = (8333, 16666, 33333)
PBFT_BCAST_FSWEEP = Config(protocol="pbft", fault_model="bcast", f=1,
                           n_nodes=4, n_rounds=64, n_sweeps=1,
                           log_capacity=16, seed=7, **ADV)

# The recorder-ON flagship program (docs/OBSERVABILITY.md §"Flight
# recorder"): pbft-100k-bcast — the one engine whose sort diet (PR 8)
# the windows must not undo — with an 8-round window. The recorder-OFF
# program is pinned by the plain pbft-100k-bcast fingerprint staying
# byte-stable (the static no-op); this target pins the ON program to
# the same sort_budget=1 / cumsum_budget=20 ceilings.
PBFT_BCAST_FLIGHT = dataclasses.replace(FLAGSHIP_CONFIGS["pbft-100k-bcast"],
                                        telemetry_window=8)


# SPEC §9 switch-model flagship targets: the flagship shapes re-lowered
# under net_model="switch" with the full fault surface compiled in
# (nonzero agg_fail/agg_stale so the STREAM_AGG machinery is part of
# the pinned program). K = 8 aggregators (divides the 100k populations
# exactly; the 10k paxos shape pads by reshape).
def _switch(cfg: Config) -> Config:
    return dataclasses.replace(cfg, net_model="switch", n_aggregators=8,
                               agg_fail_rate=0.01, agg_stale_rate=0.01,
                               agg_max_stale=4)


def targets() -> tuple[Target, ...]:
    F = FLAGSHIP_CONFIGS
    return (
        Target("raft-5node", F["raft-5node"], (SINGLE, SWEEP8)),
        Target("raft-1kx1k", F["raft-1kx1k"], (SINGLE, SWEEP8)),
        Target("raft-100k", F["raft-100k"],
               (SINGLE, Variant("node2x4", (2, 4), "bounded", "node"),
                SWEEP8)),
        Target("pbft-100k-bcast", F["pbft-100k-bcast"], (SINGLE, SWEEP8)),
        Target("hotstuff-100k", F["hotstuff-100k"],
               (SINGLE, SWEEP8,
                Variant("node1x8", (1, 8), "bounded", "node"))),
        Target("pbft-100k-bcast-flight", PBFT_BCAST_FLIGHT, (SINGLE,),
               flight=True),
        Target("pbft-100k-bcast-fsweep", PBFT_BCAST_FSWEEP, (SINGLE,),
               fsweep=FSWEEP_BCAST_FS),
        Target("paxos-10kx10k", F["paxos-10kx10k"], (SINGLE,)),
        Target("dpos-100k", F["dpos-100k"],
               (SINGLE, Variant("node1x8", (1, 8), "zero", "node"))),
        Target("raft-1k-cap8", CAPPED_1K,
               (SINGLE,
                Variant("node2x4", (2, 4), "strict", "node"),
                Variant("node1x8", (1, 8), "strict", "node"))),
        Target("pbft-1k-dense", PBFT_1K_DENSE, (SINGLE,)),
        # --- SPEC §9 switch-model flagships ------------------------------
        # pbft-bcast: the switch round DROPS the payload sort and the
        # run-count cumsums outright (segment sum/max/min + uniformity
        # replace sorted space) — the ceiling tightens to 0/0.
        Target("pbft-100k-bcast-switch", _switch(F["pbft-100k-bcast"]),
               (SINGLE,),
               contract_override=dict(sort_budget=0, cumsum_budget=0)),
        Target("paxos-10kx10k-switch", _switch(F["paxos-10kx10k"]),
               (SINGLE,)),
        Target("raft-100k-switch", _switch(F["raft-100k"]), (SINGLE,)),
        Target("hotstuff-100k-switch", _switch(F["hotstuff-100k"]),
               (SINGLE,)),
        Target("hotstuff-1k", HOTSTUFF_1K,
               (SINGLE,
                Variant("node2x4", (2, 4), "bounded", "node"),
                Variant("node1x8", (1, 8), "bounded", "node"))),
    )


def target(name: str) -> Target:
    for t in targets():
        if t.name == name:
            return t
    raise KeyError(f"unknown hlocheck target {name!r}; "
                   f"known: {[t.name for t in targets()]}")
