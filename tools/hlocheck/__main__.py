"""hlo-contract: static analysis of the COMPILED programs.

    python -m tools.hlocheck [--update] [--only NAME ...] [--list]

Lowers every registered (engine × flagship shape × mesh) config through
the production round-loop jit on the CPU backend (trace time only — no
simulation executes, no flagship-sized buffer is allocated) and
enforces the per-engine ``PROGRAM_CONTRACTS``:

  collectives    — all-reduce family / O(N)-bounded / zero, per the
                   engine's declared node-sharded claim; sweep-only
                   meshes always collective-free
  sort_budget    — sort- and cumsum-class op counts per round, pinned
                   to per-engine regression ceilings
  dtypes         — no f64/s64/u64 anywhere in the lowered module
  host_boundary  — no infeed/outfeed/host-callback custom-calls
  donation       — every chunked-carry input buffer aliases an output
                   (runner._chunk_jit donate_argnums)

and compares a normalized program fingerprint against the committed one
under ``benchmarks/parts/fingerprints/`` (`--update` regenerates after
an intentional change; a contract violation is never writable). Exit
status: nonzero on any violation, verdict drift, or same-toolchain
structural drift. When jax is missing the gate SKIPs loudly with
status 0, mirroring tools/check.py's gated-layer convention.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def _setup_platform() -> None:
    """CPU backend + 8 virtual devices, BEFORE the first jax import —
    mirrors tests/conftest.py (the container's sitecustomize may force
    the TPU plugin; lowering must never block on a tunnel)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def run_checks(only: list[str] | None = None, update: bool = False) -> int:
    import jax

    from . import contracts, fingerprint, hlo, registry

    jax.config.update("jax_platforms", "cpu")
    cons = contracts.program_contracts()
    targets = [t for t in registry.targets()
               if not only or t.name in only]
    if only:
        missing = set(only) - {t.name for t in targets}
        if missing:
            print(f"hlocheck: unknown target(s) {sorted(missing)}; known: "
                  f"{[t.name for t in registry.targets()]}", file=sys.stderr)
            return 2
    need_mesh = any(v.mesh_shape for t in targets for v in t.variants)
    if need_mesh and len(jax.devices()) < 8:
        print("hlocheck: FAIL — mesh variants need 8 virtual devices; run "
              "with JAX_PLATFORMS=cpu XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 (or via "
              "`python -m tools.hlocheck`, which sets both)",
              file=sys.stderr)
        return 1

    rc = 0
    for tgt in targets:
        from consensus_tpu.network import simulator
        eng = simulator.engine_def(tgt.cfg)
        con = cons[eng.name]
        if tgt.contract_override:
            import dataclasses as _dc
            for k in ("sort_budget", "cumsum_budget"):
                if tgt.contract_override.get(k, 0) > getattr(con, k):
                    print(f"hlocheck: {tgt.name}: contract_override may "
                          f"only TIGHTEN {k} (engine ceiling "
                          f"{getattr(con, k)})", file=sys.stderr)
                    return 2
            con = _dc.replace(con, **tgt.contract_override)
        # f-ladder targets are ONE dispatch (no chunked cross-dispatch
        # carry), so their donation contract is trivially zero leaves.
        # Flight-recorder targets donate the telemetry accumulator +
        # window ring + latency histograms on top of the carry; those
        # three riders sit AFTER the undonated r0 scalar in the entry-
        # parameter order, so the expected donated set is not a prefix.
        donated_params = None
        leaves = 0 if tgt.fsweep else hlo.n_carry_leaves(tgt.cfg, eng)
        if tgt.flight:
            donated_params = list(range(leaves)) + [leaves + 1 + i
                                                    for i in range(3)]
            leaves += 3
        variants: dict[str, dict] = {}
        bad = False
        for var in tgt.variants:
            t0 = time.perf_counter()
            rep = (hlo.fsweep_compiled_report(tgt.cfg, tgt.fsweep)
                   if tgt.fsweep
                   else hlo.compiled_report(tgt.cfg, eng, var.mesh_shape,
                                            flight=tgt.flight))
            viol = contracts.check_module(
                rep, con, tgt.cfg, mode=var.mode, axis=var.axis,
                carry_leaves=leaves,
                enforce_budgets=var.mesh_shape is None,
                donated_params=donated_params)
            verd = contracts.verdicts(viol)
            variants[var.key] = fingerprint.variant_entry(
                var, rep, verd, leaves)
            wall = time.perf_counter() - t0
            status = "ok" if not viol else "FAIL"
            print(f"hlocheck: {tgt.name}/{var.key:8s} [{eng.name}] "
                  f"{status}  ({wall:.1f}s, sort={rep.sort_ops} "
                  f"cumsum={rep.cumsum_ops} donated={len(rep.donation)}/"
                  f"{leaves})", flush=True)
            for v in viol:
                print(f"hlocheck:   {v}", flush=True)
                bad = True
        doc = fingerprint.build(tgt, eng.name, variants)
        if bad:
            rc = 1
            if update:
                print(f"hlocheck: {tgt.name}: NOT updating fingerprint — "
                      f"contracts must pass first", flush=True)
            continue
        committed = fingerprint.load(tgt.name)
        if update:
            path = fingerprint.save(doc)
            print(f"hlocheck: {tgt.name}: fingerprint written -> {path}",
                  flush=True)
            continue
        if committed is None:
            print(f"hlocheck: {tgt.name}: FAIL — no committed fingerprint "
                  f"({fingerprint.path_for(tgt.name)}); run "
                  f"`python -m tools.hlocheck --update` and commit it",
                  flush=True)
            rc = 1
            continue
        verdict_diffs, struct_diffs = fingerprint.diff(committed, doc)
        if verdict_diffs:
            print(f"hlocheck: {tgt.name}: FAIL — contract VERDICTS drifted "
                  f"from the committed fingerprint:", flush=True)
            for line in verdict_diffs:
                print(line, flush=True)
            rc = 1
        if struct_diffs:
            if fingerprint.same_toolchain(committed):
                print(f"hlocheck: {tgt.name}: FAIL — structural drift vs "
                      f"committed fingerprint (same toolchain ⇒ a code "
                      f"change; rerun with --update if intentional):",
                      flush=True)
                rc = 1
            else:
                print(f"hlocheck: {tgt.name}: WARNING — structural drift "
                      f"under a DIFFERENT jax/jaxlib; op-count churn is "
                      f"expected across compilers (verdicts above are the "
                      f"enforced layer). Diff:", flush=True)
            for line in struct_diffs:
                print(line, flush=True)
    print(f"hlocheck: {'FAILED' if rc else 'ok'} "
          f"({len(targets)} targets)", flush=True)
    return rc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.hlocheck",
        description="Compiled-program contract analyzer "
                    "(docs/STATIC_ANALYSIS.md, compiled-program layer).")
    ap.add_argument("--update", action="store_true",
                    help="regenerate the committed fingerprints (refused "
                         "while any contract fails)")
    ap.add_argument("--only", action="append", default=None,
                    help="check only this target (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list registered targets and variants")
    args = ap.parse_args(argv)

    if "jax" not in sys.modules:
        _setup_platform()
    try:
        import jax  # noqa: F401
    except ImportError:
        print("hlocheck: SKIP — jax is not installed; the compiled-"
              "program contracts need the CPU backend to lower against "
              "(install jax[cpu] to enforce this layer)", file=sys.stderr)
        return 0

    if args.list:
        from . import registry
        for t in registry.targets():
            keys = ", ".join(v.key for v in t.variants)
            print(f"{t.name:18s} [{keys}]")
        return 0
    return run_checks(only=args.only, update=args.update)


if __name__ == "__main__":
    sys.exit(main())
