"""Program fingerprints: normalized compiled-program summaries, committed
under ``benchmarks/parts/fingerprints/`` and diffed on every check run.

A fingerprint is NOT the HLO text (instruction names, ids and layouts
churn with every compiler release); it is the structure the repo's perf
and scaling claims actually rest on:

  * the op-CLASS histogram (sort / cumsum / collective / gather /
    scatter / reduce / elementwise / data / control — coarse buckets
    survive fusion-decision churn),
  * the collective census (op -> count + largest operand element count),
  * the donation map size (how many carry buffers alias),
  * the per-variant contract verdicts.

Tolerance policy: verdict drift ALWAYS fails (the verdicts are the
compiler-version-tolerant layer — a contract that passed must keep
passing on any toolchain). Structural drift (histogram, censuses,
budgets' exact values) fails when the recorded jax/jaxlib version pair
matches the running one — same compiler, same program, so any diff is a
code change that must be intentional (`--update`) — and downgrades to a
LOUD warning across compiler versions, where op-count churn is expected.
Files are written with sorted keys and a trailing newline so `--update`
round-trips byte-stable.
"""
from __future__ import annotations

import json
import pathlib

from . import hlo, registry

SCHEMA = 1


def path_for(name: str) -> pathlib.Path:
    return registry.FINGERPRINT_DIR / f"{name}.json"


def _jax_versions() -> dict[str, str]:
    import jax
    import jaxlib
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


def variant_entry(variant, rep: hlo.ModuleReport, verdicts: dict[str, str],
                  carry_leaves: int) -> dict:
    return {
        "mesh": list(variant.mesh_shape) if variant.mesh_shape else None,
        "mode": variant.mode,
        "verdicts": dict(sorted(verdicts.items())),
        "histogram": rep.histogram(),
        "collectives": {op: {"count": len(sizes),
                             "max_elems": max(sizes)}
                        for op, sizes in sorted(rep.collectives.items())},
        "sort_ops": rep.sort_ops,
        "cumsum_ops": rep.cumsum_ops,
        "donated_leaves": len(rep.donation),
        "carry_leaves": carry_leaves,
        "wide_dtypes": list(rep.wide_dtypes),
        "custom_calls": list(rep.custom_call_targets),
    }


def build(target, engine_name: str, variants: dict[str, dict]) -> dict:
    return {
        "schema": SCHEMA,
        "name": target.name,
        "engine": engine_name,
        "chunk_rounds": hlo.chunk_rounds(target.cfg),
        "toolchain": _jax_versions(),
        "config": json.loads(target.cfg.to_json()),
        "variants": dict(sorted(variants.items())),
    }


def save(doc: dict) -> pathlib.Path:
    path = path_for(doc["name"])
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return path


def load(name: str) -> dict | None:
    path = path_for(name)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def _walk_diff(prefix: str, old, new, out: list[str]) -> None:
    if isinstance(old, dict) and isinstance(new, dict):
        for k in sorted(set(old) | set(new)):
            _walk_diff(f"{prefix}.{k}" if prefix else str(k),
                       old.get(k), new.get(k), out)
    elif old != new:
        out.append(f"  {prefix}: {old!r} -> {new!r}")


def diff(committed: dict, current: dict) -> tuple[list[str], list[str]]:
    """(verdict_diffs, structural_diffs) between a committed fingerprint
    and a freshly computed one. Toolchain and schema fields are compared
    as structure (an intentional jax upgrade re-records them via
    --update)."""
    verdicts: list[str] = []
    structure: list[str] = []
    for key in sorted(set(committed.get("variants", {}))
                      | set(current.get("variants", {}))):
        old = committed.get("variants", {}).get(key, {})
        new = current.get("variants", {}).get(key, {})
        _walk_diff(f"variants.{key}.verdicts",
                   old.get("verdicts"), new.get("verdicts"), verdicts)
        for field in sorted((set(old) | set(new)) - {"verdicts"}):
            _walk_diff(f"variants.{key}.{field}",
                       old.get(field), new.get(field), structure)
    for field in ("schema", "engine", "chunk_rounds", "config", "toolchain"):
        _walk_diff(field, committed.get(field), current.get(field), structure)
    return verdicts, structure


def same_toolchain(committed: dict) -> bool:
    return committed.get("toolchain") == _jax_versions()
