"""hlo-contract: static analysis of the compiled programs.

The AST layer (``tools/lint``) checks what the SOURCE promises; this
package checks what the COMPILER produced — the layer where the repo's
perf/scaling claims actually live ("stays in the all-reduce family",
"pbft-bcast is sort-class-bound", "carry donation everywhere"). See
``python -m tools.hlocheck --help`` and docs/STATIC_ANALYSIS.md
("compiled-program layer").

Library surface:

  * :mod:`tools.hlocheck.hlo` — production-path lowering +
    compiled-HLO parsing (:func:`hlo.compiled_report`,
    :func:`hlo.compiled_collectives` — the generalized
    ``tests/test_mesh_collectives.py`` harness);
  * :mod:`tools.hlocheck.contracts` — the ``PROGRAM_CONTRACTS``
    registry (collected from the engine modules) and the five checks;
  * :mod:`tools.hlocheck.registry` — the (engine × flagship shape ×
    mesh) targets;
  * :mod:`tools.hlocheck.fingerprint` — normalized program
    fingerprints, committed under ``benchmarks/parts/fingerprints/``.
"""
