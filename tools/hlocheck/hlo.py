"""Lowering + compiled-HLO parsing for the program-contract analyzer.

This is the ``compiled_collectives`` harness of
``tests/test_mesh_collectives.py`` generalized into a library: lower a
config through the PRODUCTION round-loop jit (``runner._chunk_jit``, the
exact program the benchmarks dispatch), compile it on the CPU backend
(same GSPMD partitioner a real v5e runs — trace time only, zero FLOPs,
no 100k-node buffer is ever allocated thanks to ``jax.eval_shape``),
and parse the post-optimization HLO text into a structured
:class:`ModuleReport`: opcode census, collective census with operand
sizes, 64-bit dtype occurrences, host-boundary ops, and the
``input_output_alias`` donation map.

Counting note: the chunk program is ONE ``while`` loop whose body is the
round kernel (plus a fixed init/epilogue), so module-wide op counts ARE
per-round counts for the round-body op classes (sort/cumsum/collective)
— XLA never unrolls the scan at these lengths (see ``_chunk_jit``'s
docstring for why a length-1 chunk scans a masked pair instead).
"""
from __future__ import annotations

import dataclasses
import re
from collections import Counter

# --- compiled-text parsing (pure string work; no jax needed) -----------------

# One HLO instruction: `%name = <result-type> opcode(...)`. The result
# type may be a tuple with spaces — `(s32[4,8]{1,0}, u32[8]{0})` — so the
# type segment is matched non-greedily up to the opcode token, which is
# the first `word(` after the `=` (types never contain `(` outside the
# tuple wrapper, and metadata comes after the operand list).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT )?%[\w.\-]+ = (.*?) ([a-z][\w\-]*)\(", re.M)
_SHAPE_RE = re.compile(r"\b([a-z0-9]+)\[([\d,]*)\]")
_WIDE_RE = re.compile(r"\b(f64|s64|u64|c128)\[")
_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
# `input_output_alias={ {0}: (0, {}, may-alias), ... }` in the module
# header: output tuple index -> donated entry-parameter index.
_ALIAS_RE = re.compile(
    r"\{([\d,]*)\}:\s*\((\d+),\s*\{[\d,]*\},?\s*(?:may|must)-alias\)")

SORT_OPS = frozenset({"sort"})
# Cumsum-class = PREFIX-SCAN reduce-windows only (cumsum/cummax/cummin
# brackets — docs/PERF.md "sort diet"): their windows slide with unit
# stride (`size=1x1x16 pad=..x15_0` cascade stages). The CPU backend
# ALSO lowers large plain reductions (an ordinary ``jnp.sum``) as
# reduce-window cascades, but those windows are tiled — ``stride=1x32``
# — and a plain reduction is a single bandwidth-benign pass, not a scan
# bracket (on TPU it lowers as a plain reduce); ``analyze`` re-labels
# strided reduce-windows ``reduce-window-strided`` so they land in the
# reduce class, not against the cumsum budget. Top-k has no custom-call
# lowering here and lands in the sort class.
CUMSUM_OPS = frozenset({"reduce-window"})
_WINDOW_RE = re.compile(r"window=\{([^}]*)\}")
_STRIDE_RE = re.compile(r"stride=([\dx]+)")
COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast"})
HOST_OPS = frozenset({"infeed", "outfeed", "send", "recv",
                      "send-done", "recv-done"})
# Host-callback custom-call targets (jax.pure_callback / io_callback /
# debug prints): any target matching this is a host round-trip inside
# what must be a pure device program.
HOST_CALLBACK_RE = re.compile(r"callback|host|py_func", re.I)

# Coarse op classes for the normalized fingerprint histogram: buckets
# are stable across compiler versions even when fusion decisions move
# individual elementwise ops around.
_CLASS_PATTERNS: tuple[tuple[str, frozenset[str]], ...] = (
    ("sort", SORT_OPS),
    ("cumsum", CUMSUM_OPS),
    ("collective", COLLECTIVE_OPS),
    ("host", HOST_OPS),
    ("custom-call", frozenset({"custom-call"})),
    ("gather", frozenset({"gather", "dynamic-slice"})),
    ("scatter", frozenset({"scatter", "dynamic-update-slice"})),
    ("reduce", frozenset({"reduce", "reduce-precision",
                          "reduce-window-strided"})),
    ("rng", frozenset({"rng", "rng-bit-generator", "rng-get-and-update-state"})),
    ("control", frozenset({"while", "conditional", "call", "fusion"})),
    ("data", frozenset({
        "parameter", "constant", "iota", "broadcast", "reshape",
        "transpose", "slice", "pad", "concatenate", "convert",
        "bitcast", "bitcast-convert", "copy", "copy-start", "copy-done",
        "tuple", "get-tuple-element", "domain", "after-all",
        "optimization-barrier"})),
)


def op_class(op: str) -> str:
    for cls, ops in _CLASS_PATTERNS:
        if op in ops:
            return cls
    return "elementwise"


@dataclasses.dataclass(frozen=True)
class ModuleReport:
    """Everything the contracts need from one compiled module."""
    ops: dict[str, int]                       # raw opcode -> count
    collectives: dict[str, tuple[int, ...]]   # op -> per-instr max elems
    wide_dtypes: tuple[str, ...]              # f64/s64/u64/c128 seen
    host_ops: tuple[str, ...]                 # infeed/outfeed/send/recv hits
    custom_call_targets: tuple[str, ...]      # every custom-call target
    donation: tuple[tuple[int, int], ...]     # (output index, param index)

    @property
    def sort_ops(self) -> int:
        return sum(n for op, n in self.ops.items() if op in SORT_OPS)

    @property
    def cumsum_ops(self) -> int:
        return sum(n for op, n in self.ops.items() if op in CUMSUM_OPS)

    def histogram(self) -> dict[str, int]:
        out: Counter = Counter()
        for op, n in self.ops.items():
            out[op_class(op)] += n
        return dict(sorted(out.items()))


def _max_elems(type_segment: str) -> int:
    """Largest element count among a result type's (possibly tuple)
    array members — the size a bound on "what this op moves" must see."""
    best = 1
    for m in _SHAPE_RE.finditer(type_segment):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        best = max(best, n)
    return best


def _scan_window(rest_of_line: str) -> bool:
    """True when a reduce-window instruction's window slides with unit
    stride — the prefix-scan (cumsum-class) form. Tiled windows
    (any stride component > 1) are reduction cascade stages."""
    w = _WINDOW_RE.search(rest_of_line)
    if not w:
        return True
    s = _STRIDE_RE.search(w.group(1))
    return s is None or set(s.group(1).split("x")) <= {"1"}


def analyze(txt: str) -> ModuleReport:
    """Parse one compiled module's text into a :class:`ModuleReport`."""
    ops: Counter = Counter()
    collectives: dict[str, list[int]] = {}
    host_ops: list[str] = []
    for m in _INSTR_RE.finditer(txt):
        type_seg, op = m.group(1), m.group(2)
        if op == "reduce-window":
            eol = txt.find("\n", m.end())
            rest = txt[m.end():eol if eol != -1 else len(txt)]
            if not _scan_window(rest):
                op = "reduce-window-strided"
        ops[op] += 1
        if op in COLLECTIVE_OPS:
            collectives.setdefault(op, []).append(_max_elems(type_seg))
        if op in HOST_OPS:
            host_ops.append(op)
    header = txt.splitlines()[0] if txt else ""
    donation = tuple(
        (int(m.group(1).split(",")[0] or 0), int(m.group(2)))
        for m in _ALIAS_RE.finditer(header))
    return ModuleReport(
        ops=dict(sorted(ops.items())),
        collectives={k: tuple(sorted(v)) for k, v in
                     sorted(collectives.items())},
        wide_dtypes=tuple(sorted(set(_WIDE_RE.findall(txt)))),
        host_ops=tuple(sorted(host_ops)),
        custom_call_targets=tuple(sorted(set(_TARGET_RE.findall(txt)))),
        donation=donation)


# --- production-path lowering (imports jax lazily) ---------------------------

def carry_struct(cfg, eng):
    """ShapeDtypeStruct pytree of the batched carry via ``eval_shape`` —
    no buffer is ever allocated, so 100k-node configs are safe on any
    host."""
    import jax
    import jax.numpy as jnp
    seeds = jax.ShapeDtypeStruct((cfg.n_sweeps,), jnp.uint32)
    return jax.eval_shape(
        lambda s: jax.vmap(lambda x: eng.make_carry(cfg, x))(s), seeds)


def chunk_rounds(cfg) -> int:
    """The round count of the production chunk program (`runner.run`'s
    chunking rule without the checkpoint-implied split)."""
    return cfg.scan_chunk or cfg.n_rounds


def n_carry_leaves(cfg, eng) -> int:
    import jax
    return len(jax.tree.leaves(carry_struct(cfg, eng)))


def flight_structs(cfg, eng):
    """ShapeDtypeStructs of the flight recorder's (telem, win, lat)
    scan-riders for ``cfg`` (``cfg.telemetry_window`` must be > 0) —
    what a recorder-ON target lowers ``_chunk_jit`` with. The win/lat
    geometry comes from ``runner.flight_structs`` (the one declaration
    the dispatch path also uses), so the fingerprinted program cannot
    drift from the dispatched one."""
    import jax
    import jax.numpy as jnp

    from consensus_tpu.network import runner
    telem = jax.ShapeDtypeStruct(
        (cfg.n_sweeps, len(eng.telemetry_names)), jnp.int32)
    return (telem,) + tuple(runner.flight_structs(cfg, eng))


def compiled_text(cfg, eng=None, mesh_shape=None, *, jit_fn=None,
                  mesh=None, flight: bool = False) -> str:
    """Compiled (post-GSPMD, post-optimization) HLO text of one
    production round-loop chunk: ``runner._chunk_jit.lower(...)
    .compile().as_text()`` over eval_shape structs — trace time only.

    ``jit_fn`` substitutes another jit with the same signature (the
    un-donated fixture twin); ``mesh`` passes a prebuilt Mesh (fixtures
    close over one), else ``mesh_shape`` builds it. ``flight=True``
    lowers the recorder-ON program (telemetry accumulator + window ring
    + latency histograms riding the scan — :func:`flight_structs`).
    """
    import jax
    import jax.numpy as jnp

    from consensus_tpu.network import runner, simulator
    from consensus_tpu.parallel import mesh as meshlib
    if eng is None:
        eng = simulator.engine_def(cfg)
    if mesh is None and mesh_shape:
        mesh = meshlib.make_mesh(mesh_shape)
    carry = carry_struct(cfg, eng)
    r0 = jax.ShapeDtypeStruct((), jnp.int32)
    fn = jit_fn if jit_fn is not None else runner._chunk_jit
    extra = flight_structs(cfg, eng) if flight else ()
    lowered = fn.lower(cfg, eng, chunk_rounds(cfg), carry, r0, *extra,
                       mesh=mesh)
    return lowered.compile().as_text()


def compiled_report(cfg, eng=None, mesh_shape=None, *, jit_fn=None,
                    mesh=None, flight: bool = False) -> ModuleReport:
    return analyze(compiled_text(cfg, eng, mesh_shape, jit_fn=jit_fn,
                                 mesh=mesh, flight=flight))


def fsweep_compiled_text(cfg, fs) -> str:
    """Compiled HLO text of the one-program padded f-ladder — the exact
    ``engines/pbft_sweep._fsweep_jit`` program ``--f-sweep`` dispatches,
    lowered over ShapeDtypeStructs (trace time only). Like the chunk
    program it is ONE ``while`` loop whose body is the padded round, so
    module-wide op counts are per-round counts; unlike it, a ladder is
    a single dispatch with no cross-dispatch carry, so the donation
    contract is checked at zero carry leaves."""
    from consensus_tpu.engines import pbft_sweep
    return pbft_sweep.fsweep_lower(cfg, fs).compile().as_text()


def fsweep_compiled_report(cfg, fs) -> ModuleReport:
    return analyze(fsweep_compiled_text(cfg, fs))


def compiled_collectives(cfg, mesh_shape, eng=None) -> dict[str, list[int]]:
    """op name -> element counts of each collective's result operand —
    the original ``tests/test_mesh_collectives.py`` harness, now served
    by the shared parser (tuple-typed collectives report their LARGEST
    member, a strictly tighter reading than the old first-member one)."""
    rep = compiled_report(cfg, eng, mesh_shape)
    return {k: list(v) for k, v in rep.collectives.items()}
