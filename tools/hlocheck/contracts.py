"""Per-engine compiled-program contracts (the `PROGRAM_CONTRACTS` registry).

Each engine module declares, next to its kernel, the budgets and claims
its COMPILED program must satisfy:

    PROGRAM_CONTRACT = dict(
        sort_budget=3,      # max sort-class ops per round program
        cumsum_budget=33,   # max cumsum-class (reduce-window) ops
        node_sharded=None,  # None | "zero" | "bounded" | "strict"
    )

Budgets are regression CEILINGS: the ROADMAP sort-diet work may lower
them (then lower the declaration in the same commit), never raise them
— a new sort pass slipping into a round fails the gate at trace time on
CPU, not three benchmark rounds later on a tunnel chip.

``node_sharded`` is the strongest structural claim the engine makes for
programs whose NODE axis is sharded:

  * ``"strict"``  — collective set ⊆ {all-reduce, all-gather,
    reduce-scatter}, an all-reduce present (the quorum psum actually
    crosses the mesh), all-gathers O(N) metadata, and nothing in the
    [N, L] full-carry class. The capped-raft multi-chip story.
  * ``"bounded"`` — any collective family (distributed sorts legally
    emit all-to-all / collective-permute at flagship N), but every
    collective operand stays O(N) — bounded by
    ``collective_elems_per_node * N`` and far below the [N, L] carry.
  * ``"zero"``    — no collectives at all (dpos: its carry has no
    node-indexed leaf).
  * ``None``      — no claim yet: the engine's multi-chip story is
    unproven and hlocheck registers no node-sharded variant for it.
    This is the gate the hierarchical-engine / mesh-scaling refactors
    land behind: flipping an engine's claim from None requires its
    compiled program to actually satisfy the declared mode.

Sweep-only sharding is NOT per-engine: sweeps are independent
simulators, so every engine must compile to ZERO collectives on a
sweep-only mesh (checked unconditionally wherever the flagship shape
permits one).
"""
from __future__ import annotations

import dataclasses
import importlib

from . import hlo

CONTRACT_NAMES = ("collectives", "sort_budget", "dtypes",
                  "host_boundary", "donation")

_ENGINE_MODULES = ("raft", "raft_sparse", "pbft", "pbft_bcast",
                   "paxos", "dpos", "hotstuff")

_MODES = (None, "zero", "bounded", "strict")


@dataclasses.dataclass(frozen=True)
class EngineContract:
    engine: str
    sort_budget: int
    cumsum_budget: int
    node_sharded: str | None
    # "bounded"/"strict" size cap, in units of n_nodes: a collective may
    # move O(N) metadata (fused gathers reach a few N at flagship
    # shapes), never the [N, log_capacity] carry.
    collective_elems_per_node: int = 8
    custom_call_allow: tuple[str, ...] = ()

    def __post_init__(self):
        if self.node_sharded not in _MODES:
            raise ValueError(f"{self.engine}: node_sharded="
                             f"{self.node_sharded!r} not in {_MODES}")

    def allows_mode(self, mode: str) -> bool:
        """May a node-sharded variant be checked at ``mode``? The claim
        is the strongest mode; "strict" implies "bounded"."""
        if self.node_sharded == mode:
            return True
        return self.node_sharded == "strict" and mode == "bounded"


@dataclasses.dataclass(frozen=True)
class Violation:
    contract: str   # one of CONTRACT_NAMES
    message: str

    def __str__(self) -> str:
        return f"[{self.contract}] {self.message}"


def program_contracts() -> dict[str, EngineContract]:
    """EngineDef name -> declared contract, collected from the engine
    modules (the declaration lives next to the kernel it constrains)."""
    out: dict[str, EngineContract] = {}
    for name in _ENGINE_MODULES:
        mod = importlib.import_module(f"consensus_tpu.engines.{name}")
        eng = mod.get_engine()
        out[eng.name] = EngineContract(engine=eng.name,
                                       **mod.PROGRAM_CONTRACT)
    return out


def _check_collectives(rep: hlo.ModuleReport, con: EngineContract,
                       mode: str | None, axis: str | None,
                       cfg) -> list[Violation]:
    out: list[Violation] = []
    if mode is None:                     # single-device program
        if rep.collectives:
            out.append(Violation(
                "collectives",
                f"single-device program emitted collectives: "
                f"{sorted(rep.collectives)}"))
        return out
    if axis == "node" and not con.allows_mode(mode):
        out.append(Violation(
            "collectives",
            f"engine {con.engine} claims node_sharded="
            f"{con.node_sharded!r}; a variant checked it at {mode!r}"))
        return out
    if mode == "zero":
        if rep.collectives:
            out.append(Violation(
                "collectives",
                f"expected a collective-free program, got "
                f"{ {k: len(v) for k, v in rep.collectives.items()} }"))
        return out
    # "bounded" / "strict": ONE effective size cap — the tighter of the
    # O(N)-metadata allowance and an 8× margin below the [N, L]
    # full-carry leaf (a collective approaching the leaf is the
    # partitioner giving up on the sharding, whatever the op). Merged
    # into a single check so the verdict and its message agree about
    # which bound binds at this config's log_capacity.
    n, full_leaf = cfg.n_nodes, cfg.n_nodes * cfg.log_capacity
    cap = min(con.collective_elems_per_node * n, full_leaf // 8)
    for op, sizes in rep.collectives.items():
        worst = max(sizes)
        if worst > cap:
            out.append(Violation(
                "collectives",
                f"{op} moves {worst} elements > cap {cap} "
                f"(= min({con.collective_elems_per_node}*N "
                f"= {con.collective_elems_per_node * n}, "
                f"[N, L]/8 = {full_leaf // 8})) — more than O(N) "
                f"metadata{' / full-carry-class traffic' if 8 * worst > full_leaf else ''}"))
    if mode == "strict":
        allowed = {"all-reduce", "all-gather", "reduce-scatter"}
        extra = set(rep.collectives) - allowed
        if extra:
            out.append(Violation(
                "collectives",
                f"outside the all-reduce family: {sorted(extra)}"))
        if "all-reduce" not in rep.collectives:
            out.append(Violation(
                "collectives",
                "no all-reduce: the partitioner replicated the state "
                "and the mesh is decorative"))
        gathers = rep.collectives.get("all-gather", ())
        if gathers and max(gathers) > 2 * n:
            out.append(Violation(
                "collectives",
                f"all-gather of {max(gathers)} elements > 2N={2 * n} — "
                f"more than O(N) tracked-set metadata"))
    return out


def _check_sort_budget(rep: hlo.ModuleReport,
                       con: EngineContract) -> list[Violation]:
    out = []
    if rep.sort_ops > con.sort_budget:
        out.append(Violation(
            "sort_budget",
            f"{rep.sort_ops} sort-class ops > budget {con.sort_budget} "
            f"(engine {con.engine}; budgets only ever go down)"))
    if rep.cumsum_ops > con.cumsum_budget:
        out.append(Violation(
            "sort_budget",
            f"{rep.cumsum_ops} cumsum-class ops > budget "
            f"{con.cumsum_budget} (engine {con.engine})"))
    return out


def _check_dtypes(rep: hlo.ModuleReport) -> list[Violation]:
    if rep.wide_dtypes:
        return [Violation(
            "dtypes",
            f"64-bit types in the lowered module: "
            f"{list(rep.wide_dtypes)} — an implicit promotion the AST "
            f"lint cannot see (u32/i32 discipline, docs/SPEC.md)")]
    return []


def _check_host_boundary(rep: hlo.ModuleReport,
                         con: EngineContract) -> list[Violation]:
    out = []
    if rep.host_ops:
        out.append(Violation(
            "host_boundary",
            f"host-transfer ops in a device program: {list(rep.host_ops)}"))
    bad = [t for t in rep.custom_call_targets
           if t not in con.custom_call_allow
           and hlo.HOST_CALLBACK_RE.search(t)]
    unknown = [t for t in rep.custom_call_targets
               if t not in con.custom_call_allow
               and not hlo.HOST_CALLBACK_RE.search(t)]
    if bad:
        out.append(Violation(
            "host_boundary",
            f"host-callback custom-calls: {bad} (pure_callback/"
            f"io_callback class — a host round-trip per round)"))
    if unknown:
        out.append(Violation(
            "host_boundary",
            f"undeclared custom-call targets: {unknown} — allow-list "
            f"them in the engine's PROGRAM_CONTRACT if intentional"))
    return out


def _check_donation(rep: hlo.ModuleReport, leaves: int,
                    donated_params: list[int] | None = None
                    ) -> list[Violation]:
    donated = sorted(p for _, p in rep.donation)
    want = sorted(donated_params) if donated_params is not None \
        else list(range(leaves))
    if donated != want:
        return [Violation(
            "donation",
            f"carry not (fully) donated: {len(donated)}/{leaves} input "
            f"buffers aliased (params {donated[:8]}{'...' if len(donated) > 8 else ''}, "
            f"expected {want[:8]}{'...' if len(want) > 8 else ''}) "
            f"— the chunked carry must reuse its buffers across "
            f"dispatches (runner._chunk_jit donate_argnums)")]
    return []


def check_module(rep: hlo.ModuleReport, con: EngineContract, cfg, *,
                 mode: str | None, axis: str | None,
                 carry_leaves: int,
                 enforce_budgets: bool = True,
                 donated_params: list[int] | None = None
                 ) -> list[Violation]:
    """Evaluate all five contracts against one compiled module.

    ``mode``/``axis`` describe the variant (None = single device;
    axis "sweep" or "node" for meshed ones). ``enforce_budgets`` is off
    for meshed variants: the partitioner legitimately splits one logical
    sort into per-shard sort + merge passes, so budgets pin the
    single-device program the benchmarks dispatch (mesh counts are still
    recorded in the fingerprint). ``donated_params`` overrides the
    expected donated entry-parameter indices (default
    ``range(carry_leaves)``) — recorder-ON programs donate the carry
    leaves PLUS the telem/win/lat riders, which sit after the undonated
    ``r0`` scalar in the entry-parameter order.
    """
    out = _check_collectives(rep, con, mode, axis, cfg)
    if enforce_budgets:
        out += _check_sort_budget(rep, con)
    out += _check_dtypes(rep)
    out += _check_host_boundary(rep, con)
    out += _check_donation(rep, carry_leaves, donated_params)
    return out


def verdicts(violations: list[Violation]) -> dict[str, str]:
    """Per-contract pass/fail map — the compiler-version-TOLERANT layer
    of the fingerprint (op counts may drift across XLA versions; these
    may not)."""
    failed = {v.contract for v in violations}
    return {name: ("fail" if name in failed else "pass")
            for name in CONTRACT_NAMES}
