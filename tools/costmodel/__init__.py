"""Compiled cost model — the Observatory's analytic layer.

``tools/hlocheck`` pins WHAT the compiled programs are (op classes,
collective families, budgets); this package pins what they COST: every
hlocheck-registered (engine × flagship shape × mesh) config is lowered
through the production ``runner._chunk_jit`` (trace time only, CPU
backend, no flagship buffer allocated) and XLA's ``cost_analysis()`` is
extracted into a committed per-config **cost card** —

  * FLOPs and bytes accessed per round program,
  * per-round arithmetic intensity (FLOPs / byte),
  * a roofline prediction of steps/s against the v5e peaks the
    benchmark suite already uses (``run_benchmarks.HBM_PEAK_GBPS``),
  * the node-sharded collective byte census per device (read off the
    committed hlocheck fingerprints — both artifacts drift-gate
    together).

Cards live next to the fingerprints
(``benchmarks/parts/costcards/<target>.json``) and are drift-checked by
``make check``'s ``costcheck`` layer under the same tolerance policy as
fingerprints: same-toolchain drift is a code change (fails; rerun with
``--update`` if intentional), cross-toolchain drift warns.

``--scale`` additionally projects the node-sharded configs to
N = 500k / 1M (the ROADMAP's no-tunnel scaling fallback) — see
``docs/SCALE.md`` §"Predicted node-sharded scaling".
"""
from __future__ import annotations
