"""costcheck: the compiled cost model's drift gate + card generator.

    python -m tools.costmodel [--update] [--only NAME ...] [--list]
    python -m tools.costmodel --scale [--update]

Default mode recomputes every hlocheck-registered target's cost card on
the CPU backend and compares against the committed cards under
``benchmarks/parts/costcards/`` — same tolerance policy as the
fingerprints (same-toolchain drift fails, cross-toolchain drift warns
loudly), exit nonzero on any same-toolchain drift or missing card.
``--update`` regenerates the cards. ``--scale`` prints the predicted
node-sharded scaling table (N = 500k/1M) and with ``--update`` rewrites
the marked section of docs/SCALE.md. SKIPs loudly (exit 0) when jax is
missing, mirroring tools/check.py's gated-layer convention.
"""
from __future__ import annotations

import argparse
import os
import pathlib
import sys
import time

SCALE_DOC = pathlib.Path(__file__).resolve().parents[2] / "docs" / "SCALE.md"
SCALE_BEGIN = "<!-- costmodel:scale:begin -->"
SCALE_END = "<!-- costmodel:scale:end -->"


def _setup_platform() -> None:
    """CPU backend + 8 virtual devices BEFORE the first jax import
    (mirrors tools/hlocheck.__main__ — lowering must never block on a
    tunnel)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def run_checks(only: list[str] | None = None, update: bool = False) -> int:
    import jax

    from tools.hlocheck import registry

    from . import model

    jax.config.update("jax_platforms", "cpu")
    targets = [t for t in registry.targets() if not only or t.name in only]
    if only:
        missing = set(only) - {t.name for t in targets}
        if missing:
            print(f"costcheck: unknown target(s) {sorted(missing)}; known: "
                  f"{[t.name for t in registry.targets()]}", file=sys.stderr)
            return 2

    rc = 0
    for tgt in targets:
        t0 = time.perf_counter()
        card = model.build_card(tgt)
        wall = time.perf_counter() - t0
        c = card["cost"]
        print(f"costcheck: {tgt.name:24s} [{card['engine']}] "
              f"({wall:.1f}s, flops/round={c['flops_per_round']:.3g} "
              f"bytes/round={c['bytes_per_round']:.3g} "
              f"AI={c['arithmetic_intensity']:.2f} "
              f"pred={card['roofline']['predicted_steps_per_sec'] / 1e6:.1f}"
              f"M steps/s [{card['roofline']['bound']}])", flush=True)
        if update:
            path = model.save(card)
            print(f"costcheck: {tgt.name}: cost card written -> {path}",
                  flush=True)
            continue
        committed = model.load(tgt.name)
        if committed is None:
            print(f"costcheck: {tgt.name}: FAIL — no committed cost card "
                  f"({model.path_for(tgt.name)}); run "
                  f"`python -m tools.costmodel --update` and commit it",
                  flush=True)
            rc = 1
            continue
        diffs = model.diff(committed, card)
        if not diffs:
            continue
        if model.same_toolchain(committed):
            print(f"costcheck: {tgt.name}: FAIL — cost drift vs the "
                  f"committed card (same toolchain ⇒ a code change; rerun "
                  f"with --update if intentional):", flush=True)
            rc = 1
        else:
            print(f"costcheck: {tgt.name}: WARNING — cost drift under a "
                  f"DIFFERENT jax/jaxlib; FLOP/byte accounting churns "
                  f"across compilers. Diff:", flush=True)
        for line in diffs:
            print(line, flush=True)
    print(f"costcheck: {'FAILED' if rc else 'ok'} ({len(targets)} targets)",
          flush=True)
    return rc


def run_scale(update: bool = False) -> int:
    from . import model
    rows = model.scale_rows()
    table = model.scale_markdown(rows)
    print(table)
    if not update:
        return 0
    text = SCALE_DOC.read_text()
    if SCALE_BEGIN not in text or SCALE_END not in text:
        print(f"costcheck: {SCALE_DOC} has no "
              f"{SCALE_BEGIN}/{SCALE_END} markers", file=sys.stderr)
        return 1
    head, rest = text.split(SCALE_BEGIN, 1)
    _, tail = rest.split(SCALE_END, 1)
    SCALE_DOC.write_text(head + SCALE_BEGIN + "\n" + table + "\n"
                         + SCALE_END + tail)
    print(f"costcheck: scaling table rewritten in {SCALE_DOC}",
          file=sys.stderr)
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.costmodel",
        description="Compiled cost model: per-config cost cards + "
                    "roofline predictions (docs/OBSERVABILITY.md "
                    "§'Observatory').")
    ap.add_argument("--update", action="store_true",
                    help="regenerate the committed cost cards (or, with "
                         "--scale, rewrite the docs/SCALE.md table)")
    ap.add_argument("--only", action="append", default=None,
                    help="check only this target (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list registered targets")
    ap.add_argument("--scale", action="store_true",
                    help="print the predicted node-sharded scaling table "
                         "(N=500k/1M) from the committed cards")
    args = ap.parse_args(argv)

    if "jax" not in sys.modules:
        _setup_platform()
    try:
        import jax  # noqa: F401
    except ImportError:
        print("costcheck: SKIP — jax is not installed; the cost model "
              "needs the CPU backend to lower against (install jax[cpu] "
              "to enforce this layer)", file=sys.stderr)
        return 0

    if args.list:
        from tools.hlocheck import registry
        for t in registry.targets():
            print(t.name)
        return 0
    if args.scale:
        return run_scale(update=args.update)
    return run_checks(only=args.only, update=args.update)


if __name__ == "__main__":
    sys.exit(main())
