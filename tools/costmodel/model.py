"""Cost-card construction, drift diffing, and scaling projections.

A cost card is a normalized summary of what one compiled round program
COSTS, the way a fingerprint summarizes what it IS. Counting note
(mirrors ``tools/hlocheck/hlo.py``): the chunk program is ONE ``while``
loop whose body is the round kernel, and ``HloCostAnalysis`` visits
every instruction once — so module-wide FLOPs/bytes ARE per-round
figures for the round body, plus a fixed init/epilogue term that the
scan amortizes away at real round counts.

Roofline: a round cannot finish faster than its bytes at HBM peak nor
its FLOPs at compute peak, so

    predicted_round_s      = max(bytes / HBM_PEAK, flops / PEAK_FLOPS)
    predicted_steps_per_sec = steps_per_round / predicted_round_s

an UPPER bound on throughput (real rounds also pay dispatch, sort
passes re-touching memory, and host sync), which is exactly what makes
``measured / predicted`` in ``benchmarks/LEDGER.json`` a meaningful
efficiency ratio in [0, 1]-ish territory.
"""
from __future__ import annotations

import json
import pathlib
import sys
from typing import Any

_REPO = pathlib.Path(__file__).resolve().parents[2]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

COSTCARD_DIR = _REPO / "benchmarks" / "parts" / "costcards"

SCHEMA = 1

# Peaks of the chip the committed measurements ran on (TPU v5 lite /
# v5e). HBM bandwidth is shared with the benchmark suite's
# achieved-bandwidth column (one source of truth); the compute peak is
# the bf16 MXU figure — our kernels are u32/i32 VPU work far below it,
# so the roofline is bandwidth-bound at every registered config (the
# card records which bound bind so that claim is checkable, not
# asserted).
PEAK_FLOPS = 1.97e14  # v5e bf16 peak, FLOP/s

# Card top-level keys — the exactly-these-keys registry mirrored
# import-free in tools/validate_trace.py (COST_CARD_FIELDS) and synced
# both ways by the lint `registry` check, like the telemetry counters.
CARD_FIELDS = ("schema", "name", "engine", "chunk_rounds", "toolchain",
               "config", "cost", "roofline", "collectives")

# All-integer state discipline (docs/SPEC.md; the hlocheck dtype
# contract bans anything wider than 32 bits), so a collective operand
# element is at most 4 bytes — the census converts the fingerprint's
# element counts with this worst case.
MAX_ELEM_BYTES = 4


def path_for(name: str) -> pathlib.Path:
    return COSTCARD_DIR / f"{name}.json"


def hbm_peak_gbps() -> float:
    from benchmarks.run_benchmarks import HBM_PEAK_GBPS
    return float(HBM_PEAK_GBPS)


def _jax_versions() -> dict[str, str]:
    from tools.hlocheck import fingerprint
    return fingerprint._jax_versions()


def _cost_dict(compiled) -> dict[str, float]:
    """The module-level ``cost_analysis()`` properties (jax returns one
    dict per partition; single-partition programs have exactly one).
    Per-operand breakdown keys (``bytes accessed0{}``) are dropped —
    they churn with fusion decisions; the module totals are the stable
    layer."""
    ca = compiled.cost_analysis()
    d = ca[0] if isinstance(ca, (list, tuple)) else ca
    return {k: float(v) for k, v in d.items()
            if "{" not in k and isinstance(v, (int, float))}


def _steps_per_round(target) -> int:
    """Real node-steps one round of the target's program simulates —
    padded f-ladder lanes are FLOP waste, not simulated work, mirroring
    ``run_benchmarks.bench_pbft_fsweep``'s accounting."""
    cfg = target.cfg
    if target.fsweep:
        return cfg.n_sweeps * sum(3 * f + 1 for f in target.fsweep)
    return cfg.n_sweeps * cfg.n_nodes


def _compile_target(target):
    """Compile the target's production single-device program (the exact
    one the benchmarks dispatch; f-ladder targets compile the padded
    one-program sweep) and return the compiled executable."""
    import jax
    import jax.numpy as jnp

    from consensus_tpu.network import runner, simulator
    from tools.hlocheck import hlo

    if target.fsweep:
        from consensus_tpu.engines import pbft_sweep
        return pbft_sweep.fsweep_lower(target.cfg, target.fsweep).compile()
    eng = simulator.engine_def(target.cfg)
    carry = hlo.carry_struct(target.cfg, eng)
    r0 = jax.ShapeDtypeStruct((), jnp.int32)
    extra = hlo.flight_structs(target.cfg, eng) if target.flight else ()
    lowered = runner._chunk_jit.lower(
        target.cfg, eng, hlo.chunk_rounds(target.cfg), carry, r0, *extra,
        mesh=None)
    return lowered.compile()


def _collective_census(name: str) -> dict[str, Any]:
    """Per-device collective byte census of the target's meshed
    variants, read off the COMMITTED hlocheck fingerprint (the two
    artifacts are committed and drift-gated together, so re-lowering
    the mesh variants here would only pay the ~seconds again). Element
    counts convert at the 4-byte worst case the dtype contract
    guarantees."""
    from tools.hlocheck import fingerprint
    doc = fingerprint.load(name)
    if doc is None:
        return {}
    out: dict[str, Any] = {}
    for key, var in sorted(doc.get("variants", {}).items()):
        if not var.get("mesh"):
            continue
        census = {
            op: {"count": int(c["count"]),
                 "max_elems": int(c["max_elems"]),
                 "max_bytes": int(c["max_elems"]) * MAX_ELEM_BYTES}
            for op, c in sorted(var.get("collectives", {}).items())}
        out[key] = {"mesh": var["mesh"], "collectives": census}
    return out


def build_card(target) -> dict[str, Any]:
    """Lower + compile one registered target and assemble its card."""
    from consensus_tpu.network import simulator
    from tools.hlocheck import hlo

    compiled = _compile_target(target)
    costs = _cost_dict(compiled)
    flops = costs.get("flops", 0.0)
    nbytes = costs.get("bytes accessed", 0.0)
    steps = _steps_per_round(target)
    bw = hbm_peak_gbps() * 1e9
    round_s_bw = nbytes / bw if bw else 0.0
    round_s_fl = flops / PEAK_FLOPS
    round_s = max(round_s_bw, round_s_fl)
    card = {
        "schema": SCHEMA,
        "name": target.name,
        "engine": simulator.engine_def(target.cfg).name,
        "chunk_rounds": (target.cfg.n_rounds if target.fsweep
                         else hlo.chunk_rounds(target.cfg)),
        "toolchain": _jax_versions(),
        "config": json.loads(target.cfg.to_json()),
        "cost": {
            "flops_per_round": flops,
            "bytes_per_round": nbytes,
            "arithmetic_intensity": flops / nbytes if nbytes else 0.0,
            "steps_per_round": steps,
            "bytes_per_step": nbytes / steps if steps else 0.0,
            "transcendentals_per_round": costs.get("transcendentals", 0.0),
        },
        "roofline": {
            "hbm_peak_gbps": hbm_peak_gbps(),
            "peak_flops": PEAK_FLOPS,
            "bound": "compute" if round_s_fl > round_s_bw else "bandwidth",
            "predicted_round_s": round_s,
            "predicted_steps_per_sec": steps / round_s if round_s else 0.0,
        },
        "collectives": _collective_census(target.name),
    }
    assert tuple(card) == CARD_FIELDS, "card keys drifted from CARD_FIELDS"
    return card


def save(card: dict) -> pathlib.Path:
    path = path_for(card["name"])
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(card, indent=2, sort_keys=True) + "\n")
    return path


def load(name: str) -> dict | None:
    path = path_for(name)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def diff(committed: dict, current: dict) -> list[str]:
    """Field-path diff lines between a committed card and a freshly
    computed one (empty = no drift), via the fingerprint layer's shared
    walker — cost-card drift must read exactly like fingerprint drift.
    The whole card is structure (cost figures have no 'verdict' layer);
    toolchain tolerance is the caller's policy, same as fingerprints."""
    from tools.hlocheck.fingerprint import _walk_diff
    out: list[str] = []
    _walk_diff("", committed, current, out)
    return out


def same_toolchain(committed: dict) -> bool:
    from tools.hlocheck import fingerprint
    return fingerprint.same_toolchain(committed)


# --- scaling projection (the ROADMAP no-tunnel fallback) ---------------------

SCALE_NS = (100_000, 500_000, 1_000_000)
SCALE_DEVICES = (1, 8)
HBM_PER_DEVICE_BYTES = 16 * 1024**3  # v5e: 16 GB HBM per chip

# Targets whose engines declare a node-sharded claim (hlocheck
# contracts) — the ones a >1-chip mesh can actually scale on the node
# axis, and therefore the ones worth projecting past 100k nodes.
SCALE_TARGETS = ("raft-100k", "dpos-100k", "hotstuff-100k")


def _scaled_carry_bytes(cfg, n: int) -> int:
    import dataclasses

    from benchmarks.run_benchmarks import carry_nbytes
    changes: dict = {"n_nodes": n}
    if cfg.protocol in ("pbft", "hotstuff"):
        # BFT populations must be 3f+1: snap the projection point to
        # the nearest valid shape at or above n (the carry differs by
        # O(1) node rows — noise at these scales).
        f = -(-(n - 1) // 3)
        changes.update(f=f, n_nodes=3 * f + 1)
    return carry_nbytes(dataclasses.replace(cfg, **changes))


def _collective_bytes_per_round(card: dict) -> int:
    """Worst-case per-device collective bytes per round across the
    card's meshed variants (0 when the engine's claim is collective-free
    — dpos — or no mesh variant is registered)."""
    worst = 0
    for var in card.get("collectives", {}).values():
        total = sum(c["count"] * c["max_bytes"]
                    for c in var["collectives"].values())
        worst = max(worst, total)
    return worst


def scale_rows(names=SCALE_TARGETS) -> list[dict[str, Any]]:
    """Predicted node-sharded scaling rows from the committed cards.

    The per-round cost of every node-sharded engine is O(N) (the capped
    raft round is O(A·N + N·L), dpos O(N + C log C) — docs/SCALE.md),
    so bytes/round scale linearly from the card's measured-shape figure;
    a D-device node shard divides the state traffic by D and adds the
    per-device collective census (also O(N) by contract, scaled the
    same way). Projections assume the config's flagship sweep count.
    """
    from tools.hlocheck import registry
    rows = []
    for name in names:
        card = load(name)
        if card is None:
            raise FileNotFoundError(
                f"no committed cost card for {name!r}; run "
                f"`python -m tools.costmodel --update` first")
        tgt = registry.target(name)
        cfg = tgt.cfg
        n0 = cfg.n_nodes
        bytes0 = card["cost"]["bytes_per_round"]
        flops0 = card["cost"]["flops_per_round"]
        coll0 = _collective_bytes_per_round(card)
        bw = card["roofline"]["hbm_peak_gbps"] * 1e9
        for n in SCALE_NS:
            ratio = n / n0
            carry = _scaled_carry_bytes(cfg, n)
            for d in SCALE_DEVICES:
                # The collective census only exists on a mesh: the d=1
                # row IS the committed single-device roofline (the card
                # LEDGER's measured/predicted is computed against).
                coll = coll0 * ratio if d > 1 else 0.0
                bpd = bytes0 * ratio / d + coll
                fpd = flops0 * ratio / d
                round_s = max(bpd / bw, fpd / PEAK_FLOPS)
                rows.append({
                    "name": name,
                    "engine": card["engine"],
                    "n_nodes": n,
                    "n_sweeps": cfg.n_sweeps,
                    "devices": d,
                    "carry_bytes": carry,
                    "carry_bytes_per_device": carry // d,
                    "fits_hbm": carry // d <= HBM_PER_DEVICE_BYTES,
                    "bytes_per_round_per_device": bpd,
                    "predicted_steps_per_sec": cfg.n_sweeps * n / round_s,
                })
    return rows


def scale_markdown(rows: list[dict[str, Any]]) -> str:
    """The docs/SCALE.md projection table (see __main__ --scale)."""
    out = ["| config | N | devices | carry/device | bytes/round/device "
           "| predicted steps/s | fits HBM |",
           "|---|---|---|---|---|---|---|"]
    for r in rows:
        out.append(
            f"| {r['name']} | {r['n_nodes'] // 1000}k | {r['devices']} "
            f"| {r['carry_bytes_per_device'] / 1e9:.2f} GB "
            f"| {r['bytes_per_round_per_device'] / 1e9:.2f} GB "
            f"| {r['predicted_steps_per_sec'] / 1e6:.0f}M "
            f"| {'yes' if r['fits_hbm'] else 'NO'} |")
    return "\n".join(out)
