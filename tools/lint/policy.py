"""Scope and exemption policy shared by the purity and dtype checks.

The engines/ and ops/ packages are DEVICE code by default: every
function in them is assumed to be (part of) a jit-traced scan body and
must satisfy the purity and dtype disciplines. The handful of genuinely
host-side functions that live next to their kernels — measurement
harnesses, extraction epilogues — are exempted HERE, by name, so adding
host-side code to an engine file is an explicit, reviewed act rather
than something the lint silently tolerates (docs/STATIC_ANALYSIS.md).
"""
from __future__ import annotations

# Directories (repo-relative) whose functions are device code.
DEVICE_SCOPE = ("consensus_tpu/engines", "consensus_tpu/ops")

# path -> function names that are host-side by design. Rationale:
#   pbft_sweep: the f-ladder timing harness + host-side slice/payload
#     epilogues (wall clocks, device->host pulls) — the ladder's traced
#     body is pbft_round_padded/_fsweep_jit, which stay checked;
#   dpos: lib_index is the SPEC §7 LIB extraction epilogue (host numpy,
#     deliberately int64 — accumulation past i32 is fine off-device),
#     dpos_run wraps runner.run around it.
HOST_EXEMPT = {
    "consensus_tpu/engines/pbft_sweep.py": frozenset({
        "pbft_fsweep_timed", "_fsweep_slice", "_fsweep_device",
        "fsweep_payload", "rung_payloads", "pbft_fsweep_run",
        # Host-side ladder validation + static compile parameters
        # (padded config, bcast table width) shared by the dispatch
        # path and hlocheck's trace-time lowering — all inputs are
        # host ints/Config, nothing is traced.
        "_fsweep_static", "fsweep_lower"}),
    "consensus_tpu/engines/dpos.py": frozenset({"lib_index", "dpos_run"}),
}


def device_files(repo) -> list[str]:
    out: list[str] = []
    for d in DEVICE_SCOPE:
        out.extend(repo.glob(f"{d}/*.py"))
    return out


def exempt(rel: str, fn_name: str) -> bool:
    return fn_name in HOST_EXEMPT.get(rel, frozenset())
