"""CLI entry: ``python -m tools.lint [--check NAME ...] [--root DIR]``.

Prints one line per violation and exits 1 when any check fails —
the shape `make check` and tests/test_static_analysis.py consume.
Stdlib-only; never imports jax or the framework.
"""
from __future__ import annotations

import argparse
import pathlib
import sys

from . import CHECKS, run_checks


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="Repo-specific static checks for the determinism & "
                    "parity invariants (docs/STATIC_ANALYSIS.md).")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="run only this check (repeatable; default: all)")
    ap.add_argument("--root", default="",
                    help="repo root (default: two levels above this "
                         "package)")
    args = ap.parse_args(argv)
    root = pathlib.Path(args.root) if args.root else \
        pathlib.Path(__file__).resolve().parents[2]
    violations = run_checks(root, only=args.check)
    for v in violations:
        print(f"consensus-lint: {v}", file=sys.stderr)
    names = ", ".join(args.check) if args.check else "all checks"
    if violations:
        print(f"consensus-lint: FAILED ({len(violations)} violations, "
              f"{names})", file=sys.stderr)
        return 1
    print(f"consensus-lint: ok ({names})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
