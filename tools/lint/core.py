"""Shared infrastructure for the lint checks: a parse-caching repo view
and the Violation record. Stdlib-only (ast + pathlib) by design — the
lint must run in CI without importing jax or the framework."""
from __future__ import annotations

import ast
import dataclasses
import pathlib


@dataclasses.dataclass(frozen=True)
class Violation:
    check: str
    path: str      # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


class Repo:
    """A repo root with cached file reads and AST parses. Checks address
    files by repo-relative POSIX path, which is what lets the fixture
    trees under tests/fixtures/lint/ stand in for the real repo."""

    def __init__(self, root) -> None:
        self.root = pathlib.Path(root)
        self._text: dict[str, str] = {}
        self._ast: dict[str, ast.Module] = {}

    def exists(self, rel: str) -> bool:
        return (self.root / rel).is_file()

    def read(self, rel: str) -> str:
        if rel not in self._text:
            self._text[rel] = (self.root / rel).read_text()
        return self._text[rel]

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._ast:
            self._ast[rel] = ast.parse(self.read(rel), filename=rel)
        return self._ast[rel]

    def glob(self, pattern: str) -> list[str]:
        return sorted(p.relative_to(self.root).as_posix()
                      for p in self.root.glob(pattern) if p.is_file())

    def missing(self, check: str, rel: str) -> Violation:
        return Violation(check, rel, 0, "required file is missing")


def dotted(node: ast.AST) -> tuple[str, ...]:
    """The name chain of a Name/Attribute expression, outermost first:
    ``np.random.rand`` -> ("np", "random", "rand"). Empty for anything
    rooted in a non-Name (call results, subscripts, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def literal_str_tuple(node: ast.AST, env: dict[str, tuple]) -> tuple | None:
    """Evaluate a tuple-of-strings expression that may concatenate Name
    references resolved through ``env`` (the `("a", "b") + CRASH_TELEMETRY`
    idiom). Returns None when the expression has any other shape."""
    if isinstance(node, ast.Tuple):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = literal_str_tuple(node.left, env)
        right = literal_str_tuple(node.right, env)
        if left is not None and right is not None:
            return left + right
    return None


def assigned_names(target: ast.AST) -> list[str]:
    """Plain Name targets of an assignment target (tuples flattened;
    attribute/subscript targets are ignored)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(assigned_names(elt))
        return out
    return []
