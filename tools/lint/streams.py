"""Check `streams`: the counter-RNG stream registry and its call sites.

Every random decision in the simulator draws from a per-purpose STREAM_*
constant (docs/SPEC.md §1). Two silent failure modes motivate this
check:

  * a stream-constant COLLISION (two purposes keyed identically) makes
    logically-independent adversary events correlated across features —
    no test notices until a scenario happens to co-activate both;
  * an absorb-key ARITY drift — a call site varying a key slot the
    stream's definition pins to a constant (or vice versa) — reuses
    counter space another draw owns, the same correlation bug in
    different clothes.

core/rng.py therefore carries a machine-checked registry:

    STREAM_KEYS = {"STREAM_TIMEOUT": ("term", None, "node"), ...}

naming, for each stream, what each of the three absorb slots
(ctx, c0, c1) keys — `None` meaning "pinned: every call site must pass
a literal constant". This check enforces:

  1. every STREAM_* constant is registered in STREAM_KEYS and vice
     versa, and all constant values are unique;
  2. every threefry call site (draw/_draw/random_u32_*) uses a
     registered stream and passes literal constants in pinned slots;
  3. mixer-only streams (STREAM_MIXER_ONLY — the SPEC §2 delivery
     stream) are never drawn through the threefry entry points;
  4. the C++ mirror (cpp/threefry.h) defines the same constants with
     the same values — minus STREAM_TPU_ONLY (e.g. STREAM_ATTACK: the
     SPEC §A.3 targeted Raft attacks are not implemented by the
     oracle, and Config rejects them on engine="cpu").

Scope: call sites across consensus_tpu/ only. tests/ and benchmarks/
deliberately drive raw streams for cross-validation and ablations.
"""
from __future__ import annotations

import ast
import re

from .core import Repo, Violation, dotted

CHECK = "streams"

RNG = "consensus_tpu/core/rng.py"
CPP_MIRROR = "cpp/threefry.h"
DRAW_FNS = {"draw", "_draw", "random_u32_np", "random_u32_jnp"}
_CPP_RE = re.compile(
    r"\bSTREAM_([A-Z_0-9]+)\s*=\s*0[xX]([0-9A-Fa-f]+)u?")


def _parse_rng(repo: Repo):
    """(streams: name->(value, line), keys: name->3-tuple,
    tpu_only: set, mixer_only: set, violations)."""
    errs: list[Violation] = []
    streams: dict[str, tuple[int, int]] = {}
    keys: dict[str, tuple] = {}
    tpu_only: set[str] = set()
    mixer_only: set[str] = set()
    tree = repo.tree(RNG)
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        name = node.targets[0].id
        if name in ("STREAM_KEYS", "STREAM_TPU_ONLY", "STREAM_MIXER_ONLY"):
            pass  # registry/exemption declarations, handled below
        elif name.startswith("STREAM_") and isinstance(node.value, ast.Call):
            chain = dotted(node.value.func)
            if chain[-1:] == ("uint32",) and node.value.args \
                    and isinstance(node.value.args[0], ast.Constant):
                streams[name] = (int(node.value.args[0].value), node.lineno)
        if name == "STREAM_KEYS" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(v, ast.Tuple) and len(v.elts) == 3
                        and all(isinstance(e, ast.Constant)
                                for e in v.elts)):
                    errs.append(Violation(
                        CHECK, RNG, node.lineno,
                        "STREAM_KEYS entries must be 'STREAM_X': "
                        "(ctx, c0, c1) literal 3-tuples (None = pinned "
                        "slot)"))
                    continue
                keys[k.value] = tuple(e.value for e in v.elts)
        elif name in ("STREAM_TPU_ONLY", "STREAM_MIXER_ONLY"):
            found: set[str] = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    found.add(sub.value)
            (tpu_only if name == "STREAM_TPU_ONLY" else mixer_only) \
                .update(found)
    return streams, keys, tpu_only, mixer_only, errs


def _registry_violations(streams, keys, tpu_only, mixer_only) -> list:
    errs = []
    for name, (_, line) in streams.items():
        if name not in keys:
            errs.append(Violation(
                CHECK, RNG, line,
                f"{name} has no STREAM_KEYS entry — declare its absorb-key "
                "slots (docs/STATIC_ANALYSIS.md)"))
    for name in keys:
        if name not in streams:
            errs.append(Violation(
                CHECK, RNG, 0,
                f"STREAM_KEYS entry {name} has no STREAM constant"))
    for extra in (tpu_only | mixer_only) - set(streams):
        errs.append(Violation(
            CHECK, RNG, 0,
            f"declared exemption {extra} is not a defined stream"))
    by_value: dict[int, list[str]] = {}
    for name, (value, _) in streams.items():
        by_value.setdefault(value, []).append(name)
    for value, names in sorted(by_value.items()):
        if len(names) > 1:
            line = min(streams[n][1] for n in names)
            errs.append(Violation(
                CHECK, RNG, line,
                f"stream constant collision: {', '.join(sorted(names))} all "
                f"= 0x{value:08X} — colliding streams silently correlate "
                "independent adversary events"))
    return errs


def _cpp_violations(repo: Repo, streams, tpu_only) -> list:
    if not repo.exists(CPP_MIRROR):
        return [repo.missing(CHECK, CPP_MIRROR)]
    cpp = {"STREAM_" + m.group(1): int(m.group(2), 16)
           for m in _CPP_RE.finditer(repo.read(CPP_MIRROR))}
    errs = []
    for name, (value, line) in sorted(streams.items()):
        if name in tpu_only:
            if name in cpp:
                errs.append(Violation(
                    CHECK, RNG, line,
                    f"{name} is declared STREAM_TPU_ONLY but {CPP_MIRROR} "
                    "defines it — drop the stale exemption"))
            continue
        if name not in cpp:
            errs.append(Violation(
                CHECK, RNG, line,
                f"{name} missing from {CPP_MIRROR} (or declare it "
                "STREAM_TPU_ONLY if the oracle must not mirror it)"))
        elif cpp[name] != value:
            errs.append(Violation(
                CHECK, RNG, line,
                f"{name} = 0x{value:08X} here but 0x{cpp[name]:08X} in "
                f"{CPP_MIRROR} — the engines would draw different streams"))
    for name in sorted(set(cpp) - set(streams)):
        errs.append(Violation(
            CHECK, CPP_MIRROR, 0,
            f"{name} defined in the C++ mirror but not in {RNG}"))
    return errs


# The shared signature of every threefry entry point:
#   draw(seed, stream, ctx, c0, c1)  /  random_u32_*(seed, stream, ctx, c0, c1)
_SLOT_NAMES = ("ctx", "c0", "c1")
_SLOT_POS = {"ctx": 2, "c0": 3, "c1": 4}


def _stream_aliases(tree: ast.Module) -> dict[str, str]:
    """Local names bound to a STREAM_* constant anywhere in the module
    (`s = rng.STREAM_CHURN`) — so aliasing a stream cannot bypass the
    call-site checks."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            chain = dotted(node.value)
            if chain and chain[-1].startswith("STREAM_"):
                out[node.targets[0].id] = chain[-1]
    return out


def _resolve_stream(arg: ast.AST, aliases: dict[str, str]) -> str | None:
    chain = dotted(arg)
    if not chain:
        return None
    if chain[-1].startswith("STREAM_"):
        return chain[-1]
    if len(chain) == 1:
        return aliases.get(chain[0])
    return None


def _slot_args(node: ast.Call) -> dict[str, ast.AST | None]:
    """The (ctx, c0, c1) argument expressions of a draw call, whether
    passed positionally or by keyword; None when absent/unresolvable
    (callers flag pinned slots they cannot see — never skip silently)."""
    out: dict[str, ast.AST | None] = {s: None for s in _SLOT_NAMES}
    for slot, pos in _SLOT_POS.items():
        if len(node.args) > pos:
            out[slot] = node.args[pos]
    for kw in node.keywords:
        if kw.arg in out:
            out[kw.arg] = kw.value
    return out


def _call_site_violations(repo: Repo, keys, mixer_only) -> list:
    errs = []
    for rel in repo.glob("consensus_tpu/**/*.py"):
        if rel == RNG:
            continue  # the registry's own module builds the generic keys
        tree = repo.tree(rel)
        aliases = _stream_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if not chain or chain[-1] not in DRAW_FNS:
                continue
            sarg = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "stream"),
                None)
            stream = _resolve_stream(sarg, aliases) if sarg is not None \
                else None
            if stream is None:
                continue  # generic pass-through (a `stream` parameter)
            if stream in mixer_only:
                errs.append(Violation(
                    CHECK, rel, node.lineno,
                    f"{stream} is mixer-only (SPEC §2 delivery): draw it "
                    "through delivery_u32_*, not the threefry entry points"))
                continue
            if stream not in keys:
                errs.append(Violation(
                    CHECK, rel, node.lineno,
                    f"call site uses unregistered stream {stream} — add a "
                    f"STREAM_KEYS entry in {RNG}"))
                continue
            slots = _slot_args(node)
            for i, slot in enumerate(_SLOT_NAMES):
                if keys[stream][i] is None and not isinstance(
                        slots[slot], ast.Constant):
                    errs.append(Violation(
                        CHECK, rel, node.lineno,
                        f"{stream} pins absorb slot {slot} (STREAM_KEYS "
                        "declares it None) but this call site passes a "
                        "non-literal (or unrecognizable) argument — "
                        "counter-space reuse correlates draws across "
                        "purposes"))
    return errs


def check(repo: Repo) -> list[Violation]:
    if not repo.exists(RNG):
        return [repo.missing(CHECK, RNG)]
    streams, keys, tpu_only, mixer_only, errs = _parse_rng(repo)
    if not streams:
        errs.append(Violation(CHECK, RNG, 0, "no STREAM_* constants found"))
        return errs
    errs += _registry_violations(streams, keys, tpu_only, mixer_only)
    errs += _cpp_violations(repo, streams, tpu_only)
    errs += _call_site_violations(repo, keys, mixer_only)
    return errs
