"""Check `purity`: engine round/scan bodies must be traceable-pure.

Every function in the device scope (engines/, ops/ — see policy.py) is
jit-traced under vmap/scan. Four classes of construct silently break
the determinism/parity contract when they sneak into such a body:

  * host callbacks and host effects — jax.debug.*, pure_callback,
    io_callback, host_callback, print/open/input: side channels the
    C++ oracle cannot mirror;
  * wall clocks and stateful RNG — time.*, random.*, np.random.*: the
    counter-RNG discipline (docs/SPEC.md §1) is the ONLY randomness
    allowed, precisely because it has no shared iteration order;
  * Python coercions of traced values — float(x)/int(x)/bool(x),
    x.item(), np.asarray(x): force a trace-time concretization (an
    error under jit at best, a silently-baked constant at worst);
  * data-dependent Python branching — `if`/`while`/ternary on a traced
    value: the branch would be resolved at TRACE time from an abstract
    value, diverging from the oracle's per-element semantics. Static
    config branches (`if cfg.crash_cutoff > 0:`) are the approved
    idiom and stay allowed.

Taint rule (documented in docs/STATIC_ANALYSIS.md): positional
parameters are traced unless annotated `int`/`bool`/`float`/`str` or
named `cfg`/`self`; keyword-only parameters are static switches; a
local becomes traced when assigned from an expression referencing a
traced name — except through `.shape`/`.ndim`/`.dtype`/`.size`/`len()`
(array METADATA is static under jit). `x is None` tests are trace-time
static and exempt.
"""
from __future__ import annotations

import ast

from .core import Violation, assigned_names, dotted
from . import policy

CHECK = "purity"

STATIC_ANNOTATIONS = {"int", "bool", "float", "str"}
STATIC_PARAMS = {"cfg", "self"}
META_ATTRS = {"shape", "ndim", "dtype", "size"}

BANNED_ROOTS = {"time", "random"}
BANNED_PREFIXES = (("np", "random"), ("numpy", "random"), ("jax", "debug"))
BANNED_ATTRS = {"pure_callback", "io_callback", "host_callback"}
BANNED_CALLS = {"print", "input", "open", "breakpoint", "exec", "eval"}
COERCIONS = {"float", "int", "bool"}
HOST_PULL_ATTRS = {"item", "tolist"}


def _is_none_test(test: ast.AST) -> bool:
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops))


class _FnChecker:
    def __init__(self, rel: str, fn: ast.FunctionDef) -> None:
        self.rel = rel
        self.fn = fn
        self.violations: list[Violation] = []
        self.tainted: set[str] = set()
        self._seed_params(fn)

    def _seed_params(self, fn) -> None:
        """Seed traced params of a def OR a lambda (lambdas are the
        lax.cond/vmap-body idiom, so their params are traced too)."""
        for a in fn.args.args + fn.args.posonlyargs:
            if a.arg in STATIC_PARAMS:
                continue
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id in STATIC_ANNOTATIONS:
                continue
            self.tainted.add(a.arg)
        if fn.args.vararg:
            self.tainted.add(fn.args.vararg.arg)
        # Keyword-only params are Python-level switches (telem=False).

    # --- taint ---------------------------------------------------------

    def taint(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in META_ATTRS:
                return False
            return self.taint(node.value)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id == "len":
                return False
            parts = [node.func] + list(node.args) \
                + [kw.value for kw in node.keywords]
            return any(self.taint(p) for p in parts)
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Lambda):
            return False
        return any(self.taint(c) for c in ast.iter_child_nodes(node))

    def _propagate(self) -> None:
        """Fixpoint taint propagation over all assignments (order-free:
        two passes suffice for the straight-line kernel style; a third
        guards deeper chains)."""
        for _ in range(3):
            before = len(self.tainted)
            for node in ast.walk(self.fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and node is not self.fn:
                    self._seed_params(node)  # nested defs/lambdas: traced
                targets: list[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                elif isinstance(node, ast.comprehension):
                    targets, value = [node.target], node.iter
                if value is not None and self.taint(value):
                    for t in targets:
                        self.tainted.update(assigned_names(t))
            if len(self.tainted) == before:
                break

    # --- violations ----------------------------------------------------

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.violations.append(
            Violation(CHECK, self.rel, getattr(node, "lineno", 0),
                      f"{self.fn.name}: {msg}"))

    def _check_call(self, node: ast.Call) -> None:
        chain = dotted(node.func)
        if chain:
            if chain[0] in BANNED_ROOTS:
                self._flag(node, f"host call {'.'.join(chain)}() — wall "
                                 "clocks / stateful RNG cannot appear in a "
                                 "traced scan body")
            for pref in BANNED_PREFIXES:
                if chain[:len(pref)] == pref:
                    self._flag(node, f"host callback/RNG "
                                     f"{'.'.join(chain)}() in a scan body")
            if chain[-1] in BANNED_ATTRS:
                self._flag(node, f"host callback {'.'.join(chain)}() in a "
                                 "scan body")
            if len(chain) == 1 and chain[0] in BANNED_CALLS:
                self._flag(node, f"host-side {chain[0]}() in a scan body")
            if len(chain) == 1 and chain[0] in COERCIONS \
                    and any(self.taint(a) for a in node.args):
                self._flag(node, f"{chain[0]}() coercion of a traced value "
                                 "(concretizes at trace time)")
            if chain[0] in ("np", "numpy") \
                    and chain[-1] in ("asarray", "array") \
                    and any(self.taint(a) for a in node.args):
                self._flag(node, f"{'.'.join(chain)}() host materialization "
                                 "of a traced value")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in HOST_PULL_ATTRS:
            self._flag(node, f".{node.func.attr}() host pull in a scan body")

    def run(self) -> list[Violation]:
        self._propagate()
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
                if self.taint(node.test) and not _is_none_test(node.test):
                    kind = ("ternary" if isinstance(node, ast.IfExp)
                            else "branch")
                    self._flag(node, f"data-dependent Python {kind} on a "
                                     "traced value (use jnp.where / "
                                     "lax.select)")
            elif isinstance(node, ast.Assert):
                if self.taint(node.test):
                    self._flag(node, "assert on a traced value")
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names] \
                    if isinstance(node, ast.Import) else [node.module or ""]
                for m in mods:
                    if m.split(".")[0] in BANNED_ROOTS:
                        self._flag(node, f"import of {m} inside a scan body")
        return self.violations


def _banned_calls_only(rel: str, where: str, node: ast.AST) -> list:
    """Host-call scan for module/class-level statements (no parameters,
    so no taint — but a wall clock or stateful-RNG call at import time
    is just as banned)."""
    errs: list[Violation] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        chain = dotted(sub.func)
        if not chain:
            continue
        name = ".".join(chain)
        if chain[0] in BANNED_ROOTS \
                or any(chain[:len(p)] == p for p in BANNED_PREFIXES) \
                or chain[-1] in BANNED_ATTRS \
                or (len(chain) == 1 and chain[0] in BANNED_CALLS):
            errs.append(Violation(
                CHECK, rel, sub.lineno,
                f"{where}: host call {name}() in device scope"))
    return errs


def check(repo) -> list[Violation]:
    out: list[Violation] = []
    for rel in policy.device_files(repo):
        tree = repo.tree(rel)
        fns: list[ast.FunctionDef] = []
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                fns.append(node)
            elif isinstance(node, ast.ClassDef):
                for n in node.body:
                    if isinstance(n, ast.FunctionDef):
                        fns.append(n)
                    else:  # class-level statements are device scope too
                        out.extend(_banned_calls_only(
                            rel, f"class {node.name}", n))
            elif not isinstance(node, (ast.Import, ast.ImportFrom)):
                out.extend(_banned_calls_only(rel, "module level", node))
        for fn in fns:
            if policy.exempt(rel, fn.name):
                continue
            out.extend(_FnChecker(rel, fn).run())
    return out
