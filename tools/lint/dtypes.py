"""Check `dtypes`: u32/i32 storage discipline in the device packages.

The C++ oracle is uint32 end to end and digests are byte-compares of
32-bit records, so dtype parity in engines/ and ops/ is load-bearing
(docs/SPEC.md; engines narrow further to u8/u16 where a bound permits —
value-identical, see raft._store_dtype). Two drift vectors are checked:

  * 64-bit dtype references — jnp/np `int64`/`float64` (and their
    string spellings in dtype= positions): under TPU x64-disabled jax
    they silently downcast; under numpy they widen host-side math away
    from the oracle's u32 wraparound semantics;
  * dtype-DEFAULTED array constructors — `jnp.zeros(n)`,
    `jnp.arange(n)`, `jnp.eye(n)` invent float32/int32 defaults that
    jax version bumps or x64 flags can move. Every zeros/ones/empty/
    full/eye/arange in device code must state its dtype; jnp.array/
    jnp.asarray must state one when building from a Python literal
    (an array argument already carries its dtype).

Host-side epilogue functions (policy.HOST_EXEMPT, e.g. dpos.lib_index's
deliberately-int64 accumulation) are exempt — they are neither traced
nor oracle-paired.
"""
from __future__ import annotations

import ast

from .core import Violation, dotted
from . import policy

CHECK = "dtypes"

BANNED_64 = {"int64", "float64"}
# func name -> index of an acceptable positional dtype argument
# (None = dtype must be a keyword at this arity).
NEED_DTYPE = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
              "eye": None, "arange": None}
LITERAL_NEED_DTYPE = {"array", "asarray"}


def _has_dtype(call: ast.Call, pos) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return pos is not None and len(call.args) > pos


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal(e) for e in node.elts)
    return False


def _check_node(rel: str, fn_name: str, node: ast.AST) -> list[Violation]:
    errs: list[Violation] = []
    where = f"{fn_name}: " if fn_name else ""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in BANNED_64:
            chain = dotted(sub)
            if chain and chain[0] in ("jnp", "np", "numpy", "jax"):
                errs.append(Violation(
                    CHECK, rel, sub.lineno,
                    f"{where}{'.'.join(chain)} — 64-bit dtypes break u32 "
                    "parity with the C++ oracle (docs/SPEC.md)"))
        elif isinstance(sub, ast.Constant) and sub.value in BANNED_64:
            errs.append(Violation(
                CHECK, rel, sub.lineno,
                f"{where}dtype string {sub.value!r} — 64-bit dtypes break "
                "u32 parity with the C++ oracle"))
        elif isinstance(sub, ast.Call):
            chain = dotted(sub.func)
            if len(chain) == 2 and chain[0] == "jnp":
                name = chain[1]
                if name in NEED_DTYPE \
                        and not _has_dtype(sub, NEED_DTYPE[name]):
                    errs.append(Violation(
                        CHECK, rel, sub.lineno,
                        f"{where}jnp.{name}(...) without an explicit dtype "
                        "— defaulted dtypes drift with jax flags/versions; "
                        "state the storage width"))
                elif name in LITERAL_NEED_DTYPE and sub.args \
                        and _is_literal(sub.args[0]) \
                        and not _has_dtype(sub, 1):
                    errs.append(Violation(
                        CHECK, rel, sub.lineno,
                        f"{where}jnp.{name}(<literal>) without an explicit "
                        "dtype — a Python literal has no width; state it"))
    return errs


def check(repo) -> list[Violation]:
    out: list[Violation] = []
    for rel in policy.device_files(repo):
        tree = repo.tree(rel)
        for node in tree.body:
            fns: list[ast.FunctionDef] = []
            if isinstance(node, ast.FunctionDef):
                fns = [node]
            elif isinstance(node, ast.ClassDef):
                for n in node.body:
                    if isinstance(n, ast.FunctionDef):
                        fns.append(n)
                    else:  # class-level constants are device scope too
                        out.extend(_check_node(rel, node.name, n))
            else:
                out.extend(_check_node(rel, "", node))
                continue
            for fn in fns:
                if policy.exempt(rel, fn.name):
                    continue
                out.extend(_check_node(rel, fn.name, fn))
    return out
