"""Check `registry`: cross-file registries that must not drift.

Two registries pair engine code with validators that deliberately do
not import it:

  * TELEMETRY — each engine's on-device counter names (the *_TELEMETRY
    tuples registered as EngineDef.telemetry_names) versus the
    import-free known-name registry in tools/validate_trace.py
    (TELEMETRY_COUNTERS). A name in one but not the other means the
    schema tripwire and the engines have drifted: the validator would
    reject fresh CLI reports (or silently accept unknown ones).

  * FIELD REGISTRIES — the Observatory's producer-side exactly-these-
    keys declarations versus the validator's import-free mirrors:
    tools/costmodel/model.py CARD_FIELDS ↔ validate_trace
    COST_CARD_FIELDS, and tools/ledger.py ROW_FIELDS ↔ validate_trace
    LEDGER_ROW_FIELDS. Drift in either direction means the schema
    tripwire rejects fresh artifacts or silently accepts stale ones —
    the same failure mode as the telemetry counters.

  * CRASH_SPLIT — SPEC §6c requires every engine to partition its carry
    into persistent state (survives a crash; what the protocol's safety
    argument rests on) and volatile state (reset on recovery). The
    split used to live only in each round function's reset code; each
    engine now DECLARES it:

        CRASH_SPLIT = {"term": "persistent", "role": "volatile",
                       "seed": "meta", ...}

    and this check verifies the declaration against the actual code:
    keys cover the state NamedTuple exactly; the fields reset on the
    recovery mask (`x = jnp.where(rec, ...)`) are exactly the declared
    volatile set; and when the round freezes down nodes
    (freeze_down/_freeze), the frozen tuple covers exactly the
    persistent+volatile fields ("meta" fields — the seed, the down mask
    itself, and slot-lifecycle state with its own management — stay
    outside). A wrong declaration OR a reset-code change without a
    declaration update fails here, not in a crash-churn scenario three
    PRs later.
"""
from __future__ import annotations

import ast

from .core import Repo, Violation, assigned_names, dotted, literal_str_tuple

CHECK = "registry"

ENGINES_GLOB = "consensus_tpu/engines/*.py"
ADVERSARY = "consensus_tpu/ops/adversary.py"
AGGREGATE = "consensus_tpu/ops/aggregate.py"
VIEWSYNC = "consensus_tpu/ops/viewsync.py"
VALIDATOR = "tools/validate_trace.py"
SPLIT_KINDS = {"persistent", "volatile", "meta"}
FREEZE_FNS = {"freeze_down", "_freeze"}


# --- telemetry -------------------------------------------------------------

def _module_str_tuples(tree: ast.Module, env: dict) -> dict[str, tuple]:
    out: dict[str, tuple] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = literal_str_tuple(node.value, env | out)
            if val is not None:
                out[node.targets[0].id] = val
    return out


def _validator_registry(repo: Repo, var: str) -> tuple[set, int] | None:
    for node in repo.tree(VALIDATOR).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == var:
            names = {c.value for c in ast.walk(node.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)}
            return names, node.lineno
    return None


def _names_violations(repo: Repo, *, suffix: str, var: str, kind: str,
                      extra_env: tuple[str, ...] = ()) -> list[Violation]:
    """Two-way sync of the engines' ``*_{suffix}`` name tuples against
    the import-free ``{var}`` registry in tools/validate_trace.py —
    shared by the telemetry-counter and flight-recorder-latency
    registries (both drift the same way: a renamed engine name makes
    the validator reject fresh CLI reports, a stale registry entry
    silently matches nothing)."""
    if not repo.exists(VALIDATOR):
        return [repo.missing(CHECK, VALIDATOR)]
    got = _validator_registry(repo, var)
    if got is None:
        return [Violation(CHECK, VALIDATOR, 0,
                          f"no {var} registry found")]
    registry, reg_line = got
    env: dict[str, tuple] = {}
    for shared in (ADVERSARY, AGGREGATE, VIEWSYNC):
        if repo.exists(shared):
            env.update(_module_str_tuples(repo.tree(shared), {}))
    engine_names: set[str] = set()
    errs: list[Violation] = []
    for rel in repo.glob(ENGINES_GLOB):
        tuples = _module_str_tuples(repo.tree(rel), env)
        for name, val in tuples.items():
            if name.endswith(suffix):
                engine_names.update(val)
                for counter in val:
                    if counter not in registry:
                        errs.append(Violation(
                            CHECK, rel, 0,
                            f"{kind} {counter!r} ({name}) is "
                            f"missing from {VALIDATOR} {var} "
                            "— the CLI-report tripwire would reject it"))
    for key in extra_env:
        engine_names.update(env.get(key, ()))
    for counter in sorted(registry - engine_names):
        errs.append(Violation(
            CHECK, VALIDATOR, reg_line,
            f"{var} entry {counter!r} is reported by no "
            "engine — stale registry entry"))
    return errs


def _telemetry_violations(repo: Repo) -> list[Violation]:
    return _names_violations(repo, suffix="TELEMETRY",
                             var="TELEMETRY_COUNTERS",
                             kind="telemetry counter",
                             extra_env=("CRASH_TELEMETRY",))


def _latency_violations(repo: Repo) -> list[Violation]:
    return _names_violations(repo, suffix="LATENCY",
                             var="LATENCY_HISTOGRAMS",
                             kind="latency histogram")


# --- Observatory field registries ------------------------------------------

# (producer file, producer tuple name, validator frozenset name)
FIELD_REGISTRIES = (
    ("tools/costmodel/model.py", "CARD_FIELDS", "COST_CARD_FIELDS"),
    ("tools/ledger.py", "ROW_FIELDS", "LEDGER_ROW_FIELDS"),
    ("tools/advsearch/search.py", "FINDING_FIELDS", "FINDING_FIELDS"),
    ("consensus_tpu/service/jobs.py", "JOB_REPORT_FIELDS",
     "SERVICE_JOB_FIELDS"),
)


def _fields_violations(repo: Repo) -> list[Violation]:
    """Two-way sync of the producers' exactly-these-keys tuples against
    the import-free mirrors in tools/validate_trace.py."""
    errs: list[Violation] = []
    for producer, tup_name, var in FIELD_REGISTRIES:
        if not repo.exists(producer):
            errs.append(repo.missing(CHECK, producer))
            continue
        declared = _module_str_tuples(repo.tree(producer), {}).get(tup_name)
        if declared is None:
            errs.append(Violation(
                CHECK, producer, 0,
                f"no {tup_name} literal tuple found — the validator sync "
                "has nothing to check against"))
            continue
        got = _validator_registry(repo, var)
        if got is None:
            errs.append(Violation(CHECK, VALIDATOR, 0,
                                  f"no {var} registry found"))
            continue
        registry, reg_line = got
        for field in sorted(set(declared) - registry):
            errs.append(Violation(
                CHECK, producer, 0,
                f"field {field!r} ({tup_name}) is missing from "
                f"{VALIDATOR} {var} — the schema tripwire would reject "
                "fresh artifacts"))
        for field in sorted(registry - set(declared)):
            errs.append(Violation(
                CHECK, VALIDATOR, reg_line,
                f"{var} entry {field!r} is emitted by no producer "
                f"({producer} {tup_name}) — stale registry entry"))
    return errs


# --- CRASH_SPLIT -----------------------------------------------------------

def _named_tuples(repo: Repo) -> dict[str, list[str]]:
    """State-class name -> field names, across the engines package."""
    out: dict[str, list[str]] = {}
    for rel in repo.glob(ENGINES_GLOB):
        for node in repo.tree(rel).body:
            if isinstance(node, ast.ClassDef) and any(
                    dotted(b)[-1:] == ("NamedTuple",) for b in node.bases):
                out[node.name] = [
                    n.target.id for n in node.body
                    if isinstance(n, ast.AnnAssign)
                    and isinstance(n.target, ast.Name)]
    return out


def _crash_split_decl(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "CRASH_SPLIT" \
                and isinstance(node.value, ast.Dict):
            decl = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                    decl[k.value] = v.value
            return decl, node.lineno
    return None


def _calls_name(fn: ast.AST, names: set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = dotted(node.func)
            if chain and chain[-1] in names:
                return True
    return False


def _round_analysis(fn: ast.FunctionDef, fields: list[str]):
    """(reset_fields, frozen_fields | None) from a round function."""
    alias = {f: f for f in fields}
    field_set = set(fields)
    # x = st.field / a, b = st.a, st.b  — alias locals to carry fields.
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        pairs = []
        if isinstance(tgt, ast.Name) and isinstance(val, ast.Attribute):
            pairs = [(tgt, val)]
        elif isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                and len(tgt.elts) == len(val.elts):
            pairs = list(zip(tgt.elts, val.elts))
        for t, v in pairs:
            if isinstance(t, ast.Name) and isinstance(v, ast.Attribute) \
                    and v.attr in field_set:
                alias[t.id] = v.attr

    def to_field(node: ast.AST):
        if isinstance(node, ast.Name):
            return alias.get(node.id)
        if isinstance(node, ast.Attribute) and node.attr in field_set:
            return node.attr
        return None

    reset: set[str] = set()
    frozen: set[str] | None = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            val = node.value
            if isinstance(val, ast.Call) \
                    and dotted(val.func)[-1:] == ("where",) and val.args:
                uses_rec = any(isinstance(n, ast.Name) and n.id == "rec"
                               for n in ast.walk(val.args[0]))
                if uses_rec:
                    for t in node.targets:
                        for name in assigned_names(t):
                            f = alias.get(name)
                            if f:
                                reset.add(f)
            for t in node.targets:
                if assigned_names(t) == ["frozen"] \
                        and isinstance(val, ast.Tuple):
                    frozen = {f for f in map(to_field, val.elts) if f}
    return reset, frozen


def _crash_split_violations(repo: Repo) -> list[Violation]:
    classes = _named_tuples(repo)
    errs: list[Violation] = []
    for rel in repo.glob(ENGINES_GLOB):
        tree = repo.tree(rel)
        rounds = [n for n in tree.body if isinstance(n, ast.FunctionDef)
                  and _calls_name(n, {"crash_transition"})]
        if not rounds:
            continue
        decl = _crash_split_decl(tree)
        if decl is None:
            errs.append(Violation(
                CHECK, rel, 0,
                "engine implements the SPEC §6c crash adversary "
                "(crash_transition call) but declares no CRASH_SPLIT — "
                "add the persistent/volatile/meta carry declaration"))
            continue
        split, line = decl
        bad_kinds = {k: v for k, v in split.items()
                     if v not in SPLIT_KINDS}
        for k, v in bad_kinds.items():
            errs.append(Violation(
                CHECK, rel, line,
                f"CRASH_SPLIT[{k!r}] = {v!r}: kind must be one of "
                f"{sorted(SPLIT_KINDS)}"))
        for fn in rounds:
            state_cls = next(
                (dotted(n.func)[-1] for n in ast.walk(fn)
                 if isinstance(n, ast.Call)
                 and dotted(n.func)[-1:] != ()
                 and dotted(n.func)[-1] in classes), None)
            if state_cls is None:
                errs.append(Violation(
                    CHECK, rel, fn.lineno,
                    f"{fn.name}: cannot find the state NamedTuple this "
                    "round constructs — CRASH_SPLIT is uncheckable"))
                continue
            fields = classes[state_cls]
            if set(split) != set(fields):
                missing = sorted(set(fields) - set(split))
                extra = sorted(set(split) - set(fields))
                errs.append(Violation(
                    CHECK, rel, line,
                    f"CRASH_SPLIT keys != {state_cls} fields "
                    f"(missing: {missing}, stale: {extra})"))
                continue
            declared_vol = {k for k, v in split.items() if v == "volatile"}
            declared_per = {k for k, v in split.items() if v == "persistent"}
            reset, frozen = _round_analysis(fn, fields)
            if reset != declared_vol:
                errs.append(Violation(
                    CHECK, rel, fn.lineno,
                    f"{fn.name}: recovery-reset fields {sorted(reset)} != "
                    f"declared volatile {sorted(declared_vol)} — a "
                    "persistent field reset on `rec` rolls durable state "
                    "back; a volatile field NOT reset leaks pre-crash "
                    "state into the rejoin"))
            if _calls_name(fn, FREEZE_FNS):
                want = declared_per | declared_vol
                if frozen is None:
                    errs.append(Violation(
                        CHECK, rel, fn.lineno,
                        f"{fn.name}: freeze call without a recognizable "
                        "`frozen = (...)` tuple"))
                elif frozen != want:
                    errs.append(Violation(
                        CHECK, rel, fn.lineno,
                        f"{fn.name}: frozen tuple covers {sorted(frozen)} "
                        f"but persistent+volatile = {sorted(want)} — an "
                        "uncovered field lets a down node's state move"))
    return errs


def check(repo: Repo) -> list[Violation]:
    return (_telemetry_violations(repo) + _latency_violations(repo)
            + _fields_violations(repo) + _crash_split_violations(repo))
