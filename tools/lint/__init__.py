"""consensus-lint: repo-specific static enforcement of the determinism
and parity invariants (docs/STATIC_ANALYSIS.md).

The repo's equivalence story — byte-identical digests between the JAX
engines and the C++ oracle, bit-identity under crash/telemetry/
checkpoint features — rests on conventions that 184 dynamic tests probe
*after* a violation ships. Each check here turns one convention into a
machine-checked rule over the AST, so a violation fails `make check`
before it can reach a digest:

  purity     — engine round/scan bodies stay traceable-pure: no host
               callbacks, wall clocks, stateful RNG, Python coercions
               of tracers, or data-dependent Python branching.
  streams    — the counter-RNG stream registry (core/rng.py
               STREAM_KEYS): unique constants, declared absorb-key
               arity at every call site, C++ mirror in sync.
  dtypes     — u32/i32 dtype discipline in engines/ and ops/: no
               int64/float64, no dtype-defaulted array constructors
               (parity with the u32 C++ oracle is load-bearing).
  registry   — EngineDef.telemetry_names <-> tools/validate_trace.py
               TELEMETRY_COUNTERS, and each engine's CRASH_SPLIT
               declaration <-> its actual SPEC §6c reset/freeze code.
  cli        — every Config field reachable from both CLI front doors
               or explicitly declared native-CLI-exempt.

Run as `python -m tools.lint` (exit 0 = clean); `make check` gates it
alongside ruff/mypy/clang-tidy and tier-1. Checks are rooted at a repo
directory so the negative tests can point them at seeded-violation
fixture trees (tests/fixtures/lint/).
"""
from __future__ import annotations

from .core import Repo, Violation
from . import cli_surface, dtypes, purity, registry_sync, streams

# name -> check(repo) -> list[Violation]; ordered as documented.
CHECKS = {
    "purity": purity.check,
    "streams": streams.check,
    "dtypes": dtypes.check,
    "registry": registry_sync.check,
    "cli": cli_surface.check,
}


def run_checks(root, only=None) -> list[Violation]:
    """Run the named checks (default: all) against the repo at ``root``."""
    repo = Repo(root)
    names = list(CHECKS) if only is None else list(only)
    out: list[Violation] = []
    for name in names:
        if name not in CHECKS:
            raise ValueError(f"unknown check {name!r} "
                             f"(known: {', '.join(CHECKS)})")
        out.extend(CHECKS[name](repo))
    return out
