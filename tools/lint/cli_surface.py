"""Check `cli`: every Config field is reachable from both CLI front
doors, or explicitly declared native-CLI-exempt.

The repo's contract is ONE flag surface over two engines (SURVEY.md §2
component 13): the Python CLI (consensus_tpu/cli.py, `_FLAG_FIELDS`)
and the native CLI (cpp/consensus_sim.cpp) parse the same spellings,
and the native binary re-execs the Python module for `--engine tpu`
BEFORE strict parsing — so TPU-engine execution knobs may legitimately
exist only on the Python side. Those are declared in cli.py:

    NATIVE_CLI_TPU_ONLY = frozenset({"mesh_shape", "scan_chunk", ...})

This check fails when:
  * a Config field has no Python flag (unreachable from EITHER door);
  * a Config field has no native flag and is not declared TPU-only
    (the native cpu front door silently can't express it);
  * a NATIVE_CLI_TPU_ONLY entry is stale (field gone, or the native
    CLI actually parses it now);
  * _FLAG_FIELDS names a field Config no longer has;
  * the native CLI parses a config-shaped flag the shared map doesn't
    know (the two parsers have forked).
"""
from __future__ import annotations

import ast
import re

from .core import Repo, Violation

CHECK = "cli"

CONFIG = "consensus_tpu/core/config.py"
CLI = "consensus_tpu/cli.py"
NATIVE = "cpp/consensus_sim.cpp"

# Python-CLI flags handled outside _FLAG_FIELDS (the --mesh spelling of
# mesh_shape), and native flags that are not Config fields (--scenario
# names a scripted attack from consensus_tpu/scenarios, --serve-port
# the live-introspection endpoint from obs/serve.py — both front doors
# parse them, the Python side as dedicated argparse flags).
PY_SPECIAL = {"mesh_shape": "--mesh"}
NATIVE_NON_CONFIG = {"oracle-delivery", "out", "help", "scenario",
                     "serve-port"}

_NATIVE_FLAG_RE = re.compile(r'k == "--([a-z0-9-]+)"')


def _config_fields(repo: Repo) -> tuple[dict[str, int], list[Violation]]:
    for node in repo.tree(CONFIG).body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return ({n.target.id: n.lineno for n in node.body
                     if isinstance(n, ast.AnnAssign)
                     and isinstance(n.target, ast.Name)}, [])
    return {}, [Violation(CHECK, CONFIG, 0, "no Config dataclass found")]


def _flag_fields(repo: Repo) -> tuple[dict[str, str], int]:
    """flag -> Config field from cli.py's _FLAG_FIELDS literal."""
    for node in repo.tree(CLI).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "_FLAG_FIELDS" \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Tuple) \
                        and v.elts and isinstance(v.elts[0], ast.Constant):
                    out[k.value] = v.elts[0].value
            return out, node.lineno
    return {}, 0


def _tpu_only_decl(repo: Repo) -> tuple[set, int]:
    for node in repo.tree(CLI).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "NATIVE_CLI_TPU_ONLY":
            return ({c.value for c in ast.walk(node.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)}, node.lineno)
    return set(), 0


def check(repo: Repo) -> list[Violation]:
    errs: list[Violation] = []
    for rel in (CONFIG, CLI, NATIVE):
        if not repo.exists(rel):
            return [repo.missing(CHECK, rel)]
    fields, errs = _config_fields(repo)
    if not fields:
        return errs
    flag_map, flag_line = _flag_fields(repo)
    if not flag_map:
        return errs + [Violation(CHECK, CLI, 0,
                                 "no _FLAG_FIELDS map found")]
    tpu_only, tpu_line = _tpu_only_decl(repo)
    cli_src = repo.read(CLI)
    native_flags = set(_NATIVE_FLAG_RE.findall(repo.read(NATIVE)))

    py_covered: dict[str, str] = {}      # field -> flag spelling
    for flag, field in flag_map.items():
        if field not in fields:
            errs.append(Violation(
                CHECK, CLI, flag_line,
                f"_FLAG_FIELDS maps --{flag.replace('_', '-')} to "
                f"{field!r}, which is not a Config field — the parsers "
                "drifted"))
            continue
        py_covered[field] = flag.replace("_", "-")
    for field, spelling in PY_SPECIAL.items():
        if field in fields and spelling in cli_src:
            py_covered[field] = spelling.lstrip("-")

    for field, line in sorted(fields.items()):
        if field not in py_covered:
            errs.append(Violation(
                CHECK, CONFIG, line,
                f"Config.{field} is unreachable from the Python CLI — add "
                "a _FLAG_FIELDS entry (or a dedicated flag) in cli.py"))
            continue
        native = py_covered[field] in native_flags
        if native and field in tpu_only:
            errs.append(Violation(
                CHECK, CLI, tpu_line,
                f"NATIVE_CLI_TPU_ONLY declares {field!r} but "
                f"{NATIVE} parses --{py_covered[field]} — stale exemption"))
        elif not native and field not in tpu_only:
            errs.append(Violation(
                CHECK, CONFIG, line,
                f"Config.{field} has no native-CLI flag "
                f"(--{py_covered[field]} not parsed by {NATIVE}) and is "
                "not declared in cli.py NATIVE_CLI_TPU_ONLY — the native "
                "cpu front door silently cannot express it"))
    for field in sorted(tpu_only - set(fields)):
        errs.append(Violation(
            CHECK, CLI, tpu_line,
            f"NATIVE_CLI_TPU_ONLY declares {field!r}, which is not a "
            "Config field — stale exemption"))

    known_spellings = {f.replace("_", "-") for f in flag_map} \
        | set(NATIVE_NON_CONFIG)
    for flag in sorted(native_flags - known_spellings):
        errs.append(Violation(
            CHECK, NATIVE, 0,
            f"native CLI parses --{flag}, which the shared _FLAG_FIELDS "
            "map does not know — the two front doors have forked"))
    return errs
