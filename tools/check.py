#!/usr/bin/env python3
"""The one-command gate: lint + hlo + costcheck + ruff + mypy +
clang-tidy + tier-1.

    python tools/check.py [--skip-tests] [--only LAYER ...]
    make check                  # the same thing

Layers (docs/STATIC_ANALYSIS.md):

  lint   — tools/lint, the repo-specific determinism/parity checks
           (stdlib-only; ALWAYS runs)
  hlo    — tools/hlocheck, the COMPILED-program contracts (collective
           family, sort budgets, dtype widening, host boundary, carry
           donation + fingerprints; CPU lowering only)      [gated]
  costcheck — tools/costmodel, the compiled COST model (XLA
           cost_analysis per registered config vs the committed cost
           cards under benchmarks/parts/costcards/)         [gated]
  ruff   — generic Python lint (pyproject.toml)        [gated]
  mypy   — typed-perimeter type check (pyproject.toml) [gated]
  tidy   — clang-tidy over cpp/ (`make -C cpp tidy`)   [gated]
  scenarios — one scripted-attack run through the real CLI, timeline
           assertions enforced via its exit status       [gated on jax]
  advsearch — the coverage-guided adversary-search smoke (fixed tiny
           budget, fixed seed, CPU backend): one-compiled-program-per-
           generation witnessed on its own trace + findings schema
           (`make advsearch-smoke`)                      [gated on jax]
  service — the sweepd smoke (docs/SERVICE.md): ephemeral-port daemon,
           two compatible + one incompatible job, batching/digest/
           metrics asserted over the live API, clean SIGTERM shutdown
           (`make service-smoke`)                        [gated on jax]
  tests  — the tier-1 pytest suite (JAX_PLATFORMS=cpu, -m 'not slow')

"Gated" layers SKIP with a loud notice when their tool is not
installed — the container image bakes the jax toolchain but not
necessarily ruff/mypy/clang-tidy; CI images that carry them enforce
those layers too (the hlo layer gates on jax itself). A skip is not a
pass of nothing: the always-on layers (lint, tests) carry the
invariants that matter most.

Exit status: nonzero iff any layer that RAN failed.
"""
from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Mirrors ROADMAP.md's tier-1 verify line (plugin set included).
TIER1 = [sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
         "--continue-on-collection-errors", "-p", "no:cacheprovider",
         "-p", "no:xdist", "-p", "no:randomly"]


def _run(cmd: list[str], env: dict | None = None) -> int:
    print(f"check: $ {' '.join(cmd)}", flush=True)
    return subprocess.call(cmd, cwd=REPO, env=env)


def _have(tool: str) -> bool:
    return shutil.which(tool) is not None


def layer_lint(_: argparse.Namespace) -> str:
    return "FAIL" if _run([sys.executable, "-m", "tools.lint"]) else "ok"


def layer_hlo(_: argparse.Namespace) -> str:
    # tools/hlocheck self-gates (prints a loud SKIP and exits 0 when jax
    # is missing) and forces JAX_PLATFORMS=cpu + the 8-virtual-device
    # flags itself, so a plain subprocess is the whole layer.
    if _run([sys.executable, "-m", "tools.hlocheck"]):
        return "FAIL"
    # Tell the tier-1 layer the full hlocheck gate already ran in THIS
    # invocation: its in-process mirror test skips instead of paying the
    # ~25 s of flagship lowering a second time (a standalone pytest run
    # — the ROADMAP tier-1 line — still runs the mirror).
    os.environ["CONSENSUS_HLO_LAYER_RAN"] = "1"
    return "ok"


def layer_costcheck(_: argparse.Namespace) -> str:
    # tools/costmodel self-gates like hlocheck (loud SKIP, exit 0, when
    # jax is missing) and forces the CPU backend itself. Runs AFTER the
    # hlo layer: the cards' collective censuses read the committed
    # fingerprints, so a fingerprint failure should fail as itself, not
    # as mysterious cost drift.
    if _run([sys.executable, "-m", "tools.costmodel"]):
        return "FAIL"
    # Like the hlo layer: tell tier-1's in-process mirror test the full
    # costcheck gate already ran in THIS invocation so it skips the
    # re-lowering.
    os.environ["CONSENSUS_COST_LAYER_RAN"] = "1"
    return "ok"


def layer_ruff(_: argparse.Namespace) -> str:
    if not _have("ruff"):
        return "SKIP (ruff not installed)"
    return "FAIL" if _run(["ruff", "check", "."]) else "ok"


def layer_mypy(_: argparse.Namespace) -> str:
    if not _have("mypy"):
        return "SKIP (mypy not installed)"
    # Files/strictness come from pyproject.toml [tool.mypy].
    return "FAIL" if _run(["mypy"]) else "ok"


def layer_tidy(_: argparse.Namespace) -> str:
    if not _have("make"):
        return "SKIP (make not installed)"
    # cpp/Makefile gates on clang-tidy itself (prints SKIPPED, exits 0).
    if not _have("clang-tidy"):
        return "SKIP (clang-tidy not installed)"
    return "FAIL" if _run(["make", "-C", "cpp", "tidy"]) else "ok"


# The `make check` scenario smokes: small scripted-attack runs through
# the real CLI front door, timeline assertions judged by the scenario's
# own exit status (consensus_tpu/scenarios). Each shape IS the
# scenario's declared `tuned` reference shape — the one its bounds are
# verified at — so a smoke red is a real regression, never the
# off-tuned case the CLI hint disclaims; tests reuse these exact flag
# lists (test_python_cli_scenario_verdict /
# test_python_cli_hotstuff_smoke_verdict) so the two can't drift.
SCENARIO_SMOKE = ["-m", "consensus_tpu", "--scenario", "delay-storm",
                  "--protocol", "raft", "--nodes", "7", "--rounds", "96",
                  "--log-capacity", "32", "--max-entries", "24",
                  "--sweeps", "2", "--seed", "11", "--platform", "cpu"]

# The linear-BFT smoke: the chained-commit stall under the PR 10 delay
# stream + §6c leader outages, through the hotstuff engine (SPEC §7b).
HOTSTUFF_SMOKE = ["-m", "consensus_tpu", "--scenario",
                  "chained-commit-stall", "--protocol", "hotstuff",
                  "--f", "2", "--rounds", "96", "--log-capacity", "96",
                  "--sweeps", "2", "--seed", "11", "--platform", "cpu"]

# The SPEC §9 switch-delivery smoke: votes through in-network
# aggregators under the STREAM_AGG failure/stale fault axes — QC
# starvation and chained-commit stall bounded by the flight recorder.
SWITCH_SMOKE = ["-m", "consensus_tpu", "--scenario",
                "stale-aggregator-inconsistency", "--protocol", "hotstuff",
                "--f", "2", "--rounds", "96", "--log-capacity", "96",
                "--sweeps", "2", "--seed", "11", "--platform", "cpu"]

# The SPEC §B view-desync smoke: per-node synchronizer timer skew under
# heavy drops — premature local view changes spread the views faster
# than the highest-QC gossip heals them, commits stutter, and the
# synchronizer telemetry (view_spread_max/desync_rounds) is asserted
# live via the scenario's min_counters.
DESYNC_SMOKE = ["-m", "consensus_tpu", "--scenario", "view-desync-storm",
                "--protocol", "hotstuff", "--f", "2", "--rounds", "96",
                "--log-capacity", "96", "--sweeps", "2", "--seed", "11",
                "--platform", "cpu"]


# tuned-shape Config field -> CLI flag, for building promoted-scenario
# smokes out of the discovered catalog (same flag names _FLAG_FIELDS in
# consensus_tpu/cli.py declares; stdlib-only here by design).
_TUNED_FLAGS = {"n_nodes": "--nodes", "f": "--f", "n_rounds": "--rounds",
                "log_capacity": "--log-capacity",
                "max_entries": "--max-entries",
                "view_timeout": "--view-timeout",
                "n_candidates": "--candidates",
                "n_producers": "--producers"}


def promoted_scenario_smokes() -> list[list[str]]:
    """One CLI smoke per PROMOTED discovered scenario: catalog entries
    that passed `python -m tools.advsearch promote` (bounds held across
    K fresh seeds at the tuned shape) gate `make check` exactly like
    the hand-built smokes above; distilled-but-unpromoted entries stay
    runnable but do not gate CI."""
    import json
    path = os.path.join(REPO, "consensus_tpu", "scenarios",
                        "discovered.json")
    if not os.path.exists(path):
        return []
    doc = json.load(open(path))
    smokes = []
    for entry in doc.get("scenarios", []):
        s = entry["scenario"]
        if not s.get("promoted"):
            continue
        cmd = ["-m", "consensus_tpu", "--scenario", s["name"],
               "--protocol", s["protocol"]]
        for field, val in sorted(s["tuned"].items()):
            cmd += [_TUNED_FLAGS[field], str(val)]
        cmd += ["--sweeps", "2", "--seed",
                str(s["promoted"]["seeds"][0]), "--platform", "cpu"]
        smokes.append(cmd)
    return smokes


def layer_scenarios(_: argparse.Namespace) -> str:
    import importlib.util
    if importlib.util.find_spec("jax") is None:
        return "SKIP (jax not installed)"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for smoke in (SCENARIO_SMOKE, HOTSTUFF_SMOKE, SWITCH_SMOKE,
                  DESYNC_SMOKE, *promoted_scenario_smokes()):
        if _run([sys.executable] + smoke, env=env):
            return "FAIL"
    return "ok"


def layer_advsearch(_: argparse.Namespace) -> str:
    # `python -m tools.advsearch smoke`: a fixed tiny-budget coverage-
    # guided search (SMOKE constants in tools/advsearch/__main__.py)
    # that self-checks the one-compiled-program-per-generation contract
    # on its own trace (dispatch spans == generations) and the findings
    # schema — exits nonzero on any violation. CPU backend, seconds.
    import importlib.util
    if importlib.util.find_spec("jax") is None:
        return "SKIP (jax not installed)"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return "FAIL" if _run([sys.executable, "-m", "tools.advsearch",
                           "smoke"], env=env) else "ok"


def layer_service(_: argparse.Namespace) -> str:
    """The sweepd smoke (docs/SERVICE.md): start a daemon on an
    ephemeral port (CPU backend), submit two compatible jobs + one
    incompatible, and assert on the live API what the service promises
    — the compatible pair shares one batch (one compiled program), the
    incompatible job runs alone, every job finishes with a decided-log
    digest, /metrics carries the fleet counters, and SIGTERM shuts the
    daemon down cleanly."""
    import importlib.util
    if importlib.util.find_spec("jax") is None:
        return "SKIP (jax not installed)"
    import json
    import signal
    import tempfile
    import urllib.request

    td = tempfile.mkdtemp(prefix="sweepd-smoke")
    port_file = os.path.join(td, "port")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "consensus_tpu.service", "--port", "0",
           "--state-dir", os.path.join(td, "state"), "--platform", "cpu",
           "--port-file", port_file]
    print(f"check: $ {' '.join(cmd)}", flush=True)
    proc = subprocess.Popen(cmd, cwd=REPO, env=env)

    def fail(msg: str) -> str:
        print(f"check: service smoke: {msg}", flush=True)
        proc.kill()
        return "FAIL"

    try:
        deadline = time.time() + 120
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                return fail(f"daemon exited rc={proc.returncode} "
                            "before binding")
            if time.time() > deadline:
                return fail("daemon never wrote its port file")
            time.sleep(0.2)
        url = f"http://127.0.0.1:{open(port_file).read().strip()}"

        def call(path: str, doc=None):
            data = json.dumps(doc).encode() if doc is not None else None
            req = urllib.request.Request(url + path, data=data,
                                         method="POST" if data else "GET")
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read().decode())

        base = {"protocol": "raft", "engine": "tpu", "n_nodes": 5,
                "n_rounds": 48, "n_sweeps": 2, "seed": 3,
                "log_capacity": 32, "max_entries": 24}
        ids = [call("/jobs", {"config": base})["id"],
               call("/jobs", {"config": dict(base, seed=77)})["id"],
               call("/jobs", {"config": dict(base, protocol="paxos",
                                             n_nodes=9)})["id"]]
        deadline = time.time() + 240
        while True:
            docs = [call(f"/jobs/{i}") for i in ids]
            if all(d["status"] in ("done", "failed") for d in docs):
                break
            if time.time() > deadline:
                return fail(f"jobs never finished: "
                            f"{[d['status'] for d in docs]}")
            time.sleep(0.3)
        for d in docs:
            if d["status"] != "done" or len(
                    (d.get("result") or {}).get("digest") or "") != 64:
                return fail(f"job {d['id']}: status {d['status']}, "
                            f"error {d.get('error')}")
        pair, solo = docs[0], docs[2]
        if pair["batch"] != ids[:2] or docs[1]["batch"] != ids[:2]:
            return fail(f"compatible pair did not share a batch: "
                        f"{[d['batch'] for d in docs]}")
        if solo["batch"] is not None:
            return fail(f"incompatible job joined batch {solo['batch']}")
        listing = call("/jobs")
        if len(listing["jobs"]) != 3:
            return fail(f"/jobs listed {len(listing['jobs'])} jobs")
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            metrics = r.read().decode()
        # (the per-job labeled-gauge children are removed as jobs
        # finish — a bounded family on a long-lived daemon — so the
        # post-completion scrape asserts the fleet counters)
        for needle in ("service_jobs_completed_total 3",
                       "service_batches_total 2",
                       "service_queue_depth 0"):
            if needle not in metrics:
                return fail(f"/metrics missing {needle!r}")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        if rc != 0:
            return fail(f"SIGTERM shutdown exited rc={rc}")
    except Exception as exc:  # noqa: BLE001 — smoke harness boundary
        return fail(f"{type(exc).__name__}: {exc}")
    finally:
        if proc.poll() is None:
            proc.kill()
    return "ok"


def layer_tests(args: argparse.Namespace) -> str:
    if args.skip_tests:
        return "SKIP (--skip-tests)"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return "FAIL" if _run(TIER1, env=env) else "ok"


LAYERS = {"lint": layer_lint, "hlo": layer_hlo,
          "costcheck": layer_costcheck, "ruff": layer_ruff,
          "mypy": layer_mypy, "tidy": layer_tidy,
          "scenarios": layer_scenarios, "advsearch": layer_advsearch,
          "service": layer_service, "tests": layer_tests}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Run the full static-analysis + test gate.")
    ap.add_argument("--only", action="append", choices=sorted(LAYERS),
                    help="run only this layer (repeatable)")
    ap.add_argument("--skip-tests", action="store_true",
                    help="skip the tier-1 pytest layer (quick lint loop)")
    args = ap.parse_args(argv)
    names = list(LAYERS) if not args.only else list(args.only)

    results: dict[str, str] = {}
    for name in names:
        t0 = time.perf_counter()
        results[name] = LAYERS[name](args)
        results[name] += f"  [{time.perf_counter() - t0:.1f}s]"

    width = max(len(n) for n in results)
    print("\ncheck: summary")
    for name, status in results.items():
        print(f"  {name:<{width}}  {status}")
    failed = [n for n, s in results.items() if s.startswith("FAIL")]
    if failed:
        print(f"check: FAILED ({', '.join(failed)})")
        return 1
    print("check: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
