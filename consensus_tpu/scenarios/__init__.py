"""SPEC Appendix A scenario library: named, scripted attack configs
paired with flight-recorder timeline assertions.

Each :class:`Scenario` bundles (1) the adversary knobs that script one
attack from the vulnerability literature ("From Consensus to Chaos",
PAPERS.md 2601.00273) onto a base :class:`~consensus_tpu.core.config
.Config`, and (2) the liveness bounds the resulting timeline must
satisfy — the "availability dips, then recovers within R rounds" shape
the ROADMAP's adversary item asks for. Scenarios run through the
normal front doors (``--scenario NAME`` in both CLIs; the native
binary re-execs the Python CLI for ``--engine tpu``, and rejects
cpu-engine scenarios — the assertions read the flight recorder, which
only the TPU engine records). The verdict is emitted into the CLI
report under ``"scenario"`` and the process exits nonzero on a failed
assertion, which is what makes ``make check``'s scenario smoke layer a
tripwire rather than a demo.

Determinism: a scenario only *overrides Config fields*, so a scenario
run is exactly as reproducible (and checkpoint/resumable) as any other
run of the resulting config — the assertions are a pure function of
the run's flight series (obs/timeline.py) and, for DPoS, its decided
chains.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import numpy as np


@dataclasses.dataclass(frozen=True)
class TimelineBounds:
    """Liveness assertions evaluated against one run's derived
    timeline (obs/timeline.derive). ``None`` disables a bound.

    * ``require_fault_onset`` — some fault-counter window must fire in
      every sweep (else the scenario silently did not attack).
    * ``max_availability`` — the availability DIP: mean availability
      must not exceed this (the attack visibly hurt liveness).
    * ``min_availability`` — liveness floor: the attack must not kill
      the run outright (recovery happens).
    * ``min_stall_windows`` — at least this many zero-commit windows
      across sweeps.
    * ``max_recovery_rounds`` — every sweep recovers (commits again)
      within this many rounds of its fault onset; -1 recovery (never)
      always fails when this bound is set.
    * ``max_lib_ratio`` — DPoS only: mean (lib+1) / mean chain head
      must stay at or below this — the LIB-stall assertion (SPEC §7
      irreversibility trails the head under per-producer faults).
    * ``min_counters`` / ``max_counters`` — per-counter bounds on the
      run's TOTAL of a flight-recorder counter across sweeps and
      windows. This is how safety scenarios assert the SPEC §7c
      invariant telemetry: ``min_counters={"forked_qc": 1}`` demands
      the attack actually forged a certificate, and
      ``max_counters={"safety_violations": 0}`` is the negative
      assertion that an availability-only attack never crossed into
      agreement violation. A counter the engine does not record totals
      0 (so a min bound on it fails loudly).
    """
    require_fault_onset: bool = True
    max_availability: float | None = None
    min_availability: float | None = None
    min_stall_windows: int | None = None
    max_recovery_rounds: int | None = None
    max_lib_ratio: float | None = None
    min_counters: Mapping[str, int] | None = None
    max_counters: Mapping[str, int] | None = None


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    protocol: str
    overrides: Mapping[str, Any]   # Config fields the scenario scripts
    bounds: TimelineBounds
    window: int = 8                # telemetry_window when cfg leaves it 0
    min_rounds: int = 64           # shorter runs can't show the shape
    # The shape the bounds were verified at (tests/test_adversary_lib
    # SCENARIO_SHAPES embeds it). The assertions describe a LIVENESS
    # SHAPE, which depends on population/schedule geometry, not just
    # n_rounds — at a different (still valid) shape the same attack may
    # dip less or recover differently, so a failed verdict there is a
    # tuning signal, not necessarily a bug; the CLI prints this
    # reference shape in its failure hint.
    tuned: Mapping[str, Any] = dataclasses.field(default_factory=dict)


# The library. Rates are scripted; population/shape comes from the base
# config so tests run small and flagship runs can go big — min_rounds
# hard-guards the rounds axis, and `tuned` records the reference shape
# each scenario's bounds were actually verified at.
SCENARIOS: dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="repeated-election-disruption",
        description="SPEC §A.3 'elect': jam all election traffic in "
                    "attacked rounds where a timeout fired — availability "
                    "dips while elections are disrupted, then an election "
                    "slips through and commits resume (2601.00273's "
                    "election-disruption liveness attack).",
        protocol="raft",
        overrides=dict(attack="elect", attack_rate=0.85, drop_rate=0.05),
        bounds=TimelineBounds(max_availability=0.98, min_availability=0.25,
                              min_stall_windows=1,
                              max_recovery_rounds=96),
        window=4,
        tuned=dict(n_nodes=7, n_rounds=96, log_capacity=32,
                   max_entries=24)),
    Scenario(
        name="rolling-producer-outage",
        description="SPEC §A.1 + §6c on DPoS: per-producer slot misses "
                    "composed with crash/recover churn — gappy schedules, "
                    "chains diverge under drops, and LIB trails the head "
                    "(the VERDICT r5 'adversary never attacks DPoS's own "
                    "mechanism' gap, closed).",
        protocol="dpos",
        overrides=dict(miss_rate=0.35, crash_prob=0.08, recover_prob=0.25,
                       drop_rate=0.1),
        bounds=TimelineBounds(max_availability=0.995, min_availability=0.3,
                              max_recovery_rounds=64,
                              max_lib_ratio=0.9),
        window=4,
        tuned=dict(n_nodes=24, n_rounds=96, log_capacity=96,
                   n_candidates=12, n_producers=6)),
    Scenario(
        name="delay-storm",
        description="SPEC §A.2: heavy loss with most flights repaired by "
                    "late retransmissions — reordered/late quorum "
                    "formation (timing manipulation), commits stutter but "
                    "survive.",
        protocol="raft",
        overrides=dict(drop_rate=0.55, max_delay_rounds=8),
        bounds=TimelineBounds(max_availability=0.99, min_availability=0.2,
                              min_stall_windows=1,
                              max_recovery_rounds=96),
        window=4,
        tuned=dict(n_nodes=7, n_rounds=96, log_capacity=32,
                   max_entries=24)),
    Scenario(
        name="chained-commit-stall",
        description="SPEC §7b chained HotStuff under the §A.2 delay "
                    "stream + §6c leader outages: crashed/churned "
                    "leaders force view-timeout changes, failed views "
                    "break the consecutive-view 3-chain so commits "
                    "stall while the QC pipeline re-fills, and heavy "
                    "lossy-but-delayed delivery stutters quorum "
                    "formation (the chained-commit-stall liveness "
                    "shape the linear-BFT literature targets; "
                    "PAPERS.md 2007.12637).",
        protocol="hotstuff",
        overrides=dict(drop_rate=0.35, max_delay_rounds=6,
                       crash_prob=0.12, recover_prob=0.35,
                       max_crashed=2, churn_rate=0.05,
                       view_timeout=4),
        bounds=TimelineBounds(max_availability=0.98,
                              min_availability=0.25,
                              min_stall_windows=1,
                              max_recovery_rounds=96),
        window=4,
        tuned=dict(n_nodes=7, f=2, n_rounds=96, log_capacity=96)),
    Scenario(
        name="stale-aggregator-inconsistency",
        description="SPEC §9 switch delivery under aggregator faults "
                    "(hotstuff): votes route through 2 in-network "
                    "aggregators — a failed aggregator silently drops "
                    "half the vote segment and a stale one re-serves a "
                    "shifted round's delivery pattern (the paper's "
                    "stale-in-switch-state axis, PAPERS.md 1605.05619), "
                    "so QCs fail, the pacemaker burns view timeouts, "
                    "and the chained 3-commit stalls — switch-vs-replica "
                    "divergence bounded by the flight recorder. (Dip "
                    "bound retuned for the SPEC §B per-node "
                    "synchronizer: highest-QC gossip re-syncs views "
                    "faster than the retired global pacemaker did, so "
                    "availability under this attack sits higher.)",
        protocol="hotstuff",
        overrides=dict(net_model="switch", n_aggregators=2,
                       agg_fail_rate=0.3, agg_stale_rate=0.5,
                       agg_max_stale=4, drop_rate=0.2, view_timeout=4),
        bounds=TimelineBounds(max_availability=0.8, min_availability=0.1,
                              min_stall_windows=4,
                              max_recovery_rounds=48),
        window=4,
        tuned=dict(n_nodes=7, f=2, n_rounds=96, log_capacity=96)),
    Scenario(
        name="view-desync-storm",
        description="SPEC §B per-node view desync on chained HotStuff: "
                    "STREAM_DESYNC timer skew fires premature local view "
                    "changes while a heavy drop rate keeps the highest-QC "
                    "gossip from healing the spread within the round — "
                    "nodes disagree about who leads, proposals land on "
                    "receivers already past the proposer's view, and "
                    "commits stutter until catch-up wins (the "
                    "view-synchronization liveness attack of the "
                    "pacemaker literature; PAPERS.md 2007.12637).",
        protocol="hotstuff",
        overrides=dict(desync_rate=0.15, max_skew_rounds=4,
                       drop_rate=0.25, view_timeout=4),
        bounds=TimelineBounds(max_availability=0.9, min_availability=0.2,
                              min_stall_windows=1,
                              max_recovery_rounds=96,
                              min_counters={"view_spread_max": 2,
                                            "desync_rounds": 1,
                                            "sync_msgs_delivered": 1},
                              max_counters={"safety_violations": 0}),
        window=4,
        tuned=dict(n_nodes=7, f=2, n_rounds=96, log_capacity=96)),
    Scenario(
        name="crash-churn-under-partition",
        description="SPEC §6c crash/recover under intermittent "
                    "bipartitions and leader churn (PBFT): view changes "
                    "and crash windows suppress quorums, recovery rejoins "
                    "from the persisted slot log.",
        protocol="pbft",
        overrides=dict(crash_prob=0.12, recover_prob=0.35, max_crashed=2,
                       partition_rate=0.25, churn_rate=0.05,
                       drop_rate=0.05),
        bounds=TimelineBounds(max_availability=0.995, min_availability=0.2,
                              max_recovery_rounds=96),
        window=4,
        tuned=dict(n_nodes=7, f=2, n_rounds=96, log_capacity=16)),
)}


# --- discovered scenarios (tools/advsearch) --------------------------------
#
# The coverage-guided adversary search distills its oracle-confirmed
# findings into this same Scenario format; they ship as data
# (discovered.json next to this module, written by `python -m
# tools.advsearch distill`) rather than code, so a search run can grow
# the library without editing source. Each catalog entry embeds the
# original finding (knobs, fitness metrics, oracle digest — schema
# tools/validate_trace.py FINDING_FIELDS), and distillation refuses
# anything that fails its own TimelineBounds on a fresh run or its C++
# oracle replay (docs/RESILIENCE.md §8).

def _load_discovered() -> dict[str, Scenario]:
    import json
    import pathlib
    path = pathlib.Path(__file__).with_name("discovered.json")
    if not path.exists():
        return {}
    doc = json.loads(path.read_text())
    out: dict[str, Scenario] = {}
    for entry in doc.get("scenarios", []):
        s = entry["scenario"]
        if s["name"] in SCENARIOS or s["name"] in out:
            raise ValueError(
                f"discovered scenario {s['name']!r} collides with an "
                "already-registered name (discovered.json vs the "
                "hand-built library)")
        out[s["name"]] = Scenario(
            name=s["name"], description=s["description"],
            protocol=s["protocol"], overrides=dict(s["overrides"]),
            bounds=TimelineBounds(**s["bounds"]), window=int(s["window"]),
            min_rounds=int(s["min_rounds"]), tuned=dict(s["tuned"]))
    return out


DISCOVERED: dict[str, Scenario] = _load_discovered()
SCENARIOS.update(DISCOVERED)


def get(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise ValueError(f"unknown scenario {name!r}; known: "
                         f"{sorted(SCENARIOS)}") from None


def apply(cfg, scenario: Scenario, explicit=frozenset()):
    """The scenario's scripted config: ``cfg`` with the attack knobs
    overridden, the protocol forced, and the flight recorder on
    (scenario assertions read the windowed series; an explicit
    ``telemetry_window > 0`` on ``cfg`` is honored).

    When the scenario forces a protocol SWITCH, the base config's
    population geometry is meaningless for the target protocol, so the
    protocol-specific shape fields are re-derived (pbft: ``n_nodes``
    from ``f``; dpos: candidates/producers clamped into ``n_nodes``).
    ``explicit`` names the Config fields the caller actually set (the
    CLI passes its typed flags): a re-derivation that would DISCARD an
    explicit value raises instead — the repo-wide reject-don't-ignore
    contract."""
    from ..core.config import Config  # lazy: keep module import light

    assert isinstance(cfg, Config)
    if cfg.n_rounds < scenario.min_rounds:
        raise ValueError(
            f"scenario {scenario.name!r} needs n_rounds >= "
            f"{scenario.min_rounds} to show its availability/recovery "
            f"shape (got {cfg.n_rounds})")
    fields: dict[str, Any] = dict(scenario.overrides)
    fields["protocol"] = scenario.protocol
    if scenario.protocol != cfg.protocol:
        if "protocol" in explicit:
            raise ValueError(
                f"scenario {scenario.name!r} runs on protocol "
                f"{scenario.protocol!r}, contradicting the explicitly "
                f"requested {cfg.protocol!r}; drop --protocol or pass "
                f"--protocol {scenario.protocol}")
        derived: dict[str, Any] = {}
        if scenario.protocol in ("pbft", "hotstuff"):
            derived["n_nodes"] = 3 * cfg.f + 1
        elif scenario.protocol == "dpos":
            cand = min(cfg.n_candidates, cfg.n_nodes)
            derived["n_candidates"] = cand
            derived["n_producers"] = min(cfg.n_producers, cand)
        clash = sorted(k for k, v in derived.items()
                       if k in explicit and getattr(cfg, k) != v)
        if clash:
            got = ", ".join(f"{k}={getattr(cfg, k)}" for k in clash)
            raise ValueError(
                f"scenario {scenario.name!r} forces protocol "
                f"{scenario.protocol!r} and would discard {got}; drop "
                f"those flags, or run with --protocol "
                f"{scenario.protocol} and a consistent shape")
        fields.update(derived)
    if cfg.telemetry_window == 0:
        fields["telemetry_window"] = scenario.window
    return dataclasses.replace(cfg, **fields)


def off_tuned(scenario: Scenario, cfg) -> dict[str, tuple[Any, Any]]:
    """Shape fields where ``cfg`` deviates from the reference shape the
    scenario's bounds were verified at: ``{field: (got, tuned)}``.
    Empty ⇒ a failed verdict is a real regression; non-empty ⇒ it may
    just be an untuned shape (the CLI prints this as its hint)."""
    return {k: (getattr(cfg, k), v) for k, v in scenario.tuned.items()
            if getattr(cfg, k) != v}


def _check(checks: dict, name: str, ok, value, bound) -> None:
    checks[name] = {"ok": bool(ok), "value": value, "bound": bound}


def evaluate(scenario: Scenario, result) -> dict:
    """Judge one finished run against the scenario's bounds.

    ``result`` is the :class:`~consensus_tpu.network.simulator
    .RunResult` of the applied config (its ``extras["flight"]`` series
    must be present — ``apply`` guarantees the recorder was on).
    Returns the JSON-ready verdict the CLI embeds under ``"scenario"``:
    ``{"name", "passed", "checks": {check: {ok, value, bound}}}``.
    """
    from ..obs import timeline as obs_timeline

    fl = result.extras.get("flight")
    if fl is None:
        raise ValueError(
            f"scenario {scenario.name!r}: result carries no flight series "
            "— the run was made without the recorder (scenarios.apply "
            "forces telemetry_window > 0)")
    tl = obs_timeline.from_flight_dict(fl)
    derived = obs_timeline.derive(tl)
    b = scenario.bounds
    checks: dict[str, dict] = {}

    if b.require_fault_onset:
        onsets = derived["fault_onset_window"]
        _check(checks, "fault_onset", all(o is not None for o in onsets),
               onsets, "every sweep")
    avail = derived["availability"]["mean"]
    if b.max_availability is not None:
        _check(checks, "availability_dip", avail <= b.max_availability,
               avail, b.max_availability)
    if b.min_availability is not None:
        _check(checks, "availability_floor", avail >= b.min_availability,
               avail, b.min_availability)
    if b.min_stall_windows is not None:
        stalls = derived["stall_windows"]["total"]
        _check(checks, "stall_windows", stalls >= b.min_stall_windows,
               stalls, b.min_stall_windows)
    if b.max_recovery_rounds is not None:
        rec = [r for r in derived["recovery_rounds"] if r is not None]
        ok = bool(rec) and all(0 <= r <= b.max_recovery_rounds for r in rec)
        _check(checks, "recovery_bounded", ok, rec, b.max_recovery_rounds)
    if b.max_lib_ratio is not None:
        lib = np.asarray(result.extras["lib"], dtype=np.int64)
        head = np.asarray(result.counts, dtype=np.int64)
        ratio = float((lib + 1).mean() / max(1.0, float(head.mean())))
        _check(checks, "lib_stall", ratio <= b.max_lib_ratio,
               round(ratio, 6), b.max_lib_ratio)

    def counter_total(name: str) -> int:
        w = tl.windows.get(name)
        return 0 if w is None else int(w.sum())
    for name, lo in sorted((b.min_counters or {}).items()):
        tot = counter_total(name)
        _check(checks, f"min_{name}", tot >= int(lo), tot, int(lo))
    for name, hi in sorted((b.max_counters or {}).items()):
        tot = counter_total(name)
        _check(checks, f"max_{name}", tot <= int(hi), tot, int(hi))

    return {"name": scenario.name,
            "passed": all(c["ok"] for c in checks.values()),
            "availability": avail,
            "checks": checks}
