"""Test-only fault-injection harness for the execution layer.

The resilience story (docs/RESILIENCE.md) is only as trustworthy as the
fault scenarios actually exercised against it — "From Consensus to
Chaos" (PAPERS.md) makes the same argument about the consensus
protocols themselves. This module provides the injectable failure
modes the resilience tests drive:

  * **kill after chunk k** — SIGKILL the process right after the k-th
    scan chunk completes (and its checkpoint, if any, is written), so a
    subprocess test can prove an interrupted-then-resumed run's digest
    is bit-identical to an uninterrupted one;
  * **transient error on the n-th dispatch** — raise
    :class:`InjectedTransientError` before the n-th chunk dispatch, to
    exercise the supervisor's retry/resume loop without a real device
    flake;
  * **kill mid-write** — SIGKILL during the n-th snapshot write (tmp
    bytes on disk, rename not yet issued), the torn window the async
    checkpoint writer must recover from via fallback-to-newest-valid;
  * **corrupt / truncate checkpoint bytes** — host-side helpers that
    damage a snapshot the way a torn write or bit-rot would, to prove
    the checksum manifest detects it and recovery falls back to an
    older rotation.

The hooks are wired into :mod:`consensus_tpu.network.runner` and cost
one ``is None`` check per scan chunk when no plan is installed — the
production path never pays for the harness. A plan is installed either
programmatically (:func:`install` / :func:`reset`, in-process tests) or
via the ``CONSENSUS_TPU_FAULTS`` environment variable (JSON, read once
at first hook call — how the subprocess crash tests reach into a child
``python -m consensus_tpu``), e.g.::

    CONSENSUS_TPU_FAULTS='{"kill_after_chunk": 2}'
    CONSENSUS_TPU_FAULTS='{"transient_dispatches": [2, 3]}'
"""
from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import signal
import sys

ENV_VAR = "CONSENSUS_TPU_FAULTS"


class InjectedTransientError(RuntimeError):
    """A synthetic transient failure (stands in for a device/tunnel
    flake). The supervisor classifies it as retryable."""


@dataclasses.dataclass
class FaultPlan:
    # SIGKILL this process after the k-th (1-based) completed scan chunk,
    # *after* its checkpoint (if any) has been written.
    kill_after_chunk: int | None = None
    # Raise InjectedTransientError before these (1-based) chunk
    # dispatches. Counters are process-global, so a plan spanning a
    # supervised retry ("fail dispatch 2, let the retry's dispatches
    # through") needs no re-arming between attempts.
    transient_dispatches: tuple = ()
    # SIGKILL during the k-th (1-based) snapshot WRITE: after the tmp
    # file's bytes are on disk, before the atomic rename — the torn
    # mid-write window. With the async checkpoint pipeline this fires
    # on the WRITER thread while the main loop may already be
    # dispatching the next chunk; recovery must come from the newest
    # previously-renamed rotation (fallback-to-newest-valid).
    kill_mid_write: int | None = None


_PLAN: FaultPlan | None = None
_ENV_CHECKED = False
_dispatches = 0
_chunks = 0
_writes = 0


def install(**kw) -> FaultPlan:
    """Install a fault plan (in-process tests) and zero the counters."""
    global _PLAN, _ENV_CHECKED, _dispatches, _chunks, _writes
    kw["transient_dispatches"] = tuple(kw.get("transient_dispatches", ()))
    _PLAN = FaultPlan(**kw)
    _ENV_CHECKED = True
    _dispatches = _chunks = _writes = 0
    return _PLAN


def reset() -> None:
    """Remove any installed plan and zero the counters."""
    global _PLAN, _ENV_CHECKED, _dispatches, _chunks, _writes
    _PLAN = None
    _ENV_CHECKED = True  # an explicit reset also wins over the env
    _dispatches = _chunks = _writes = 0


def _active() -> FaultPlan | None:
    global _PLAN, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            d = json.loads(spec)
            d["transient_dispatches"] = tuple(d.get("transient_dispatches",
                                                    ()))
            _PLAN = FaultPlan(**d)
    return _PLAN


def plan_active() -> bool:
    """Is ANY fault plan installed? The runner uses this to force the
    async checkpoint writer's drain barrier before :func:`on_chunk_end`,
    preserving the harness contract that a ``kill_after_chunk`` fires
    only once that chunk's snapshot is durably renamed."""
    return _active() is not None


def on_dispatch() -> None:
    """Called by the runner before each scan-chunk dispatch."""
    global _dispatches
    plan = _active()
    if plan is None:
        return
    _dispatches += 1
    if _dispatches in plan.transient_dispatches:
        raise InjectedTransientError(
            f"injected transient failure on dispatch {_dispatches}")


def on_chunk_end() -> None:
    """Called by the runner after each scan chunk completes (and after
    its between-chunk checkpoint, if any, has been written)."""
    global _chunks
    plan = _active()
    if plan is None:
        return
    _chunks += 1
    if plan.kill_after_chunk is not None and \
            _chunks == plan.kill_after_chunk:
        print(f"faults: SIGKILL after chunk {_chunks}", file=sys.stderr,
              flush=True)
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


def on_checkpoint_write() -> None:
    """Called by the checkpoint write step (sync save or async writer
    thread) after the tmp file's bytes are written, BEFORE the rotation
    renames — the window where a kill leaves a complete-but-invisible
    tmp and the previous rotation as newest-valid."""
    global _writes
    plan = _active()
    if plan is None:
        return
    _writes += 1
    if plan.kill_mid_write is not None and _writes == plan.kill_mid_write:
        print(f"faults: SIGKILL mid-write of snapshot {_writes}",
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


# --- checkpoint damage (host-side helpers; no hook needed) -------------------

def corrupt_checkpoint(path, mode: str = "flip") -> None:
    """Damage a snapshot file the way real failures would.

    ``truncate``    — keep only the first third of the file (torn write);
    ``flip``        — XOR one byte mid-payload of the LARGEST stored npz
                      member (bit rot / bad sector; targeting the member
                      data deterministically — a fixed mid-FILE offset
                      used to land in zip padding whenever the embedded
                      config JSON grew — so either the zip-level or the
                      manifest-level CRC catches it);
    ``leaf-tamper`` — rewrite the archive with one leaf's bytes modified
                      but the ORIGINAL ``__meta__`` kept: the zip
                      container is internally consistent, so only the
                      per-leaf CRC32 manifest can detect the damage.
    """
    import numpy as np

    path = pathlib.Path(path)
    data = bytearray(path.read_bytes())
    if mode == "truncate":
        path.write_bytes(bytes(data[: max(1, len(data) // 3)]))
    elif mode == "flip":
        import struct
        import zipfile
        with zipfile.ZipFile(path) as z:
            info = max(z.infolist(), key=lambda i: i.file_size)
        off = info.header_offset
        # Local file header: name/extra lengths at +26, data at +30+n+m.
        n, m = struct.unpack("<HH", data[off + 26:off + 30])
        data[off + 30 + n + m + info.file_size // 2] ^= 0xFF
        path.write_bytes(bytes(data))
    elif mode == "leaf-tamper":
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        leaf = arrays["leaf_0"]
        flipped = leaf.copy()
        flipped.reshape(-1).view(np.uint8)[0] ^= 0xFF
        arrays["leaf_0"] = flipped
        np.savez(path, **arrays)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
