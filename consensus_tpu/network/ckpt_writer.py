"""Double-buffered async checkpoint writer — snapshot IO off the hot path.

The chunked round loop used to stall on every checkpoint:
``save_checkpoint`` serialized a device→host pull, an npz write, CRC and
manifest hashing and (with ``fsync=True``) two fsyncs against the next
chunk's dispatch, so the device sat idle through the whole save — at
100k-node carries that is ~GB of IO per snapshot on the hot path
(``RunResult.extras["checkpoint_io"]``, the ROADMAP's "measure first"
datum). This module moves the entire save onto ONE background thread
behind a depth-1 queue, so chunk *k+1* dispatches immediately while
chunk *k*'s snapshot is pulled and written — the same
overlap-IO-with-compute discipline the hardware-accelerated consensus
literature lives by (PAPERS.md).

**Double buffering, precisely.** At most two snapshots are captured at
once: the one the writer thread is writing and one pending in the
queue. A third ``submit`` blocks the main loop until the in-flight
write finishes — that wait is real backpressure (snapshots are being
produced faster than the disk absorbs them) and is observed in the
``checkpoint_backpressure_s`` histogram. A deeper queue would retain
one extra carry of device memory per slot while adding no overlap.

Correctness contracts:

* ``submit`` captures the immutable JAX carry *reference* (jax arrays
  are never mutated in place) plus ``next_round``/seeds; the
  device→host transfer runs on the writer thread
  (``runner._host_arrays``), so the main loop's only cost is the
  enqueue.
* The write step is ``runner._write_snapshot`` — the same tmp-file +
  CRC-manifest + atomic-rename + optional-fsync machinery the sync path
  uses, so the on-disk bytes are identical to a sync save (asserted
  per engine in tests/test_ckpt_writer.py) and
  ``load_checkpoint``/resume/rotation need no changes.
* Writer-thread errors are never silently dropped: each failure is
  mirrored into a traced ``checkpoint_write_failed`` event and the
  ``checkpoint_errors`` counter the moment it happens, then re-raised
  on the main thread at the next ``submit`` or the final drain barrier.
* ``drain()`` is the completion barrier: queue empty, in-flight write
  durably renamed, pending error re-raised. ``runner.run`` drains at
  run end and on ANY exception (without masking the original failure),
  so no write is ever in flight when a supervisor retry's resume scans
  the rotation set — and the crash-injection harness forces the same
  barrier before ``faults.on_chunk_end()`` so a ``kill_after_chunk``
  still observes a durably renamed snapshot.

IO accounting (``checkpoint_io``): the main thread owns ``save_s``
(hot-path blocking: enqueue waits + drain waits); the writer thread
owns ``saves / save_hidden_s / pull_s / write_s / bytes_written``.
The two key sets are disjoint, so the shared dict needs no lock.
"""
from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace


@dataclasses.dataclass
class _Job:
    path: Any
    cfg: Any
    carry: Any          # immutable JAX pytree reference; pulled off-thread
    next_round: int
    seeds: np.ndarray
    keep: int
    fsync: bool


_SENTINEL = object()


class CheckpointWriter:
    """One background writer thread behind a depth-1 queue.

    ``io`` (optional) is the runner's ``checkpoint_io`` dict; see the
    module docstring for the key-ownership split that keeps it
    lock-free.
    """

    def __init__(self, io: dict | None = None):
        self._io = io
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._err: BaseException | None = None
        self._lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._loop,
                                        name="ckpt-writer", daemon=True)
        self._thread.start()

    # --- main-thread API -----------------------------------------------------

    def submit(self, path, cfg, carry, next_round: int, *, seeds,
               keep: int = 1, fsync: bool = False) -> float:
        """Enqueue a snapshot; returns the seconds the enqueue blocked.

        Re-raises any pending writer error BEFORE enqueuing (a failed
        write must surface within one chunk, not at run end). Blocks
        when a snapshot is already pending behind the in-flight one —
        the wait lands in ``checkpoint_backpressure_s`` and in the hot
        path's ``save_s``.
        """
        if self._closed:
            raise RuntimeError("submit() on a closed CheckpointWriter")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self._reraise()
        job = _Job(path, cfg, carry, int(next_round), np.asarray(seeds),
                   keep, fsync)
        t0 = time.perf_counter()
        self._q.put(job)
        wait = time.perf_counter() - t0
        obs_metrics.histogram("checkpoint_backpressure_s").observe(wait)
        if self._io is not None:
            self._io["save_s"] += wait
        return wait

    def drain(self) -> None:
        """Block until every submitted snapshot is durably renamed,
        then re-raise the first writer error (if any)."""
        self._q.join()
        self._reraise()

    def close(self, raise_errors: bool = True) -> None:
        """Drain remaining jobs, stop the thread, and (by default)
        re-raise any pending writer error. ``raise_errors=False`` is
        the exception-path variant: it still WAITS for the in-flight
        write — a retry's resume must never race a background write to
        the same rotation set — but lets the caller's original failure
        propagate (the writer error was already mirrored to the trace
        and the ``checkpoint_errors`` counter). Idempotent."""
        if not self._closed:
            self._closed = True
            self._q.put(_SENTINEL)
            self._thread.join()
        if raise_errors:
            self._reraise()

    def _reraise(self) -> None:
        with self._lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    # --- writer thread -------------------------------------------------------

    def _loop(self) -> None:
        # Deferred import: runner imports this module at its top level.
        from . import runner
        while True:
            job = self._q.get()
            try:
                if job is _SENTINEL:
                    return
                self._write(runner, job)
            except BaseException as exc:  # noqa: BLE001 — mirrored + re-raised
                obs_metrics.counter("checkpoint_errors").inc()
                obs_trace.event("checkpoint_write_failed",
                                next_round=job.next_round, error=repr(exc))
                with self._lock:
                    if self._err is None:  # first error wins; later saves
                        self._err = exc    # may still land fine
            finally:
                self._q.task_done()
                # Drop the job reference BEFORE blocking in get(): the
                # written snapshot's carry (a full device-memory pytree
                # — ~GB at flagship scale) must not stay pinned through
                # the next inter-checkpoint compute window.
                job = None

    def _write(self, runner, job: _Job) -> None:
        t0 = time.perf_counter()
        with obs_trace.span("ckpt_snapshot", next_round=job.next_round) as sp:
            arrays = runner._host_arrays(job.carry)
            if sp is not None:
                sp["bytes"] = int(sum(a.nbytes for a in arrays.values()))
        t1 = time.perf_counter()
        with obs_trace.span("ckpt_write", next_round=job.next_round) as sp:
            nbytes = runner._write_snapshot(job.path, job.cfg, arrays,
                                            job.next_round, job.seeds,
                                            job.keep, job.fsync)
            if sp is not None:
                sp["bytes"] = nbytes
        t2 = time.perf_counter()
        obs_metrics.histogram("checkpoint_hidden_s").observe(t2 - t0)
        io = self._io
        if io is not None:
            io["saves"] += 1
            io["save_hidden_s"] += t2 - t0
            io["pull_s"] += t1 - t0
            io["write_s"] += t2 - t1
            io["bytes_written"] += nbytes
