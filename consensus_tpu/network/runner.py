"""Generic batched round-loop runner shared by all four protocol engines.

This is the TPU analog of the reference's `network::Simulator` round loop
(SURVEY.md §3a): the `for round / for node` nest becomes `lax.scan` over
rounds of a `vmap`'d round kernel, compiled once per (config, shapes).
On top of the plain loop it provides, uniformly for every protocol:

  * **mesh sharding** — carry pytrees pinned to a ("sweep", "node")
    `Mesh` via sharding constraints (see consensus_tpu.parallel.mesh);
  * **blocked scan** — `cfg.scan_chunk` splits the round loop into
    fixed-size jitted chunks driven from the host, bounding XLA program
    size and compile time for 1k+ round runs (SURVEY.md §7 hard parts);
  * **checkpoint / resume** — between chunks the carry (a pytree of
    arrays) can be snapshotted to an .npz; a resumed run continues the
    scan at the saved round and produces bit-identical decided logs
    because every round kernel is a pure function of (state, round).
    Snapshots carry a per-leaf CRC32 + manifest checksum and rotate the
    last K files, so a torn/corrupted latest file is detected and
    recovery falls back to the previous rotation (docs/RESILIENCE.md;
    supervised retry/resume lives in network/supervisor.py, the
    crash-injection hooks in network/faults.py).

Engines register an :class:`EngineDef`; no protocol code lives here.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import os
import pathlib
import sys
import time
import zipfile
import zlib
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import Config
from ..obs import metrics as obs_metrics
from ..obs import timeline as obs_timeline
from ..obs import trace as obs_trace
from ..ops import flight as flightlib
from ..parallel import mesh as meshlib
from . import ckpt_writer, faults


@dataclasses.dataclass(frozen=True)
class EngineDef:
    """A protocol engine, as seen by the runner.

    make_carry(cfg, seed) -> carry    # unbatched; vmapped over sweeps
    round_fn(cfg, carry, r) -> carry  # one round; pure; r = absolute round
    extract(batched_carry) -> dict[str, np.ndarray]
    carry_pspec(cfg) -> pytree of PartitionSpec matching the unbatched carry

    Optional on-device telemetry (docs/OBSERVABILITY.md §"Telemetry"):
    round_telem(cfg, carry, r) -> (carry, i32[K]) runs the SAME state
    update as round_fn plus a K-vector of per-round protocol counters
    (K = len(telemetry_names)) reduced from the round's intermediates.
    The vector is accumulated across the scan alongside the carry and
    never feeds back into state, so enabling it is digest-neutral by
    construction (tests/test_obs.py proves bit-identity per engine).

    Optional flight recorder (docs/OBSERVABILITY.md §"Flight recorder"):
    round_flight(cfg, carry, r) -> (carry, i32[K], i32[H, N_BUCKETS])
    extends round_telem with the engine's per-round protocol-latency
    bucket matrix (H = len(latency_names), buckets per
    ops/flight.bucket_counts). Selected by cfg.telemetry_window > 0;
    same digest-neutrality contract (tests/test_flight.py).
    """
    name: str
    make_carry: Callable[..., Any]
    round_fn: Callable[..., Any]
    extract: Callable[[Any], dict[str, Any]]
    carry_pspec: Callable[[Config], Any]
    telemetry_names: tuple[str, ...] = ()
    round_telem: Callable[..., Any] | None = None
    latency_names: tuple[str, ...] = ()
    round_flight: Callable[..., Any] | None = None


def n_windows(cfg: Config) -> int:
    """Static window count of the flight-recorder ring:
    ceil(n_rounds / telemetry_window). Requires telemetry_window > 0."""
    return -(-cfg.n_rounds // cfg.telemetry_window)


def flight_structs(cfg: Config, eng: EngineDef) -> tuple:
    """ShapeDtypeStructs of the flight recorder's (win, lat) arrays for
    ``cfg`` (``telemetry_window`` must be > 0) — the ONE declaration of
    the recorder geometry, shared by :func:`run` (checkpoint template +
    initial zeros) and ``tools/hlocheck``'s recorder-ON lowering, so the
    fingerprinted program cannot drift from the dispatched one."""
    return (jax.ShapeDtypeStruct(
                (cfg.n_sweeps, n_windows(cfg), len(eng.telemetry_names)),
                jnp.int32),
            jax.ShapeDtypeStruct(
                (cfg.n_sweeps, len(eng.latency_names), flightlib.N_BUCKETS),
                jnp.int32))


def make_seeds(cfg: Config) -> np.ndarray:
    """Per-sweep u32 seeds; sweep b uses lo32(seed + b) (docs/SPEC.md §1)."""
    return ((np.uint64(cfg.seed) + np.arange(cfg.n_sweeps, dtype=np.uint64))
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


@functools.partial(jax.jit, static_argnums=(0, 1), static_argnames=("mesh",))
def _init_jit(cfg: Config, eng: EngineDef, seeds, *, mesh=None):
    carry = jax.vmap(lambda s: eng.make_carry(cfg, s))(seeds)
    return meshlib.constrain(carry, cfg, mesh, eng.carry_pspec(cfg))


def _chunk_body(cfg: Config, eng: EngineDef, mesh, pspec, masked: bool,
                telemetry: bool, recorder: bool):
    """Build the shared scan body every chunked dispatch runs — the one
    place the telemetry accumulator and the flight-recorder window ring
    + latency histograms attach to the round loop, for all six engines.

    The scan carry is ``(c, t, w, h)``: engine carry, [B, K] running
    counter totals, [B, n_windows, K] window ring, [B, H, N_BUCKETS]
    latency buckets. ``t``/``w``/``h`` are None (empty pytree nodes —
    zero leaves, nothing traced) below their enabling flag, so the
    telemetry-off and recorder-off programs are byte-for-byte the
    narrower ones (pinned by the recorder-off hlocheck fingerprints).

    The window add is a dynamic-slice + add + dynamic-update-slice at
    window index ``r // telemetry_window`` — O(B·K) per round, never a
    scatter (serial unit) and never an [n_windows]-one-hot.
    """
    W = cfg.telemetry_window

    def shard_sweep(x):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(
                    meshlib.SWEEP_AXIS, *([None] * (x.ndim - 1)))))

    def body(ct, ra):
        c, t, w, h = ct
        if masked:
            r, active = ra
        else:
            r = ra
        if recorder:
            new, d, lh = jax.vmap(
                lambda s: eng.round_flight(cfg, s, r))(c)
            if masked:  # the dead lane must not double-count
                d = jnp.where(active, d, jnp.zeros_like(d))
                lh = jnp.where(active, lh, jnp.zeros_like(lh))
            t = shard_sweep(t + d)
            wi = r // jnp.int32(W)
            z = jnp.int32(0)
            cur = jax.lax.dynamic_slice(
                w, (z, wi, z), (w.shape[0], 1, w.shape[2]))
            w = shard_sweep(jax.lax.dynamic_update_slice(
                w, cur + d[:, None, :], (z, wi, z)))
            h = shard_sweep(h + lh)
        elif telemetry:
            new, d = jax.vmap(lambda s: eng.round_telem(cfg, s, r))(c)
            if masked:  # the dead lane must not double-count
                d = jnp.where(active, d, jnp.zeros_like(d))
            t = shard_sweep(t + d)
        else:
            new = jax.vmap(lambda s: eng.round_fn(cfg, s, r))(c)
        if masked:
            new = jax.tree.map(lambda a, b: jnp.where(active, a, b), new, c)
        return (meshlib.constrain(new, cfg, mesh, pspec), t, w, h), None

    return body


@functools.partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("mesh",),
                   donate_argnums=(3, 5, 6, 7))
def _chunk_jit(cfg: Config, eng: EngineDef, n_rounds: int, carry, r0,
               telem=None, win=None, lat=None, *, mesh=None):
    """Advance the batched carry by ``n_rounds`` rounds starting at ``r0``.

    The carry (and the telemetry accumulator + flight-recorder arrays,
    when present) is DONATED: every input leaf has a same-shape/dtype
    output leaf, so XLA aliases the buffers (``input_output_alias`` in
    the compiled module — statically enforced by ``tools/hlocheck``'s
    donation contract) and a chunked run holds ONE carry instead of two
    across dispatches — the ROADMAP bandwidth lever at 100k-node
    carries. Consequences at the call sites: the passed-in carry is dead
    after the call (callers must rebind, which they all did already),
    and any reference that must outlive the next dispatch — the async
    checkpoint writer's pending snapshot — must be a copy (see
    :func:`_snapshot_copy`). Inside an outer jit trace
    (``__graft_entry__.entry``) donation is inert.

    The round body must stay inside a scan of length >= 2: XLA unrolls a
    length-1 scan into the top-level computation, and the CPU backend's
    codegen of the unrolled round kernel is pathological (minutes for a
    body that compiles in ~2s inside a while loop — measured 2026-07-29).
    A 1-round chunk therefore scans a masked pair: round r0, then a
    dead lane whose output is discarded leaf-wise.

    ``telem`` (optional, [B, K] i32) switches the scan body (built by
    :func:`_chunk_body`) to ``eng.round_telem`` and rides the scan carry
    as a running per-sweep counter accumulator; the return becomes
    ``(carry, telem)``. ``win``/``lat`` (optional, [B, n_windows, K] /
    [B, H, N_BUCKETS] i32 — passed together, with ``telem``) switch to
    ``eng.round_flight`` and additionally accumulate the window ring and
    latency histograms; the return becomes ``(carry, telem, win, lat)``.
    With the defaults the call and return shapes are unchanged — callers
    predating telemetry (tests, __graft_entry__) keep working verbatim,
    and the no-telemetry / no-recorder programs are byte-for-byte the
    pre-feature ones (nothing new is traced; None arguments carry zero
    pytree leaves).
    """
    pspec = eng.carry_pspec(cfg)
    telemetry = telem is not None
    recorder = win is not None
    if recorder and (lat is None or not telemetry):
        raise ValueError("the flight recorder rides the telemetry "
                         "accumulator: pass telem, win AND lat together")
    # Only the padded 1-round chunk needs the dead-lane select; for real
    # chunks every scan step is live, and a full-carry jnp.where per round
    # costs measurable HBM traffic (bench.py ran ~25% under the bare
    # kernel before this was made conditional).
    masked = n_rounds == 1
    body = _chunk_body(cfg, eng, mesh, pspec, masked, telemetry, recorder)

    if masked:
        xs = (jnp.stack([r0, r0]), jnp.asarray([True, False]))
    else:
        xs = r0 + jnp.arange(n_rounds, dtype=jnp.int32)
    (carry, telem, win, lat), _ = jax.lax.scan(
        body, (carry, telem, win, lat), xs)
    if recorder:
        return carry, telem, win, lat
    return (carry, telem) if telemetry else carry


def _snapshot_copy(carry):
    """Device-side copy of the carry for the async checkpoint writer.

    ``_chunk_jit`` donates its carry, so the buffers a pending snapshot
    references are reused by the very next dispatch — the writer thread's
    device→host pull would race the overwrite (jax surfaces it as
    "Array has been deleted", but only when the dispatch wins). The copy
    is dispatched asynchronously BEFORE that donation, ordered on the
    device stream, so the writer owns stable buffers while the original
    is recycled. Costs one carry of HBM traffic per checkpoint interval
    — the donation saves the same amount on every round in between.
    Sharding is preserved leaf-wise (``jnp.copy`` keeps it)."""
    return jax.tree.map(jnp.copy, carry)


@jax.jit
def _sync_elem(a):
    """First element of ``a`` — the O(1)-byte device-completion witness
    run_device's sync barrier transfers (see comment there)."""
    return a.ravel()[0]


# --- checkpointing -----------------------------------------------------------
#
# Format (docs/RESILIENCE.md): one .npz per snapshot holding the carry
# leaves (leaf_0..leaf_{n-1}) plus a JSON ``__meta__`` record:
#
#   {"config": {...}, "next_round": R, "seeds": [...],
#    "integrity": {"leaf_crc32": [...],    # CRC32 of each leaf's raw bytes
#                  "manifest_crc32": C}}   # CRC32 over (config, next_round,
#                                          #   seeds, leaf_crc32) — canonical
#                                          #   sorted-key JSON
#
# Writes are atomic (tmp + rename) and rotate the last ``keep`` snapshots
# (ckpt.npz, ckpt.1.npz, ...); loads scan newest -> oldest and return the
# first snapshot that is both INTACT (zip readable, manifest + per-leaf
# checksums verify) and MATCHING (config / seed vector), so a torn or
# bit-rotted latest file costs one rotation of progress, not the run.
# Pre-integrity-era snapshots (no "integrity" key) are accepted as-is.


class CheckpointError(Exception):
    """A snapshot file exists but is unreadable or fails its checksums
    (torn write, truncation, bit rot, stale manifest)."""


def _leaf_crc(a) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _manifest_crc(config: dict, next_round: int, seeds: list[int],
                  leaf_crc32: list[int]) -> int:
    return zlib.crc32(json.dumps(
        {"config": config, "next_round": next_round, "seeds": seeds,
         "leaf_crc32": leaf_crc32}, sort_keys=True).encode())


def rotation_path(path: str | os.PathLike, i: int) -> pathlib.Path:
    """The i-th rotated snapshot of ``path``: ckpt.npz -> ckpt.{i}.npz
    (i=0 is ``path`` itself)."""
    p = pathlib.Path(path)
    return p if i == 0 else p.with_name(f"{p.stem}.{i}{p.suffix}")


def checkpoint_candidates(path) -> list[pathlib.Path]:
    """Existing snapshot paths for ``path``, newest first.

    Tolerates ONE missing rung before stopping: save_checkpoint's
    rotation is a sequence of single renames, so a kill mid-rotation
    leaves exactly one hole (most commonly index 0, killed between the
    rotate and the final tmp rename) — the still-valid older rungs
    behind it must stay reachable or the "torn latest leaves a
    fallback" guarantee dies in precisely the crash window it exists
    for. Two consecutive missing indices mean the set really ends;
    anything beyond is leftover from an unrelated older run (and would
    be config-checked anyway)."""
    out, i, misses = [], 0, 0
    while misses < 2:
        p = rotation_path(path, i)
        if p.exists():
            out.append(p)
            misses = 0
        else:
            misses += 1
        i += 1
    return out


def _fsync_file(path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path) -> None:
    # Directory fsync makes the rename itself durable; not every
    # filesystem supports an O_RDONLY open+fsync on a directory — treat
    # a refusal as "nothing to sync" rather than failing the save.
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _host_arrays(carry) -> dict[str, np.ndarray]:
    """The snapshot PULL step: the batched carry's leaves as contiguous
    host arrays under the format's ``leaf_i`` naming. This is where the
    device→host transfer blocks — the async writer
    (:mod:`consensus_tpu.network.ckpt_writer`) runs it off-thread so the
    chunk loop never waits on it."""
    leaves, _ = jax.tree.flatten(carry)
    return {f"leaf_{i}": np.ascontiguousarray(x)
            for i, x in enumerate(leaves)}


def _write_npz(path, arrays: dict) -> None:
    """npz container write with pinned zip timestamps.

    ``np.savez`` stamps each member with the wall-clock mtime, so two
    saves of identical state differ in bytes across a 2-second DOS-time
    boundary. Pinning ``date_time`` makes a snapshot's bytes a pure
    function of (arrays, meta) — which is what lets the async-vs-sync
    byte-identity contract be TESTED, not just argued. Same container
    otherwise (STORED members, zip64 allowed); ``np.load`` and the zip
    member CRCs behave identically.
    """
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED,
                         allowZip64=True) as zf:
        for name, val in arrays.items():
            info = zipfile.ZipInfo(name + ".npy",
                                   date_time=(1980, 1, 1, 0, 0, 0))
            with zf.open(info, "w", force_zip64=True) as fp:
                np.lib.format.write_array(fp, np.asanyarray(val),
                                          allow_pickle=False)


def _write_snapshot(path, cfg: Config, arrays: dict, next_round: int,
                    seeds, keep: int, fsync: bool) -> int:
    """The snapshot WRITE step, shared verbatim by the sync path
    (:func:`save_checkpoint`) and the async writer: CRC manifest, tmp
    file, rotation ladder, atomic rename, optional fsync — so the
    on-disk bytes are identical no matter which thread wrote them.
    ``arrays`` is :func:`_host_arrays`' dict (already host-resident).
    Returns the snapshot's byte size."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".tmp.npz")
    config = json.loads(cfg.to_json())
    seed_list = [int(s) for s in np.asarray(seeds)]
    leaf_crc32 = [_leaf_crc(arrays[f"leaf_{i}"])
                  for i in range(len(arrays))]
    meta = {"config": config, "next_round": next_round,
            "seeds": seed_list,
            "integrity": {
                "leaf_crc32": leaf_crc32,
                "manifest_crc32": _manifest_crc(config, next_round,
                                                seed_list, leaf_crc32)}}
    _write_npz(tmp, {"__meta__": np.frombuffer(json.dumps(meta).encode(),
                                               dtype=np.uint8), **arrays})
    nbytes = tmp.stat().st_size
    if fsync:
        _fsync_file(tmp)
    faults.on_checkpoint_write()  # test seam: SIGKILL mid-write window
    for i in range(keep - 1, 0, -1):
        src = rotation_path(path, i - 1)
        if src.exists():
            src.replace(rotation_path(path, i))
    tmp.replace(path)
    if fsync:
        _fsync_dir(path.parent)
    obs_metrics.counter("checkpoint_saves_total").inc()
    obs_metrics.counter("checkpoint_bytes_written_total").inc(nbytes)
    return nbytes


def save_checkpoint(path, cfg: Config, carry, next_round: int, seeds=None,
                    keep: int = 1,
                    fsync: bool = False) -> dict[str, int | float]:
    """Snapshot the batched carry after ``next_round`` rounds have run,
    synchronously on the calling thread (the async pipeline in
    :mod:`consensus_tpu.network.ckpt_writer` composes the same two steps
    — :func:`_host_arrays` then :func:`_write_snapshot` — off-thread).

    ``seeds`` records the per-sweep seed vector the carry was produced
    with (default: ``make_seeds(cfg)``) so a resume under different
    explicit seeds is detected as a mismatch, not silently continued.

    ``keep`` retains the last ``keep`` snapshots: before the atomic
    tmp+rename of the new file, existing snapshots rotate
    ckpt.npz -> ckpt.1.npz -> ... -> ckpt.{keep-1}.npz (the oldest is
    dropped). Every step is a single rename, so a kill at any point
    leaves only whole files — recovery never sees a half-rotated state
    worse than one missing rung.

    ``fsync=True`` (docs/RESILIENCE.md §2b) additionally fsyncs the tmp
    file's bytes BEFORE the renames and the directory entry AFTER them,
    closing the power-loss window where a rename becomes durable while
    the file content it points at never hit disk. Off by default: a
    process kill (the common failure) can't produce that state, and on
    network filesystems the sync can dominate the save.

    Returns ``{"bytes", "wall_s", "pull_s", "write_s"}`` — total wall
    plus the device→host-pull vs container-write split (recorded as
    metrics and, via the runner, in
    ``RunResult.extras["checkpoint_io"]``).
    """
    t0 = time.perf_counter()
    with obs_trace.span("checkpoint_save", next_round=next_round) as sp:
        seeds = make_seeds(cfg) if seeds is None else np.asarray(seeds)
        arrays = _host_arrays(carry)
        t_pull = time.perf_counter()
        nbytes = _write_snapshot(path, cfg, arrays, next_round, seeds,
                                 keep, fsync)
        t_write = time.perf_counter()
        if sp is not None:
            sp["bytes"] = nbytes
    wall = time.perf_counter() - t0
    obs_metrics.histogram("checkpoint_save_s").observe(wall)
    return {"bytes": nbytes, "wall_s": wall, "pull_s": t_pull - t0,
            "write_s": t_write - t_pull}


def _read_verified(path):
    """Read one snapshot file; return (meta, leaves: list[np.ndarray]).

    Raises :class:`CheckpointError` when the file is unreadable or its
    recorded checksums don't verify. Snapshots without an "integrity"
    record (pre-manifest era) are read as-is — the zip container's own
    member CRCs still cover gross corruption for those.
    """
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            integ = meta.get("integrity")
            n = (len(integ["leaf_crc32"]) if integ
                 else len(z.files) - 1)
            leaves = [np.asarray(z[f"leaf_{i}"]) for i in range(n)]
    except (zipfile.BadZipFile, zlib.error, OSError, EOFError, KeyError,
            ValueError) as exc:  # ValueError covers JSON/Unicode decode
        raise CheckpointError(f"{path}: unreadable snapshot: {exc!r}")
    if integ:
        want = _manifest_crc(meta.get("config"), meta.get("next_round"),
                             meta.get("seeds"), integ["leaf_crc32"])
        if integ.get("manifest_crc32") != want:
            raise CheckpointError(f"{path}: manifest checksum mismatch")
        for i, (leaf, crc) in enumerate(zip(leaves, integ["leaf_crc32"])):
            if _leaf_crc(leaf) != crc:
                raise CheckpointError(f"{path}: leaf_{i} checksum mismatch")
    return meta, leaves


def _meta_matches(meta: dict, cfg: Config, seeds) -> bool:
    """Does a verified snapshot's meta belong to (cfg, seeds)?"""
    # Round-trip the saved dict through Config so a field added to
    # the schema AFTER the snapshot was written compares at its
    # default (a pre-sweep_chunk checkpoint ran with sweep_chunk=0
    # semantics by definition) instead of silently invalidating
    # every existing checkpoint via a key-for-key dict mismatch.
    # Keys NOT in the current schema mean the snapshot came from a
    # *newer* (or foreign) semantics — reject those rather than
    # resume a carry whose meaning we can't represent; likewise a
    # saved config today's validation refuses is a mismatch, not a
    # crash.
    saved = {k: v for k, v in meta["config"].items() if k != "_cutoffs"}
    if not set(saved) <= {f.name for f in dataclasses.fields(Config)}:
        return False
    try:
        # telemetry_window is an observability knob, not trajectory
        # identity: the carry a recorder-off run saved IS the carry a
        # recorder-on run would have (digest-neutral by construction).
        # ACROSS the on/off boundary the fields compare normalized and
        # the ring's presence is settled at the leaf-count level
        # (load_checkpoint's schema skip), where a mismatch degrades
        # loudly instead of silently rejecting every cross-setting
        # snapshot. Between two recorder-ON runs, though, W is the
        # series' bin geometry — the saved ring's windows mean rounds
        # [i*W_saved, ...) — so differing nonzero values are a real
        # mismatch: equal n_windows could otherwise resume a ring whose
        # bins this run would extend at a different width.
        saved_cfg = Config.from_json(json.dumps(saved))
        if saved_cfg.telemetry_window == 0 or cfg.telemetry_window == 0:
            saved_cfg = dataclasses.replace(saved_cfg, telemetry_window=0)
            want_cfg = dataclasses.replace(cfg, telemetry_window=0)
        else:
            want_cfg = cfg
        if saved_cfg != want_cfg:
            return False
    except (ValueError, TypeError):
        return False
    want = make_seeds(cfg) if seeds is None else np.asarray(seeds)
    have = meta.get("seeds")
    have = make_seeds(cfg) if have is None else np.asarray(have)
    return bool(np.array_equal(want.astype(np.uint32),
                               have.astype(np.uint32)))


def _log_ckpt(msg: str) -> None:
    print(f"checkpoint: {msg}", file=sys.stderr, flush=True)


def _scan_valid(path, cfg: Config, seeds):
    """Yield (meta, leaves) for each intact AND matching snapshot of
    ``path``, newest rotation first; warn (stderr) on corrupt files."""
    for cand in checkpoint_candidates(path):
        try:
            meta, leaves = _read_verified(cand)
        except CheckpointError as exc:
            _log_ckpt(f"{exc} — trying older rotation")
            continue
        if _meta_matches(meta, cfg, seeds):
            yield cand, meta, leaves


def load_checkpoint(path, cfg: Config, eng: EngineDef, seeds=None, *,
                    io: dict | None = None, recorder_template=None):
    """Return (carry, next_round) from the newest VALID snapshot of
    ``path`` — or None when no rotation is both intact and matching.

    ``recorder_template`` (a tuple of ShapeDtypeStructs for the flight
    recorder's window ring + latency histograms) declares that the
    caller snapshots ``(carry, win, lat)`` tuples instead of the bare
    carry; the returned first element is then that tuple. A snapshot
    whose leaf count disagrees — written with the recorder off and
    loaded with it on, or vice versa — is skipped LOUDLY via the
    schema-skip path below (the run restarts from round 0 with a
    stderr message), never a pytree/shape crash
    (tests/test_flight.py pins both directions).

    ``seeds`` is the seed vector the caller will resume under (default
    ``make_seeds(cfg)``); a snapshot taken under a different vector is a
    mismatch — its carry belongs to other trajectories. Snapshots from
    before seeds were recorded compare at ``make_seeds(cfg)``, which is
    what they necessarily ran with.

    A torn/corrupted rotation (checksum or container failure) is
    skipped with a warning and the next-oldest is tried: recovery costs
    one rotation of progress, never the whole run.

    ``io`` (optional dict with loads/load_s/bytes_read keys) accumulates
    the wall time and npz byte size of a successful load — the
    checkpoint-IO record surfaced via ``RunResult.extras``.
    """
    t0 = time.perf_counter()
    with obs_trace.span("checkpoint_load") as sp:
        for cand, meta, leaves in _scan_valid(path, cfg, seeds):
            if cand != pathlib.Path(path):
                _log_ckpt(f"recovered from rotation {cand} "
                          f"(round {meta['next_round']})")
            template = jax.eval_shape(
                lambda s: _init_template(cfg, eng, s),
                jax.ShapeDtypeStruct((cfg.n_sweeps,), jnp.uint32))
            if recorder_template is not None:
                template = (template,) + tuple(recorder_template)
            # Cast to the template dtypes: an engine may narrow a state
            # field's storage dtype between versions (e.g. raft match/next
            # i32 -> u8); the saved integer values are identical, but
            # lax.scan requires the carry dtype to match what round_fn
            # returns.
            tleaves = jax.tree.leaves(template)
            if len(leaves) != len(tleaves):
                # A carry schema from another era: a state field added
                # since the snapshot was written (SPEC §6c's `down`
                # mask), or a flight-recorder on/off mismatch (the ring
                # + histogram leaves ride the snapshot only when
                # telemetry_window > 0). The saved trajectory is still
                # valid but its pytree can't be unflattened into this
                # run's carry: treat as not-my-snapshot and try the
                # next rotation — a loud degradation, not a shape
                # crash.
                _log_ckpt(f"{cand}: carry has {len(leaves)} leaves, "
                          f"this run expects {len(tleaves)} (carry "
                          f"schema from another era — e.g. a flight-"
                          f"recorder on/off mismatch) — skipping")
                continue
            shape_drift = [(np.asarray(leaf).shape, t.shape)
                           for leaf, t in zip(leaves, tleaves)
                           if np.asarray(leaf).shape != t.shape]
            if shape_drift:
                # Same leaf COUNT but a different leaf shape. W-vs-W
                # recorder mismatches are already settled upstream
                # (_meta_matches rejects differing nonzero
                # telemetry_window); this is the defensive backstop
                # for any OTHER same-arity geometry drift (an engine
                # reshaping a state field between versions, a foreign
                # snapshot). Unflattening would silently corrupt the
                # carry, so skip loudly instead.
                got, want = shape_drift[0]
                _log_ckpt(f"{cand}: carry leaf shape {got} != expected "
                          f"{want} (e.g. a flight-recorder window-"
                          f"geometry mismatch) — skipping")
                continue
            leaves = [np.asarray(leaf).astype(t.dtype)
                      for leaf, t in zip(leaves, tleaves)]
            treedef = jax.tree.structure(template)
            nbytes = cand.stat().st_size
            wall = time.perf_counter() - t0
            if sp is not None:
                sp["bytes"] = nbytes
                sp["next_round"] = meta["next_round"]
            obs_metrics.counter("checkpoint_loads_total").inc()
            obs_metrics.counter("checkpoint_bytes_read_total").inc(nbytes)
            obs_metrics.histogram("checkpoint_load_s").observe(wall)
            if io is not None:
                io["loads"] += 1
                io["load_s"] += wall
                io["bytes_read"] += nbytes
            return jax.tree.unflatten(treedef, leaves), meta["next_round"]
    return None


def peek_checkpoint(path, cfg: Config, seeds=None):
    """``next_round`` of the snapshot :func:`load_checkpoint` would
    resume from (newest intact + matching rotation), or None.

    Runs the FULL validation load_checkpoint runs — container, manifest
    and per-leaf checksums, config and seed match — so its answer
    exactly predicts a subsequent load; it only skips the dtype-cast /
    unflatten epilogue. That makes it a full snapshot read: use it as a
    diagnostic probe, not on a hot path (the supervisor reads each
    attempt's start round from ``stats`` instead)."""
    for _, meta, _ in _scan_valid(path, cfg, seeds):
        return meta["next_round"]
    return None


def _init_template(cfg, eng, seeds):
    return jax.vmap(lambda s: eng.make_carry(cfg, s))(seeds)


# --- the run loop ------------------------------------------------------------

def _sweep_groups(cfg: Config, seeds=None):
    """Split ``cfg`` into (sub-config, seed-slice) groups of at most
    ``cfg.sweep_chunk`` sweeps, or None when the run is one program.
    An explicit ``seeds`` vector is sliced instead of regenerated.

    The one-program seed vector (docs/SPEC.md §1: sweep b ⇒
    lo32(seed + b)) is sliced positionally, so grouping can never change
    any sweep's trajectory — only which XLA program hosts it. Every
    full-size group shares one sub-config (the parent's seed field,
    unused when explicit seeds are passed), so jit re-traces once, not
    once per group; only a ragged tail adds a second program.
    """
    g = cfg.sweep_chunk
    if not g or g >= cfg.n_sweeps:
        return None
    seeds = make_seeds(cfg) if seeds is None else _check_seeds(cfg, seeds)
    return [(dataclasses.replace(cfg, n_sweeps=min(g, cfg.n_sweeps - s),
                                 sweep_chunk=0), seeds[s:s + g])
            for s in range(0, cfg.n_sweeps, g)]


def _check_groups(cfg: Config, groups, mesh):
    """Fail fast on an unshardable group — in particular a ragged tail
    whose size the mesh sweep axis doesn't divide — BEFORE any group
    runs, not after minutes of device time on the full-size groups."""
    if mesh is None and cfg.mesh_shape:
        mesh = meshlib.make_mesh(cfg.mesh_shape)
    for sub, _ in groups:
        meshlib.check_divisible(sub, mesh)
    return mesh


def _concat_carries(carries):
    return jax.tree.map(lambda *leaves: jnp.concatenate(leaves, axis=0),
                        *carries)


def _check_seeds(cfg: Config, seeds):
    """An explicit seed vector must cover exactly cfg.n_sweeps — a short
    one would silently shrink the batch while callers report throughput
    and digests for the configured sweep count (no silent ignores)."""
    seeds = np.asarray(seeds)
    if seeds.shape != (cfg.n_sweeps,):
        raise ValueError(f"seeds shape {seeds.shape} != (n_sweeps,) = "
                         f"({cfg.n_sweeps},)")
    return seeds


def _prepare(cfg: Config, eng: EngineDef, mesh, seeds=None):
    """Shared setup: resolve the mesh, check shardability, shard seeds."""
    if mesh is None and cfg.mesh_shape:
        mesh = meshlib.make_mesh(cfg.mesh_shape)
    meshlib.check_divisible(cfg, mesh)
    seeds = jnp.asarray(make_seeds(cfg) if seeds is None
                        else _check_seeds(cfg, seeds))
    if mesh is not None:
        seeds = jax.device_put(seeds, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(meshlib.SWEEP_AXIS)))
    return mesh, seeds


# Telemetry counters that measure COMMIT progress — derived from the
# timeline layer's per-engine declaration so the live -v progress line
# and the derived availability/stall metrics can never rate different
# counters (obs/timeline is numpy-only at import; no cycle).
PROGRESS_COUNTERS = frozenset(
    name for names in obs_timeline.COMMIT_COUNTERS.values()
    for name in names)


def _progress_info(cfg: Config, eng: EngineDef, r: int, n: int, telem, win,
                   prev_total: int) -> tuple[dict, int]:
    """The live-progress datum after an ``n``-round chunk ended at round
    ``r``: the current commit rate (per round, summed over sweeps) read
    off the flight recorder's LIVE window when present, else the last
    chunk's delta of the running telemetry totals. Pulls only O(B·K)
    bytes, but the pull IS a device sync — this only runs under an
    installed progress callback (-v)."""
    idx = [k for k, name in enumerate(eng.telemetry_names)
           if name in PROGRESS_COUNTERS]
    info: dict = {"round": r, "n_rounds": cfg.n_rounds}
    if win is not None:
        W = cfg.telemetry_window
        wi = (r - 1) // W
        row = np.asarray(win[:, wi, :])          # [B, K] — the live window
        in_window = (r - 1) % W + 1
        info["window"] = (wi, int(win.shape[1]))
        info["commit_rate"] = float(row[:, idx].sum()) / in_window
        return info, prev_total
    total = int(np.asarray(telem)[:, idx].sum()) if telem is not None else 0
    info["commit_rate"] = None if prev_total < 0 or telem is None else \
        (total - prev_total) / n
    return info, total


def _advance(cfg: Config, eng: EngineDef, carry, start: int, chunk: int,
             mesh, checkpoint_path=None, seeds=None, keep: int = 1,
             telem=None, io: dict | None = None, fsync: bool = False,
             writer=None, win=None, lat=None, progress=None):
    """Drive fixed-shape jitted chunks from ``start`` to ``cfg.n_rounds``.
    Returns ``(carry, telem, win, lat)`` — ``telem`` is the accumulated
    [B, K] telemetry counters, ``win``/``lat`` the flight recorder's
    [B, n_windows, K] window ring and [B, H, N_BUCKETS] latency buckets
    (None for whichever layer is off).

    With ``writer`` (a :class:`ckpt_writer.CheckpointWriter`) snapshots
    are ENQUEUED and written in the background while the next chunk
    dispatches — the hot path pays only the enqueue (plus backpressure
    when the disk falls a full snapshot behind). Without one, saves run
    synchronously on this thread (``sync_checkpoints=True``, the
    pre-async behavior).

    The two ``faults`` hooks are the crash-injection harness's seams
    (one ``is None`` check each when no plan is installed): a transient
    error fires BEFORE a chunk dispatches; a kill fires AFTER a chunk
    completes and its checkpoint (if any) is durably on disk — with an
    async writer the harness forces a drain barrier first, so the
    kill-after-durable-snapshot contract survives the overlap.

    Each chunk dispatch is traced as a "dispatch" span and fed into the
    ``dispatch_wall_s`` histogram. The measured quantity is the HOST
    time inside the dispatch call — on an async backend device work may
    continue past it; any subsequent checkpoint pull (a device→host
    transfer) absorbs the remainder, which with the async writer now
    happens on the writer thread (the ``ckpt_snapshot`` span).

    After every chunk the ``rounds_completed`` and ``sim_eta_s`` gauges
    are updated (the sweep-service job-status datum — readable from a
    ``--metrics-out`` snapshot of a still-running process); ``progress``
    (a callable taking one info dict) additionally receives the live
    commit rate per chunk — see :func:`_progress_info` for the device
    sync it costs, which is why it only rides ``-v``.

    With the flight recorder on (``win``/``lat`` arrays passed), mid-run
    snapshots hold the ``(carry, win, lat)`` TUPLE — the window ring
    resumes with the carry so a recovered run's series covers the whole
    trajectory (tests/test_flight.py), while the plain telemetry totals
    stay deliberately un-checkpointed (they cover executed rounds).
    """
    r = start
    t_loop = time.perf_counter()
    prev_total = -1
    while r < cfg.n_rounds:
        faults.on_dispatch()
        n = min(chunk, cfg.n_rounds - r)
        t0 = time.perf_counter()
        with obs_trace.span("dispatch", engine=eng.name, r0=r, n_rounds=n):
            if win is not None:
                carry, telem, win, lat = _chunk_jit(
                    cfg, eng, n, carry, jnp.int32(r), telem, win, lat,
                    mesh=mesh)
            elif telem is None:
                carry = _chunk_jit(cfg, eng, n, carry, jnp.int32(r),
                                   mesh=mesh)
            else:
                carry, telem = _chunk_jit(cfg, eng, n, carry, jnp.int32(r),
                                          telem, mesh=mesh)
        obs_metrics.histogram("dispatch_wall_s").observe(
            time.perf_counter() - t0)
        r += n
        obs_metrics.gauge("rounds_completed").set(r)
        elapsed = time.perf_counter() - t_loop
        eta = elapsed / (r - start) * (cfg.n_rounds - r)
        obs_metrics.gauge("sim_eta_s").set(round(eta, 3))
        if progress is not None:
            info, prev_total = _progress_info(cfg, eng, r, n, telem, win,
                                              prev_total)
            info["eta_s"] = eta
            progress(info)
        if checkpoint_path and r < cfg.n_rounds:
            snap = (carry, win, lat) if win is not None else carry
            if writer is not None:
                # The writer's pull overlaps the NEXT dispatch, which
                # donates (and so recycles) this carry's buffers — hand
                # the writer its own copy (see _snapshot_copy).
                writer.submit(checkpoint_path, cfg, _snapshot_copy(snap),
                              r, seeds=seeds, keep=keep, fsync=fsync)
            else:
                rec = save_checkpoint(checkpoint_path, cfg, snap, r,
                                      seeds=seeds, keep=keep, fsync=fsync)
                if io is not None:
                    io["saves"] += 1
                    io["save_s"] += rec["wall_s"]
                    io["pull_s"] += rec["pull_s"]
                    io["write_s"] += rec["write_s"]
                    io["bytes_written"] += rec["bytes"]
        if writer is not None and faults.plan_active():
            # Crash-injection contract (docs/RESILIENCE.md): the kill
            # hook below must observe this chunk's snapshot durably
            # renamed, so the harness forces the drain barrier the
            # production path deliberately skips.
            t0 = time.perf_counter()
            writer.drain()
            if io is not None:
                io["save_s"] += time.perf_counter() - t0
        faults.on_chunk_end()
    return carry, telem, win, lat


def run_device(cfg: Config, eng: EngineDef, *, mesh=None, seeds=None):
    """Advance a fresh batched carry through ``cfg.n_rounds`` rounds and
    return it ON DEVICE, synchronized via the smallest extract leaf.

    Benchmarks use this instead of :func:`run` so the timed quantity is
    the simulation itself: with the chip behind a remote tunnel, pulling
    the full final state (logs are ~MBs per sweep) costs more wall time
    than a 1k-round scan, and the decided-log extraction is a one-time
    epilogue, not part of the per-round metric (BASELINE.json:2).
    """
    groups = _sweep_groups(cfg, seeds)
    if groups is not None:
        mesh = _check_groups(cfg, groups, mesh)
        carry = _concat_carries([run_device(sub, eng, mesh=mesh, seeds=s)
                                 for sub, s in groups])
        # The per-group barriers don't cover the concat itself — sync on
        # the concatenated result too, or the contract ("returned ON
        # DEVICE, synchronized") breaks and timed callers leak this
        # round's concat work into the next timed window.
        np.asarray(_sync_elem(jax.tree.leaves(carry)[0]))
        return carry
    mesh, seeds = _prepare(cfg, eng, mesh, seeds)
    carry = _init_jit(cfg, eng, seeds, mesh=mesh)
    carry, _, _, _ = _advance(cfg, eng, carry, 0,
                              cfg.scan_chunk or cfg.n_rounds, mesh)
    # Sync barrier, O(1) bytes: transfer a jitted 1-element slice of a
    # final-carry leaf. The slice program has a data dependency on the
    # whole round loop, so its 4-byte result reaching the host proves
    # the computation finished. Two prior barriers were dishonest here
    # (caught 2026-07-30): pulling the *smallest extract leaf* is O(N·S)
    # for paxos (100 MB at 10k×10k — the "benchmark" measured the tunnel
    # at ~27 s/run vs ~0.25 s of device time), and
    # jax.block_until_ready returns BEFORE device completion on the
    # tunnel backend (timings collapse to ~0 — not a barrier at all).
    np.asarray(_sync_elem(jax.tree.leaves(carry)[0]))
    return carry


def _empty_io() -> dict[str, int | float]:
    # save_s = time the CHUNK LOOP was blocked for checkpointing (the
    # full save wall when sync; enqueue + backpressure + drain waits
    # when async). save_hidden_s = writer-thread time overlapped with
    # compute (0 when sync), split into pull_s (device→host) + write_s
    # (container + rename [+ fsync]); sync saves fill the same split.
    return {"saves": 0, "save_s": 0.0, "save_hidden_s": 0.0,
            "pull_s": 0.0, "write_s": 0.0, "bytes_written": 0,
            "loads": 0, "load_s": 0.0, "bytes_read": 0}


# --- grouped-sweep checkpoint layout -----------------------------------------
#
# A grouped run is a sequence of independent sub-runs, so its resumable
# layout is one checkpoint SUBDIRECTORY per group (rotations never
# collide across groups) plus a manifest naming the groups that
# finished:
#
#   root/group_0000/ck.npz (+ rotations)   <- snapshots; the last one is
#   root/group_0001/ck.npz ...                the group's FINAL carry
#   root/groups.json                       <- completed-group manifest
#
# run(group_dir=..., resume=True) drives recovery from this layout:
# each group resumes from its own newest valid rotation — a COMPLETED
# group's final snapshot (written at next_round == n_rounds as it
# finished) loads and executes ZERO rounds, so completed groups are
# skipped at the cost of one load; the first incomplete group resumes
# mid-scan from its last mid-run snapshot; untouched groups start
# fresh. Bit-identity is inherited from the ungrouped resume contract
# (every snapshot validates against its OWN sub-config + seed slice).
# The manifest cross-checks run identity (config + full-seed-vector
# CRC) and records which groups completed.

GROUP_MANIFEST_VERSION = 1


def group_checkpoint_path(root, group_index: int) -> pathlib.Path:
    """The snapshot path for group ``group_index`` under ``root``."""
    return pathlib.Path(root) / f"group_{group_index:04d}" / "ck.npz"


def _group_manifest_path(root) -> pathlib.Path:
    return pathlib.Path(root) / "groups.json"


def _seeds_crc(seeds) -> int:
    return zlib.crc32(np.ascontiguousarray(
        np.asarray(seeds, dtype=np.uint32)).tobytes())


def write_group_manifest(root, cfg: Config, seeds, completed: list[int],
                         n_groups: int) -> None:
    """Atomically record which sweep groups of ``cfg`` have completed.
    ``seeds`` is the FULL per-sweep seed vector (its CRC guards a future
    resume against a mislabeled manifest, like snapshot seed vectors)."""
    root = pathlib.Path(root)
    root.mkdir(parents=True, exist_ok=True)
    doc = {"version": GROUP_MANIFEST_VERSION,
           "config": json.loads(cfg.to_json()),
           "seeds_crc32": _seeds_crc(seeds),
           "n_groups": int(n_groups),
           "completed": sorted(int(i) for i in completed)}
    path = _group_manifest_path(root)
    tmp = path.with_suffix(".tmp.json")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True))
    tmp.replace(path)


def read_group_manifest(root, cfg: Config, seeds=None):
    """Completed group indices recorded under ``root`` for (cfg, seeds)
    — or None when the manifest is missing, unreadable, or belongs to a
    different run (config or seed-vector mismatch, like
    :func:`load_checkpoint`'s not-my-snapshot rule)."""
    try:
        doc = json.loads(_group_manifest_path(root).read_text())
    except (OSError, ValueError):
        return None
    if doc.get("version") != GROUP_MANIFEST_VERSION:
        return None
    if not _meta_matches({"config": doc.get("config", {})}, cfg, None):
        return None
    seeds = make_seeds(cfg) if seeds is None else np.asarray(seeds)
    if doc.get("seeds_crc32") != _seeds_crc(seeds):
        return None
    return sorted(int(i) for i in doc.get("completed", []))


# --- knob-batched generation dispatch (adversary search) --------------------
#
# tools/advsearch evaluates a GENERATION of adversary-knob candidates at
# a time. Each candidate is one vmap lane of one compiled program: the
# lane's knob cutoffs arrive as traced operands through a
# core/knobs.KnobView over a shared static base config, so candidates
# that agree on (protocol, shape, static gates) NEVER recompile — the
# grouped-sweep axis batches them exactly like sweeps of one config.
# Fitness reads the lane's flight-recorder series (obs/timeline), so
# the base config must have telemetry_window > 0.

@functools.partial(jax.jit, static_argnums=(0, 1))
def _knob_batch_jit(cfg: Config, eng: EngineDef, seeds, kmat):
    from ..core import knobs as knobslib
    W = cfg.telemetry_window
    nw = n_windows(cfg)
    K = len(eng.telemetry_names)
    H = len(eng.latency_names)

    def lane(seed, kv):
        traced = {name: kv[i] for i, name in
                  enumerate(knobslib.KNOB_COLUMNS)}
        # attack_target is a node id (indexing/compares against i32
        # id vectors), not a probability cutoff.
        traced["attack_target"] = traced["attack_target"].astype(jnp.int32)
        view = knobslib.KnobView(cfg, **traced)
        c = eng.make_carry(view, seed)
        w0 = jnp.zeros((nw, K), jnp.int32)
        h0 = jnp.zeros((H, flightlib.N_BUCKETS), jnp.int32)

        # No running-totals accumulator here (unlike _chunk_body): the
        # search reads only the window ring, and totals are its
        # windows-axis sum anyway.
        def body(ct, r):
            c, w, h = ct
            c2, d, lh = eng.round_flight(view, c, r)
            wi = r // jnp.int32(W)
            cur = jax.lax.dynamic_slice(w, (wi, jnp.int32(0)), (1, K))
            w = jax.lax.dynamic_update_slice(w, cur + d[None, :],
                                             (wi, jnp.int32(0)))
            return (c2, w, h + lh), None

        (c, w, h), _ = jax.lax.scan(
            body, (c, w0, h0),
            jnp.arange(cfg.n_rounds, dtype=jnp.int32))
        return c, w, h

    return jax.vmap(lane)(seeds, kmat)


def run_knob_batch(cfg: Config, eng: EngineDef, seeds, kmat, *,
                   generation: int = 0):
    """Evaluate ``len(seeds)`` adversary-knob candidates as vmap lanes
    of ONE compiled program and return ``(out, flight)``.

    ``cfg`` is the static base: shapes, protocol dispatch, and —
    critically — the adversary GATES must be representative for the
    knobs the lanes vary (``Config.crash_on`` etc.; a gated-off feature
    is not traced, so a lane's nonzero cutoff for it would be silently
    ignored — rejected below instead). ``seeds`` is the per-lane u32
    trajectory seed vector; ``kmat[c]`` is lane ``c``'s knob row in
    :data:`consensus_tpu.core.knobs.KNOB_COLUMNS` order (u32 cutoffs +
    attack_target id). A lane whose row equals the base's own cutoffs
    reproduces a plain ``run`` of that config bit-for-bit
    (tests/test_advsearch.py).

    ``out`` is ``eng.extract``'s numpy dict batched over lanes;
    ``flight`` is a ``RunResult.extras["flight"]``-shaped dict (lane ==
    sweep) ready for :func:`consensus_tpu.obs.timeline.from_flight_dict`
    — the search's fitness input. Each call is traced as one
    ``dispatch`` span, which is the acceptance witness that a
    generation costs one dispatch, not one per candidate.
    """
    from ..core import knobs as knobslib
    if cfg.telemetry_window <= 0:
        raise ValueError("run_knob_batch needs telemetry_window > 0: "
                         "candidate fitness is read off the flight "
                         "recorder series (obs/timeline)")
    if eng.round_flight is None:
        raise ValueError(f"engine {eng.name!r} provides no flight "
                         "recorder (EngineDef.round_flight is None)")
    seeds = np.asarray(seeds, dtype=np.uint32)
    kmat = np.asarray(kmat, dtype=np.uint32)
    if seeds.ndim != 1 or kmat.shape != (seeds.shape[0],
                                         len(knobslib.KNOB_COLUMNS)):
        raise ValueError(
            f"seeds {seeds.shape} / kmat {kmat.shape}: expected [C] and "
            f"[C, {len(knobslib.KNOB_COLUMNS)}] (KNOB_COLUMNS order)")
    if seeds.shape[0] != cfg.n_sweeps:
        raise ValueError(
            f"{seeds.shape[0]} candidate lanes but cfg.n_sweeps = "
            f"{cfg.n_sweeps} — the lane axis IS the sweep axis; size "
            "the base config to the generation's lane count")
    gates = {"crash_cutoff": cfg.crash_on, "recover_cutoff": cfg.crash_on,
             "miss_cutoff": cfg.miss_on,
             "suppress_cutoff": cfg.suppress_on,
             "partition_cutoff": not cfg.no_partition,
             "attack_cutoff": cfg.attack != "none",
             "attack_target": cfg.attack != "none",
             "agg_poison_cutoff": cfg.agg_poison_on,
             "byz_uplink_cutoff": cfg.uplink_lies_on}
    for i, name in enumerate(knobslib.KNOB_COLUMNS):
        if not gates.get(name, True) \
                and (kmat[:, i] != np.uint32(getattr(cfg, name))).any():
            raise ValueError(
                f"kmat column {name!r} varies from the base value but "
                "the base config gates that adversary OFF — its "
                "machinery is untraced and the lane values would be "
                "silently ignored; make the base gate-representative "
                "(core/knobs.KnobView)")
    with obs_trace.span("dispatch", engine=eng.name,
                        generation=generation,
                        n_candidates=int(seeds.shape[0])):
        carry, win, lat = _knob_batch_jit(
            cfg, eng, jnp.asarray(seeds), jnp.asarray(kmat))
        out = {k: np.asarray(v) for k, v in eng.extract(carry).items()}
    warr = np.asarray(win).astype(np.int64)
    larr = np.asarray(lat).astype(np.int64)
    flight = {
        "engine": eng.name,
        "window_rounds": cfg.telemetry_window,
        "n_windows": n_windows(cfg),
        "n_rounds": cfg.n_rounds,
        "bucket_lo": list(flightlib.BUCKET_LO),
        "windows": {name: warr[:, :, k]
                    for k, name in enumerate(eng.telemetry_names)},
        "latency": {name: larr[:, h, :]
                    for h, name in enumerate(eng.latency_names)},
    }
    return out, flight


def run(cfg: Config, eng: EngineDef, *, mesh=None, checkpoint_path=None,
        resume: bool = False, stats: dict | None = None,
        seeds=None, keep_checkpoints: int = 2,
        telemetry: bool = False, fsync_checkpoints: bool = False,
        sync_checkpoints: bool = False,
        group_dir=None, progress=None,
        final_checkpoint: bool = False) -> dict[str, np.ndarray]:
    """Run ``cfg.n_rounds`` rounds and return ``eng.extract``'s numpy dict.

    With no ``cfg.scan_chunk`` the whole run is one XLA program. With a
    chunk size, the host drives fixed-shape chunks (one compile for the
    common size + one for the ragged tail) and optionally checkpoints
    between them, rotating the last ``keep_checkpoints`` snapshots
    (default 2, so a torn latest file still leaves a valid fallback —
    docs/RESILIENCE.md). ``fsync_checkpoints=True`` makes each snapshot
    durable against power loss, not just process death (see
    :func:`save_checkpoint`).

    Checkpoints are written ASYNCHRONOUSLY by default: a double-buffered
    background writer (:mod:`consensus_tpu.network.ckpt_writer`) pulls
    and writes chunk *k*'s snapshot while chunk *k+1* dispatches, so the
    chunk loop pays only the enqueue (plus backpressure when the disk
    falls a full snapshot behind). On-disk bytes, rotation, and resume
    semantics are identical to a sync save; the pipeline drains at run
    end and on any exception, re-raising writer errors on this thread.
    ``sync_checkpoints=True`` restores the on-thread save exactly.

    ``group_dir`` (sweep_chunk grouping only, exclusive with
    ``checkpoint_path``) is the grouped-sweep resumable layout: each
    group checkpoints into its own subdirectory
    (:func:`group_checkpoint_path`), writes a FINAL snapshot
    (``next_round == n_rounds``) as it completes, and a manifest of
    completed groups (:func:`write_group_manifest`) is updated as
    groups finish. With ``resume=True`` each group resumes from its own
    newest valid rotation — completed groups load their final snapshot
    and execute zero rounds, the first incomplete group resumes
    mid-scan — and ``stats`` gains ``n_groups`` / ``groups_skipped`` /
    ``group_start_rounds``. Results are bit-identical to the
    uninterrupted run (tests/test_ckpt_writer.py;
    tests/test_resilience.py SIGKILLs it for real).

    ``final_checkpoint=True`` (requires ``checkpoint_path``) writes one
    last snapshot at ``next_round == n_rounds`` after the scan
    completes — what makes a finished run's result recoverable without
    recomputation. The grouped path sets it per group; an already-
    complete resumed run does not rewrite it.

    If ``stats`` is given it is filled with ``start_round`` and
    ``executed_rounds`` so callers can report throughput for the rounds
    this call actually ran (a resumed run skips the first
    ``start_round`` rounds — counting them would inflate steps/sec).
    A checkpointing run additionally fills ``stats["checkpoint_io"]``
    (save/load counts, wall seconds, npz bytes — recorded even when
    tracing is off; the ROADMAP's "measure first" datum).

    ``telemetry=True`` accumulates the engine's on-device protocol
    counters (``eng.telemetry_names``) alongside the carry and fills
    ``stats["telemetry"] = {name: i64[n_sweeps]}``. Digest-neutral by
    construction: the counters are reduced from the same state update
    and never feed back into it (docs/OBSERVABILITY.md). The counters
    cover the rounds THIS process executed — a resumed run restarts
    them at zero, mirroring ``executed_rounds``; they are deliberately
    not checkpointed (the snapshot format stays telemetry-agnostic).

    ``cfg.telemetry_window > 0`` (the FLIGHT RECORDER,
    docs/OBSERVABILITY.md §"Flight recorder"; requires ``telemetry``)
    additionally reduces the same counters into a bounded
    ``[n_sweeps, n_windows, K]`` window ring plus the engine's
    ``[n_sweeps, H, N_BUCKETS]`` protocol-latency histograms, riding the
    scan carry, and fills ``stats["flight"]``. Unlike the totals, the
    ring and histograms ARE checkpointed (the snapshot becomes the
    ``(carry, win, lat)`` tuple), so a resumed run's series covers the
    whole trajectory — SIGKILL-resume yields the identical series
    (tests/test_flight.py). Same digest-neutrality contract; with the
    field at 0 the compiled program is byte-for-byte the recorder-free
    one (the recorder-off hlocheck fingerprints).

    ``progress`` (a callable receiving one info dict per chunk) gets
    the live commit rate + ETA the CLI prints at ``-v``; the
    ``rounds_completed``/``sim_eta_s`` gauges update per chunk
    regardless (see :func:`_advance`).
    """
    if telemetry and eng.round_telem is None:
        raise ValueError(f"engine {eng.name!r} provides no telemetry "
                         "counters (EngineDef.round_telem is None)")
    if telemetry and stats is None:
        raise ValueError("telemetry=True needs a stats dict to receive "
                         "the counters (stats['telemetry'])")
    recorder = cfg.telemetry_window > 0
    if recorder and not telemetry:
        raise ValueError(
            "telemetry_window > 0 without telemetry=True: the window "
            "ring IS the telemetry counter series, windowed — enable "
            "telemetry (the CLI's --telemetry-window implies it) rather "
            "than silently recording nothing")
    if recorder and eng.round_flight is None:
        raise ValueError(f"engine {eng.name!r} provides no flight "
                         "recorder (EngineDef.round_flight is None)")
    if fsync_checkpoints and not (checkpoint_path or group_dir):
        raise ValueError("fsync_checkpoints=True without a checkpoint_path "
                         "would be silently ignored (nothing is saved)")
    if sync_checkpoints and not (checkpoint_path or group_dir):
        raise ValueError("sync_checkpoints=True without a checkpoint_path "
                         "would be silently ignored (nothing is saved)")
    if group_dir and checkpoint_path:
        raise ValueError("group_dir and checkpoint_path are exclusive: a "
                         "grouped run snapshots into per-group "
                         "subdirectories of group_dir")
    if final_checkpoint and not checkpoint_path:
        raise ValueError("final_checkpoint=True without a checkpoint_path "
                         "would be silently ignored (nothing is saved)")
    groups = _sweep_groups(cfg, seeds)
    if group_dir and groups is None:
        raise ValueError("group_dir is the grouped-sweep checkpoint layout "
                         "and needs sweep_chunk grouping; use "
                         "checkpoint_path for an ungrouped run")
    if groups is not None:
        mesh = _check_groups(cfg, groups, mesh)
        if checkpoint_path:
            # One rotation set cannot hold N groups' snapshots; reject
            # rather than checkpoint only the last group (no silent
            # ignores) — group_dir= is the per-group snapshot layout,
            # and run(group_dir=..., resume=True) drives recovery from
            # it (skip completed groups, resume the first incomplete
            # one mid-scan).
            raise ValueError("checkpointing is not supported with "
                             "sweep_chunk; use scan_chunk for mid-run "
                             "snapshots, sweep_chunk=0, or group_dir= for "
                             "the per-group snapshot layout")
        all_seeds = make_seeds(cfg) if seeds is None else np.asarray(seeds)
        prior: list[int] | None = None
        if group_dir and resume:
            # Informational cross-check only: recovery itself rests on
            # each group's OWN validated snapshots (a completed group's
            # final snapshot loads at next_round == n_rounds and skips
            # execution), so a missing/foreign manifest degrades to
            # recomputation, never to wrong results.
            prior = read_group_manifest(group_dir, cfg, all_seeds)
            if prior:
                _log_ckpt(f"group manifest: groups {prior} recorded "
                          "complete — resuming from per-group snapshots")
        outs, telems, flights, done = [], [], [], []
        gio = _empty_io() if group_dir else None
        skipped, starts = 0, []
        for gi, (sub, s) in enumerate(groups):
            gstats: dict = {}
            kw: dict = {}
            if group_dir:
                kw.update(checkpoint_path=group_checkpoint_path(group_dir,
                                                                gi),
                          keep_checkpoints=keep_checkpoints,
                          fsync_checkpoints=fsync_checkpoints,
                          sync_checkpoints=sync_checkpoints,
                          resume=resume, final_checkpoint=True)
            outs.append(run(sub, eng, mesh=mesh, stats=gstats, seeds=s,
                            telemetry=telemetry, progress=progress, **kw))
            if group_dir:
                starts.append(gstats.get("start_round", 0))
                if starts[-1] >= sub.n_rounds:
                    skipped += 1
                done.append(gi)
                write_group_manifest(group_dir, cfg, all_seeds, done,
                                     len(groups))
                for k, v in gstats.pop("checkpoint_io").items():
                    gio[k] += v
            if telemetry:
                telems.append(gstats.pop("telemetry"))
            if recorder:
                flights.append(gstats.pop("flight"))
            if stats is not None:
                stats.update(gstats)
        if group_dir and stats is not None:
            stats["checkpoint_io"] = gio
            # The grouped-resume audit trail: where each group started
            # (n_rounds == skipped-as-complete) — the supervisor's
            # RunReport and the tests read these.
            stats["n_groups"] = len(groups)
            stats["groups_skipped"] = skipped
            stats["group_start_rounds"] = starts
        if telemetry:
            stats["telemetry"] = {
                k: np.concatenate([t[k] for t in telems])
                for k in telems[0]}
        if recorder:
            # Groups split the SWEEP axis; windows/latency concatenate
            # along it like the telemetry vectors (the series are
            # per-sweep).
            stats["flight"] = {
                **{k: flights[0][k]
                   for k in ("window_rounds", "n_windows", "n_rounds",
                             "bucket_lo")},
                "windows": {k: np.concatenate([f["windows"][k]
                                               for f in flights])
                            for k in flights[0]["windows"]},
                "latency": {k: np.concatenate([f["latency"][k]
                                               for f in flights])
                            for k in flights[0]["latency"]},
            }
        return {k: np.concatenate([o[k] for o in outs], axis=0)
                for k in outs[0]}
    mesh, seeds = _prepare(cfg, eng, mesh, seeds)

    io = _empty_io() if checkpoint_path else None
    win = lat = None
    recorder_template = flight_structs(cfg, eng) if recorder else None
    start = 0
    carry = None
    if resume and checkpoint_path:
        loaded = load_checkpoint(checkpoint_path, cfg, eng, seeds=seeds,
                                 io=io, recorder_template=recorder_template)
        if loaded is not None:
            carry, start = loaded
            if recorder:
                # The ring + histograms resume with the carry: the
                # recovered series covers the WHOLE trajectory.
                carry, win, lat = carry
                win, lat = jax.device_put(win), jax.device_put(lat)
            carry = jax.device_put(carry)
    if carry is None:
        carry = _init_jit(cfg, eng, seeds, mesh=mesh)
    if recorder and win is None:
        win = jnp.zeros(recorder_template[0].shape, jnp.int32)
        lat = jnp.zeros(recorder_template[1].shape, jnp.int32)

    # A checkpoint request implies chunking — a single-chunk run would
    # finish (or die) without ever writing a snapshot, so derive a chunk
    # that guarantees at least one mid-run save whenever one is possible
    # (n_rounds >= 2). 64 rounds/chunk is the SURVEY.md §7 compile-time
    # sweet spot for long runs; results are bit-identical regardless of
    # chunking (tests/test_runner.py).
    if cfg.scan_chunk:
        chunk = cfg.scan_chunk
    elif checkpoint_path:
        chunk = min(64, max(1, cfg.n_rounds // 2))
    else:
        chunk = cfg.n_rounds
    # start_round is known BEFORE the advance and is recorded first, so
    # a caller whose run dies mid-flight (the supervisor's per-attempt
    # records) still learns where the attempt began without re-reading
    # and re-verifying the snapshot it just loaded.
    if stats is not None:
        stats["start_round"] = start
    telem = (jnp.zeros((cfg.n_sweeps, len(eng.telemetry_names)), jnp.int32)
             if telemetry else None)
    writer = (ckpt_writer.CheckpointWriter(io=io)
              if checkpoint_path and not sync_checkpoints else None)
    try:
        carry, telem, win, lat = _advance(
            cfg, eng, carry, start, chunk, mesh,
            checkpoint_path, seeds=np.asarray(seeds),
            keep=keep_checkpoints, telem=telem, io=io,
            fsync=fsync_checkpoints, writer=writer, win=win, lat=lat,
            progress=progress)
    except BaseException:
        if writer is not None:
            # Wait for the in-flight write (a supervisor retry's resume
            # must never race a background write to the same rotation
            # set) but let the ORIGINAL failure propagate — any writer
            # error was already mirrored to the trace and the
            # checkpoint_errors counter.
            writer.close(raise_errors=False)
        raise
    if writer is not None:
        # Final drain barrier: every snapshot durably renamed, pending
        # writer errors re-raised here. The wait is hot-path blocking
        # time — the one place the pipeline can't hide behind compute.
        t0 = time.perf_counter()
        writer.close()
        io["save_s"] += time.perf_counter() - t0
    if final_checkpoint and start < cfg.n_rounds:
        # The completed-run snapshot (grouped-resume's skip handle).
        # Synchronous: the writer is already drained, and nothing
        # overlaps a run that just ended.
        snap = (carry, win, lat) if recorder else carry
        rec = save_checkpoint(checkpoint_path, cfg, snap, cfg.n_rounds,
                              seeds=np.asarray(seeds),
                              keep=keep_checkpoints,
                              fsync=fsync_checkpoints)
        io["saves"] += 1
        io["save_s"] += rec["wall_s"]
        io["pull_s"] += rec["pull_s"]
        io["write_s"] += rec["write_s"]
        io["bytes_written"] += rec["bytes"]

    if stats is not None:
        stats["executed_rounds"] = cfg.n_rounds - start
        if io is not None:
            stats["checkpoint_io"] = io
        if telemetry:
            # int64 on host: per-round deltas are i32-safe, but a long
            # run's accumulation should be summed/reported unclamped.
            tarr = np.asarray(telem).astype(np.int64)
            stats["telemetry"] = {
                name: tarr[:, k]
                for k, name in enumerate(eng.telemetry_names)}
        if recorder:
            warr = np.asarray(win).astype(np.int64)
            larr = np.asarray(lat).astype(np.int64)
            stats["flight"] = {
                "window_rounds": cfg.telemetry_window,
                "n_windows": n_windows(cfg),
                "n_rounds": cfg.n_rounds,
                "bucket_lo": list(flightlib.BUCKET_LO),
                "windows": {name: warr[:, :, k]
                            for k, name in enumerate(eng.telemetry_names)},
                "latency": {name: larr[:, h, :]
                            for h, name in enumerate(eng.latency_names)},
            }

    return {k: np.asarray(v) for k, v in eng.extract(carry).items()}
