"""Generic batched round-loop runner shared by all four protocol engines.

This is the TPU analog of the reference's `network::Simulator` round loop
(SURVEY.md §3a): the `for round / for node` nest becomes `lax.scan` over
rounds of a `vmap`'d round kernel, compiled once per (config, shapes).
On top of the plain loop it provides, uniformly for every protocol:

  * **mesh sharding** — carry pytrees pinned to a ("sweep", "node")
    `Mesh` via sharding constraints (see consensus_tpu.parallel.mesh);
  * **blocked scan** — `cfg.scan_chunk` splits the round loop into
    fixed-size jitted chunks driven from the host, bounding XLA program
    size and compile time for 1k+ round runs (SURVEY.md §7 hard parts);
  * **checkpoint / resume** — between chunks the carry (a pytree of
    arrays) can be snapshotted to an .npz; a resumed run continues the
    scan at the saved round and produces bit-identical decided logs
    because every round kernel is a pure function of (state, round).

Engines register an :class:`EngineDef`; no protocol code lives here.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import pathlib
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import Config
from ..parallel import mesh as meshlib


@dataclasses.dataclass(frozen=True)
class EngineDef:
    """A protocol engine, as seen by the runner.

    make_carry(cfg, seed) -> carry    # unbatched; vmapped over sweeps
    round_fn(cfg, carry, r) -> carry  # one round; pure; r = absolute round
    extract(batched_carry) -> dict[str, np.ndarray]
    carry_pspec(cfg) -> pytree of PartitionSpec matching the unbatched carry
    """
    name: str
    make_carry: Callable[..., Any]
    round_fn: Callable[..., Any]
    extract: Callable[[Any], dict]
    carry_pspec: Callable[[Config], Any]


def make_seeds(cfg: Config) -> np.ndarray:
    """Per-sweep u32 seeds; sweep b uses lo32(seed + b) (docs/SPEC.md §1)."""
    return ((np.uint64(cfg.seed) + np.arange(cfg.n_sweeps, dtype=np.uint64))
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


@functools.partial(jax.jit, static_argnums=(0, 1), static_argnames=("mesh",))
def _init_jit(cfg: Config, eng: EngineDef, seeds, *, mesh=None):
    carry = jax.vmap(lambda s: eng.make_carry(cfg, s))(seeds)
    return meshlib.constrain(carry, cfg, mesh, eng.carry_pspec(cfg))


@functools.partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("mesh",))
def _chunk_jit(cfg: Config, eng: EngineDef, n_rounds: int, carry, r0, *, mesh=None):
    """Advance the batched carry by ``n_rounds`` rounds starting at ``r0``.

    The round body must stay inside a scan of length >= 2: XLA unrolls a
    length-1 scan into the top-level computation, and the CPU backend's
    codegen of the unrolled round kernel is pathological (minutes for a
    body that compiles in ~2s inside a while loop — measured 2026-07-29).
    A 1-round chunk therefore scans a masked pair: round r0, then a
    dead lane whose output is discarded leaf-wise.
    """
    pspec = eng.carry_pspec(cfg)
    # Only the padded 1-round chunk needs the dead-lane select; for real
    # chunks every scan step is live, and a full-carry jnp.where per round
    # costs measurable HBM traffic (bench.py ran ~25% under the bare
    # kernel before this was made conditional).
    masked = n_rounds == 1

    def body(c, ra):
        if masked:
            r, active = ra
        else:
            r = ra
        new = jax.vmap(lambda s: eng.round_fn(cfg, s, r))(c)
        if masked:
            new = jax.tree.map(lambda a, b: jnp.where(active, a, b), new, c)
        return meshlib.constrain(new, cfg, mesh, pspec), None

    if masked:
        xs = (jnp.stack([r0, r0]), jnp.asarray([True, False]))
    else:
        xs = r0 + jnp.arange(n_rounds, dtype=jnp.int32)
    carry, _ = jax.lax.scan(body, carry, xs)
    return carry


@jax.jit
def _sync_elem(a):
    """First element of ``a`` — the O(1)-byte device-completion witness
    run_device's sync barrier transfers (see comment there)."""
    return a.ravel()[0]


# --- checkpointing -----------------------------------------------------------

def save_checkpoint(path, cfg: Config, carry, next_round: int,
                    seeds=None) -> None:
    """Snapshot the batched carry after ``next_round`` rounds have run.

    ``seeds`` records the per-sweep seed vector the carry was produced
    with (default: ``make_seeds(cfg)``) so a resume under different
    explicit seeds is detected as a mismatch, not silently continued.
    """
    leaves, _ = jax.tree.flatten(carry)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".tmp.npz")
    seeds = make_seeds(cfg) if seeds is None else np.asarray(seeds)
    np.savez(tmp, __meta__=np.frombuffer(json.dumps(
        {"config": json.loads(cfg.to_json()), "next_round": next_round,
         "seeds": [int(s) for s in seeds]}
    ).encode(), dtype=np.uint8), **arrays)
    tmp.replace(path)


def load_checkpoint(path, cfg: Config, eng: EngineDef, seeds=None):
    """Return (carry, next_round) or None if absent / config mismatch.

    ``seeds`` is the seed vector the caller will resume under (default
    ``make_seeds(cfg)``); a snapshot taken under a different vector is a
    mismatch — its carry belongs to other trajectories. Snapshots from
    before seeds were recorded compare at ``make_seeds(cfg)``, which is
    what they necessarily ran with.
    """
    path = pathlib.Path(path)
    if not path.exists():
        return None
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        # Round-trip the saved dict through Config so a field added to
        # the schema AFTER the snapshot was written compares at its
        # default (a pre-sweep_chunk checkpoint ran with sweep_chunk=0
        # semantics by definition) instead of silently invalidating
        # every existing checkpoint via a key-for-key dict mismatch.
        # Keys NOT in the current schema mean the snapshot came from a
        # *newer* (or foreign) semantics — reject those rather than
        # resume a carry whose meaning we can't represent; likewise a
        # saved config today's validation refuses is a mismatch, not a
        # crash.
        saved = {k: v for k, v in meta["config"].items() if k != "_cutoffs"}
        if not set(saved) <= {f.name for f in dataclasses.fields(Config)}:
            return None
        try:
            if Config.from_json(json.dumps(saved)) != cfg:
                return None
        except (ValueError, TypeError):
            return None
        want = make_seeds(cfg) if seeds is None else np.asarray(seeds)
        have = meta.get("seeds")
        have = make_seeds(cfg) if have is None else np.asarray(have)
        if not np.array_equal(want.astype(np.uint32),
                              have.astype(np.uint32)):
            return None
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
    template = jax.eval_shape(lambda s: _init_template(cfg, eng, s),
                              jax.ShapeDtypeStruct((cfg.n_sweeps,), jnp.uint32))
    # Cast to the template dtypes: an engine may narrow a state field's
    # storage dtype between versions (e.g. raft match/next i32 -> u8);
    # the saved integer values are identical, but lax.scan requires the
    # carry dtype to match what round_fn returns.
    leaves = [np.asarray(leaf).astype(t.dtype)
              for leaf, t in zip(leaves, jax.tree.leaves(template))]
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves), meta["next_round"]


def _init_template(cfg, eng, seeds):
    return jax.vmap(lambda s: eng.make_carry(cfg, s))(seeds)


# --- the run loop ------------------------------------------------------------

def _sweep_groups(cfg: Config, seeds=None):
    """Split ``cfg`` into (sub-config, seed-slice) groups of at most
    ``cfg.sweep_chunk`` sweeps, or None when the run is one program.
    An explicit ``seeds`` vector is sliced instead of regenerated.

    The one-program seed vector (docs/SPEC.md §1: sweep b ⇒
    lo32(seed + b)) is sliced positionally, so grouping can never change
    any sweep's trajectory — only which XLA program hosts it. Every
    full-size group shares one sub-config (the parent's seed field,
    unused when explicit seeds are passed), so jit re-traces once, not
    once per group; only a ragged tail adds a second program.
    """
    g = cfg.sweep_chunk
    if not g or g >= cfg.n_sweeps:
        return None
    seeds = make_seeds(cfg) if seeds is None else _check_seeds(cfg, seeds)
    return [(dataclasses.replace(cfg, n_sweeps=min(g, cfg.n_sweeps - s),
                                 sweep_chunk=0), seeds[s:s + g])
            for s in range(0, cfg.n_sweeps, g)]


def _check_groups(cfg: Config, groups, mesh):
    """Fail fast on an unshardable group — in particular a ragged tail
    whose size the mesh sweep axis doesn't divide — BEFORE any group
    runs, not after minutes of device time on the full-size groups."""
    if mesh is None and cfg.mesh_shape:
        mesh = meshlib.make_mesh(cfg.mesh_shape)
    for sub, _ in groups:
        meshlib.check_divisible(sub, mesh)
    return mesh


def _concat_carries(carries):
    return jax.tree.map(lambda *leaves: jnp.concatenate(leaves, axis=0),
                        *carries)


def _check_seeds(cfg: Config, seeds):
    """An explicit seed vector must cover exactly cfg.n_sweeps — a short
    one would silently shrink the batch while callers report throughput
    and digests for the configured sweep count (no silent ignores)."""
    seeds = np.asarray(seeds)
    if seeds.shape != (cfg.n_sweeps,):
        raise ValueError(f"seeds shape {seeds.shape} != (n_sweeps,) = "
                         f"({cfg.n_sweeps},)")
    return seeds


def _prepare(cfg: Config, eng: EngineDef, mesh, seeds=None):
    """Shared setup: resolve the mesh, check shardability, shard seeds."""
    if mesh is None and cfg.mesh_shape:
        mesh = meshlib.make_mesh(cfg.mesh_shape)
    meshlib.check_divisible(cfg, mesh)
    seeds = jnp.asarray(make_seeds(cfg) if seeds is None
                        else _check_seeds(cfg, seeds))
    if mesh is not None:
        seeds = jax.device_put(seeds, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(meshlib.SWEEP_AXIS)))
    return mesh, seeds


def _advance(cfg: Config, eng: EngineDef, carry, start: int, chunk: int,
             mesh, checkpoint_path=None, seeds=None):
    """Drive fixed-shape jitted chunks from ``start`` to ``cfg.n_rounds``."""
    r = start
    while r < cfg.n_rounds:
        n = min(chunk, cfg.n_rounds - r)
        carry = _chunk_jit(cfg, eng, n, carry, jnp.int32(r), mesh=mesh)
        r += n
        if checkpoint_path and r < cfg.n_rounds:
            save_checkpoint(checkpoint_path, cfg, carry, r, seeds=seeds)
    return carry


def run_device(cfg: Config, eng: EngineDef, *, mesh=None, seeds=None):
    """Advance a fresh batched carry through ``cfg.n_rounds`` rounds and
    return it ON DEVICE, synchronized via the smallest extract leaf.

    Benchmarks use this instead of :func:`run` so the timed quantity is
    the simulation itself: with the chip behind a remote tunnel, pulling
    the full final state (logs are ~MBs per sweep) costs more wall time
    than a 1k-round scan, and the decided-log extraction is a one-time
    epilogue, not part of the per-round metric (BASELINE.json:2).
    """
    groups = _sweep_groups(cfg, seeds)
    if groups is not None:
        mesh = _check_groups(cfg, groups, mesh)
        carry = _concat_carries([run_device(sub, eng, mesh=mesh, seeds=s)
                                 for sub, s in groups])
        # The per-group barriers don't cover the concat itself — sync on
        # the concatenated result too, or the contract ("returned ON
        # DEVICE, synchronized") breaks and timed callers leak this
        # round's concat work into the next timed window.
        np.asarray(_sync_elem(jax.tree.leaves(carry)[0]))
        return carry
    mesh, seeds = _prepare(cfg, eng, mesh, seeds)
    carry = _init_jit(cfg, eng, seeds, mesh=mesh)
    carry = _advance(cfg, eng, carry, 0, cfg.scan_chunk or cfg.n_rounds, mesh)
    # Sync barrier, O(1) bytes: transfer a jitted 1-element slice of a
    # final-carry leaf. The slice program has a data dependency on the
    # whole round loop, so its 4-byte result reaching the host proves
    # the computation finished. Two prior barriers were dishonest here
    # (caught 2026-07-30): pulling the *smallest extract leaf* is O(N·S)
    # for paxos (100 MB at 10k×10k — the "benchmark" measured the tunnel
    # at ~27 s/run vs ~0.25 s of device time), and
    # jax.block_until_ready returns BEFORE device completion on the
    # tunnel backend (timings collapse to ~0 — not a barrier at all).
    np.asarray(_sync_elem(jax.tree.leaves(carry)[0]))
    return carry


def run(cfg: Config, eng: EngineDef, *, mesh=None, checkpoint_path=None,
        resume: bool = False, stats: dict | None = None,
        seeds=None) -> dict:
    """Run ``cfg.n_rounds`` rounds and return ``eng.extract``'s numpy dict.

    With no ``cfg.scan_chunk`` the whole run is one XLA program. With a
    chunk size, the host drives fixed-shape chunks (one compile for the
    common size + one for the ragged tail) and optionally checkpoints
    between them.

    If ``stats`` is given it is filled with ``start_round`` and
    ``executed_rounds`` so callers can report throughput for the rounds
    this call actually ran (a resumed run skips the first
    ``start_round`` rounds — counting them would inflate steps/sec).
    """
    groups = _sweep_groups(cfg, seeds)
    if groups is not None:
        mesh = _check_groups(cfg, groups, mesh)
        if checkpoint_path:
            # A grouped run would need one snapshot per group; nothing
            # writes or resumes that layout, so reject rather than
            # checkpoint only the last group (no silent ignores).
            raise ValueError("checkpointing is not supported with "
                             "sweep_chunk; use scan_chunk for mid-run "
                             "snapshots or sweep_chunk=0")
        outs = [run(sub, eng, mesh=mesh, stats=stats, seeds=s)
                for sub, s in groups]
        return {k: np.concatenate([o[k] for o in outs], axis=0)
                for k in outs[0]}
    mesh, seeds = _prepare(cfg, eng, mesh, seeds)

    start = 0
    carry = None
    if resume and checkpoint_path:
        loaded = load_checkpoint(checkpoint_path, cfg, eng, seeds=seeds)
        if loaded is not None:
            carry, start = loaded
            carry = jax.device_put(carry)
    if carry is None:
        carry = _init_jit(cfg, eng, seeds, mesh=mesh)

    # A checkpoint request implies chunking — a single-chunk run would
    # finish (or die) without ever writing a snapshot, so derive a chunk
    # that guarantees at least one mid-run save whenever one is possible
    # (n_rounds >= 2). 64 rounds/chunk is the SURVEY.md §7 compile-time
    # sweet spot for long runs; results are bit-identical regardless of
    # chunking (tests/test_runner.py).
    if cfg.scan_chunk:
        chunk = cfg.scan_chunk
    elif checkpoint_path:
        chunk = min(64, max(1, cfg.n_rounds // 2))
    else:
        chunk = cfg.n_rounds
    carry = _advance(cfg, eng, carry, start, chunk, mesh, checkpoint_path,
                     seeds=np.asarray(seeds))

    if stats is not None:
        stats["start_round"] = start
        stats["executed_rounds"] = cfg.n_rounds - start

    return {k: np.asarray(v) for k, v in eng.extract(carry).items()}
