"""The simulator driver — the framework analog of `network::Simulator` [B:5].

One entry point runs any protocol on either engine and returns the decided
logs in canonical serialized form plus throughput stats:

    result = run(Config(protocol="raft", engine="tpu", ...))
    result.digest          # SHA-256 of canonical decided-log bytes
    result.steps_per_sec   # node-round-steps/sec (BASELINE.json:2)

The TPU engine executes the whole run as one XLA program (scan over rounds,
vmap over sweeps); the CPU engine loops the C++ scalar oracle over sweeps.
Byte-equivalence of `result.payload` across engines is the framework's
acceptance criterion (BASELINE.json:2,5).
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass

import numpy as np

from ..core.config import Config
from ..core import serialize


@dataclass
class RunResult:
    config: Config
    payload: bytes          # canonical decided-log serialization
    digest: str
    wall_s: float
    node_round_steps: int   # steps actually executed in the timed window
    counts: np.ndarray      # [B, N]
    rec_a: np.ndarray       # [B, N, L]
    rec_b: np.ndarray
    # True when wall_s includes jit tracing + XLA compilation (cold or
    # checkpoint-resumed runs skip the warmup execution) — steps_per_sec
    # is then a lower bound, not a steady-state throughput.
    timing_includes_compile: bool = False
    # Protocol-specific derived outputs (dpos: the SPEC §7 `lib` index),
    # computed engine-independently from the decided records so both
    # front doors report the same extras (ADVICE r4). A supervised run
    # (network/supervisor.py) additionally records its structured
    # RunReport here under "run_report".
    extras: dict = dataclasses.field(default_factory=dict)

    @property
    def steps_per_sec(self) -> float:
        return self.node_round_steps / self.wall_s if self.wall_s > 0 else 0.0


def _decided_raft(out) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    # Decided records: (log_term[k], log_val[k]) for k < commit (SPEC §3).
    return out["commit"], out["log_term"], out["log_val"]


def run(cfg: Config, warmup: bool = True, warm_cache: bool = False,
        **engine_kw) -> RunResult:
    """Run a config. With ``warmup`` (default) the TPU engine is executed
    once before the timed run so ``wall_s`` measures steady-state execution,
    not jit tracing + XLA compilation; the oracle's shared library is built
    outside the window for the same reason. Pass ``warmup=False`` for a
    single cold run when only the decided logs matter — or, when the
    caller has already compiled this exact config in this process (e.g. a
    benchmark loop timing repeats), ``warmup=False, warm_cache=True`` so
    the result isn't mislabeled as compile-inclusive. Extra keyword args
    (mesh=, checkpoint_path=, resume=) pass through to the TPU engine's
    :func:`consensus_tpu.network.runner.run`."""
    executed_rounds = cfg.n_rounds
    timing_includes_compile = False
    stats = None
    # Oracle-only knob (cpp/engine.h oracle_delivery): how the C++ Net
    # answers delivery queries — "auto" | "dense" | "edge". Execution
    # strategy only; digests are identical for every value
    # (tests/test_oracle_delivery.py), so the TPU engine rejects a
    # non-default rather than silently ignoring it.
    oracle_delivery = engine_kw.pop("oracle_delivery", "auto")
    if cfg.engine == "tpu" and oracle_delivery != "auto":
        raise ValueError(
            f"oracle_delivery={oracle_delivery!r} is a cpu-oracle execution "
            "knob (cpp/oracle.cpp Net); the tpu engine has no [N,N] "
            "materialization to switch and would silently ignore it")
    if cfg.engine == "tpu":
        # Honor a caller-provided stats dict (it is filled in place by
        # runner.run) instead of silently shadowing it with our own.
        kw = dict(engine_kw)
        if kw.get("stats") is None:
            kw["stats"] = {}
        stats = kw["stats"]
        # Snapshot-writing runs (ungrouped checkpoint or the grouped
        # per-group layout) skip the warmup pass: its hidden execution
        # would write real snapshots the timed run then resumes from —
        # measuring a skip, not the simulation.
        warm = warmup and not (engine_kw.get("checkpoint_path")
                               or engine_kw.get("group_dir"))
        if warm:
            # Compile + warm; discard result. The pass's dispatches are
            # EXCLUDED from metrics and trace — exported artifacts must
            # measure the run, not jit tracing + XLA compilation (the
            # benchmark suite resets its registry for the same reason).
            # One "warmup" span (opened before the suspension, so it
            # still records at close) covers the whole pass.
            from ..obs import metrics as obs_metrics
            from ..obs import trace as obs_trace
            with obs_trace.span("warmup", protocol=cfg.protocol):
                with obs_trace.suspended(), obs_metrics.paused():
                    # No live-progress lines for the hidden compile pass
                    # (its "rounds" would double every count the user
                    # sees) — the gauges are paused with the metrics.
                    _run_jax(cfg, **{k: v for k, v in kw.items()
                                     if k != "progress"})
        t0 = time.perf_counter()
        out = _run_jax(cfg, **kw)
        wall = time.perf_counter() - t0
        executed_rounds = stats.get("executed_rounds", cfg.n_rounds)
        timing_includes_compile = not (warm or warm_cache)
    else:
        if engine_kw:
            raise ValueError(
                f"engine_kw {sorted(engine_kw)} only apply to the tpu "
                f"engine; cfg.engine={cfg.engine!r} would silently ignore "
                "them (mesh/checkpoint/resume are TPU-engine features)")
        from ..obs import trace as obs_trace
        from ..oracle import bindings
        bindings.get_lib()  # build outside the timed window
        t0 = time.perf_counter()
        with obs_trace.span("oracle_run", protocol=cfg.protocol,
                            n_sweeps=cfg.n_sweeps,
                            oracle_delivery=oracle_delivery):
            out = _run_oracle(cfg, delivery=oracle_delivery)
        wall = time.perf_counter() - t0

    counts, rec_a, rec_b, payload = decided_payload(cfg, out)
    extras = {}
    if stats is not None:
        tstats = stats.get("telemetry")
        if tstats is not None:
            # Per-sweep counters reduced on device inside the scan body
            # (docs/OBSERVABILITY.md §"Telemetry"); totals are the
            # host-side sum over sweeps — the CLI-report shape.
            extras["telemetry"] = {
                "names": list(tstats),
                "per_sweep": {k: np.asarray(v) for k, v in tstats.items()},
                "totals": {k: int(np.asarray(v, dtype=np.int64).sum())
                           for k, v in tstats.items()}}
        fl = stats.get("flight")
        if fl is not None:
            # The flight recorder's windowed series + latency histograms
            # (docs/OBSERVABILITY.md §"Flight recorder") — the engine
            # name keys the timeline layer's commit-counter choice.
            extras["flight"] = {"engine": engine_def(cfg).name, **fl}
        io = stats.get("checkpoint_io")
        if io is not None:
            # Save/load wall time + npz bytes, recorded even with
            # tracing off — the ROADMAP's async-writer "measure first"
            # numbers (printed by the CLI at -v).
            extras["checkpoint_io"] = dict(io)
    if cfg.protocol == "dpos":
        # For dpos the decided records ARE the chain (counts=chain_len,
        # rec_b=chain_p), so `lib` derives uniformly for either engine.
        from ..engines.dpos import lib_index
        extras["lib"] = lib_index(rec_b, counts, cfg.n_candidates,
                                  cfg.n_producers)
    return RunResult(
        config=cfg, payload=payload, digest=serialize.digest(payload),
        wall_s=wall,
        node_round_steps=cfg.n_sweeps * cfg.n_nodes * executed_rounds,
        counts=counts, rec_a=np.asarray(rec_a), rec_b=np.asarray(rec_b),
        timing_includes_compile=timing_includes_compile, extras=extras)


def decided_payload(cfg: Config, out: dict):
    """Canonical decided-log packing for an engine's extract dict —
    the one place the per-protocol record shapes are known. Returns
    (counts, rec_a, rec_b, payload)."""
    if cfg.protocol == "raft":
        counts, rec_a, rec_b = _decided_raft(out)
    elif cfg.protocol == "paxos":
        counts, rec_a, rec_b = serialize.pack_sparse(
            np.asarray(out["learned_mask"]).astype(bool),
            np.asarray(out["learned_val"]))
    elif cfg.protocol in ("pbft", "hotstuff"):
        counts, rec_a, rec_b = serialize.pack_sparse(
            np.asarray(out["committed"]).astype(bool),
            np.asarray(out["dval"]))
    elif cfg.protocol == "dpos":
        counts = np.asarray(out["chain_len"])
        rec_a, rec_b = np.asarray(out["chain_r"]), np.asarray(out["chain_p"])
    else:
        counts, rec_a, rec_b = out["counts"], out["rec_a"], out["rec_b"]
    counts = np.asarray(counts)
    rec_a, rec_b = np.asarray(rec_a), np.asarray(rec_b)
    payload = serialize.serialize_decided(cfg.protocol, counts, rec_a, rec_b)
    return counts, rec_a, rec_b, payload


def engine_def(cfg: Config):
    """The TPU EngineDef a config resolves to (raft honors the SPEC §3b
    ``max_active`` dispatch). Benchmarks use this with
    :func:`consensus_tpu.network.runner.run_device` to time the round
    loop without pulling the full final state through the tunnel."""
    if cfg.protocol == "raft":
        if cfg.max_active > 0:
            from ..engines import raft_sparse
            return raft_sparse.get_engine()
        from ..engines import raft
        return raft.get_engine()
    if cfg.protocol == "paxos":
        from ..engines import paxos
        return paxos.get_engine()
    if cfg.protocol == "pbft":
        if cfg.fault_model == "bcast":
            from ..engines import pbft_bcast
            return pbft_bcast.get_engine()
        from ..engines import pbft
        return pbft.get_engine()
    if cfg.protocol == "dpos":
        from ..engines import dpos
        return dpos.get_engine()
    if cfg.protocol == "hotstuff":
        from ..engines import hotstuff
        return hotstuff.get_engine()
    raise NotImplementedError(cfg.protocol)


def _run_jax(cfg: Config, **engine_kw):
    # One dispatch table (engine_def) serves both the timed benchmark
    # path (runner.run_device) and this digest path, so a timed kernel
    # is always the kernel whose digest validates it.
    from . import runner
    return runner.run(cfg, engine_def(cfg), **engine_kw)


def _run_oracle(cfg: Config, delivery: str = "auto"):
    from ..oracle import bindings
    runners = {"raft": bindings.raft_run, "paxos": bindings.paxos_run,
               "pbft": bindings.pbft_run, "dpos": bindings.dpos_run,
               "hotstuff": bindings.hotstuff_run}
    if cfg.protocol not in runners:
        raise NotImplementedError(cfg.protocol)
    fn = runners[cfg.protocol]
    if cfg.protocol in ("dpos", "hotstuff"):
        # Neither has an [N, N] delivery layer to switch (one producer/
        # leader row per round is already edge-wise) — reject rather
        # than ignore.
        if delivery != "auto":
            raise ValueError(
                f"oracle_delivery does not apply to {cfg.protocol} (its "
                "oracle queries one leader/producer row per round)")
        kw = {}
    else:
        kw = {"delivery": delivery}
    outs = [fn(cfg, sweep=b, **kw) for b in range(cfg.n_sweeps)]
    return {k: np.stack([o[k] for o in outs]) for k in outs[0]}
