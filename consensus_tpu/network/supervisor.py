"""Supervised execution: bounded retry + resume around the simulator.

The checkpoint layer (network/runner.py) makes an interrupted run
*resumable*; this module makes the recovery *automatic*. A supervised
run wraps :func:`consensus_tpu.network.simulator.run` with:

  * **bounded retry with exponential backoff** on transient errors (a
    dropped device tunnel, an RPC flake, an injected fault) — permanent
    errors (bad config, shape mismatch) re-raise immediately;
  * **resume-from-newest-valid-checkpoint** between attempts: each
    retry continues from whatever the verified rotation set proves was
    durably completed, so a flake costs one chunk of progress, not
    hours of sweeps;
  * **a wall-clock deadline** gating new attempts (a running attempt is
    never interrupted — JAX dispatches can't be safely cancelled);
  * **opt-in graceful degradation to the CPU oracle** once retries or
    the deadline are exhausted — sound because both engines are
    decided-log digest-equivalent by contract (docs/SPEC.md,
    BASELINE.json:2);
  * a structured :class:`RunReport` (per-attempt outcomes, resume
    round, fallback flag) surfaced through ``RunResult.extras
    ["run_report"]`` so callers — including the CLI's ``--retries /
    --deadline / --fallback-cpu`` flags — can audit what actually
    happened.

Soundness: resuming never changes results. Every round kernel is a pure
function of (state, round) and the checkpoint layer refuses any
snapshot whose checksums, config, or seed vector don't match, so a
supervised run's digest is bit-identical to an uninterrupted one
(tests/test_resilience.py proves this with real SIGKILLs).
"""
from __future__ import annotations

import dataclasses
import json
import random
import time

from ..core.config import Config
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from . import faults, simulator


class SupervisorError(RuntimeError):
    """All attempts failed (retries and/or deadline exhausted) and CPU
    fallback was not enabled. Carries the :class:`RunReport`."""

    def __init__(self, msg: str, report: "RunReport"):
        super().__init__(msg)
        self.report = report


# Exception types retrying can plausibly fix. PJRT/XLA runtime errors
# are matched by name: the concrete class lives in jaxlib internals
# whose import path is not stable across versions.
_TRANSIENT_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "RpcError", "UnavailableError",
    "InternalError", "AbortedError", "DeadlineExceededError"})
# Permanent: caller/config errors — retrying replays the same failure.
_PERMANENT_TYPES = (ValueError, TypeError, KeyError, AttributeError,
                    NotImplementedError, AssertionError)


def is_transient(exc: BaseException) -> bool:
    """Is retrying this failure plausibly useful? Device/tunnel/IO
    flakes are; usage and semantic errors are not."""
    if isinstance(exc, faults.InjectedTransientError):
        return True
    if isinstance(exc, _PERMANENT_TYPES):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    return any(c.__name__ in _TRANSIENT_NAMES for c in type(exc).__mro__)


@dataclasses.dataclass
class Attempt:
    index: int              # 0-based attempt number
    start_round: int        # round the attempt began at (0 = fresh)
    wall_s: float
    error: str | None = None  # None = the attempt succeeded


@dataclasses.dataclass
class RunReport:
    """What the supervisor actually did — one entry per attempt."""
    retries: int
    attempts: list = dataclasses.field(default_factory=list)
    resumed_from_round: int = 0       # successful attempt's start round
    fallback_used: bool = False
    deadline_exceeded: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["n_attempts"] = len(self.attempts)
        return d

    def to_json(self, indent: int | None = 2) -> str:
        """Canonical JSON form (per-attempt wall times included) — the
        artifact the CLI writes next to ``--metrics-out``."""
        return json.dumps(self.to_dict(), indent=indent)


def supervised_run(cfg: Config, *, retries: int = 2, backoff_s: float = 0.5,
                   backoff_cap_s: float = 30.0, backoff_jitter: float = 0.25,
                   jitter_rng=None, deadline_s: float | None = None,
                   fallback_cpu: bool = False, checkpoint_path=None,
                   group_dir=None,
                   keep_checkpoints: int = 2, fsync_checkpoints: bool = False,
                   sync_checkpoints: bool = False,
                   mesh=None, seeds=None,
                   warmup: bool = False, telemetry: bool = False,
                   oracle_delivery: str = "auto",
                   progress=None,
                   sleep=time.sleep):
    """Run ``cfg`` under supervision; return the :class:`RunResult` with
    ``extras["run_report"]`` filled in.

    ``group_dir`` supervises a GROUPED sweep (``cfg.sweep_chunk``)
    against the per-group resumable layout: between attempts each
    completed group is skipped via its final snapshot and the first
    incomplete group resumes from its own rotation set mid-scan —
    closing the ROADMAP's "supervisor-driven sweep_chunk recovery"
    item. Digests are bit-identical to the uninterrupted run
    (tests/test_resilience.py SIGKILLs a grouped run for real).

    ``retries`` bounds re-attempts after transient failures (total
    attempts = retries + 1); between attempts the supervisor sleeps
    ``backoff_s * 2**k``, stretched by bounded multiplicative jitter —
    a uniform factor in ``[1, 1 + backoff_jitter]`` — and capped at
    ``backoff_cap_s``, then resumes from the newest valid rotation of
    ``checkpoint_path`` (when given). The jitter decorrelates
    co-scheduled retries (a fleet of sweeps knocked over by one tunnel
    blip must not stampede the device in lockstep); pass a seeded
    ``jitter_rng`` (``random.Random``) for deterministic delays in
    tests, or ``backoff_jitter=0`` to disable. ``fsync_checkpoints``
    passes through to the checkpoint writer (docs/RESILIENCE.md §2b),
    as does ``sync_checkpoints`` (write snapshots on the chunk loop
    instead of the default async double-buffered pipeline).

    Retry/deadline vs the async checkpoint pipeline: the runner drains
    its background writer before ANY exception propagates out of an
    attempt, so by the time a failure is classified here no write is in
    flight — the next attempt's resume scans a quiescent rotation set,
    and the "flake costs one chunk" accounting still holds (the
    interrupted attempt's last submitted snapshot is durably renamed
    during that drain). A deadline never interrupts a running attempt,
    so it never interrupts an in-flight write either.
    ``deadline_s`` is a wall-clock budget: no new attempt (or backoff
    sleep) starts past it. When everything is exhausted,
    ``fallback_cpu=True`` reruns the config on the CPU oracle engine —
    digest-equivalent by contract — instead of raising
    :class:`SupervisorError`.

    ``warmup=False`` (default): a supervised run cares about completion,
    not steady-state timing, so the compile-then-rerun warmup of
    :func:`simulator.run` is skipped; ``RunResult.timing_includes_compile``
    is set accordingly.

    ``progress`` (tpu engine only; a callable receiving one info dict
    per chunk, :func:`consensus_tpu.network.runner._advance`) rides
    every attempt — the sweep service's per-JOB live gauges need the
    per-chunk round/ETA signal even while the supervisor is the one
    driving the run, and a retried attempt keeps reporting through the
    same callback.

    ``telemetry=True`` enables the tpu engine's on-device protocol
    counters (``RunResult.extras["telemetry"]``, docs/OBSERVABILITY.md).
    A CPU-oracle fallback run carries no on-device telemetry — the
    degraded result's extras simply lack the key (likewise the flight
    recorder's ``"flight"`` series when ``cfg.telemetry_window > 0``:
    the fallback drops the digest-neutral recorder rather than dying on
    the oracle's rejection of it), and ``report.fallback_used`` says
    why.

    Supervision itself is observable: each attempt runs inside a
    ``supervised_attempt`` trace span, retries/backoffs emit events and
    bump ``supervisor_retries_total``, and a fallback bumps
    ``supervisor_fallbacks_total``.
    """
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    if backoff_jitter < 0:
        raise ValueError(f"backoff_jitter must be >= 0, got {backoff_jitter}")
    if fallback_cpu and cfg.engine != "tpu":
        raise ValueError("fallback_cpu degrades the tpu engine to the CPU "
                         f"oracle; cfg.engine={cfg.engine!r} already is it")
    if fallback_cpu and cfg.attack != "none":
        # Die HERE, at supervision setup — not via Config's engine="cpu"
        # rejection three retries later, mid-degradation. Trajectory-
        # changing TPU-only adversaries cannot degrade (unlike the
        # digest-neutral flight recorder, which the fallback simply
        # drops); §6c crash/§A.1 slot-miss/§A.2 delay CAN — they are
        # mirrored scalar-for-scalar in the oracle since the
        # adversary-library PR.
        raise ValueError(
            "fallback_cpu cannot honor attack != 'none': the SPEC §A.3 "
            "targeted Raft attacks are not implemented by the CPU oracle, "
            "so the degraded run would simulate different trajectories — "
            "drop --fallback-cpu or the attack")
    if fallback_cpu and seeds is not None:
        raise ValueError(
            "fallback_cpu cannot honor an explicit seeds vector: the CPU "
            "oracle derives per-sweep seeds from cfg.seed (docs/SPEC.md §1), "
            "so the degraded run would silently simulate different "
            "trajectories than the supervised attempts")
    if checkpoint_path and cfg.engine != "tpu":
        raise ValueError("checkpoint_path is a tpu-engine feature "
                         f"(cfg.engine={cfg.engine!r})")
    if group_dir:
        # The grouped-sweep resumable layout (network/runner.py): each
        # retry resumes per group — completed groups skip via their
        # final snapshots, the first incomplete group resumes mid-scan
        # from its own rotation set.
        if cfg.engine != "tpu":
            raise ValueError("group_dir is a tpu-engine feature "
                             f"(cfg.engine={cfg.engine!r})")
        if checkpoint_path:
            raise ValueError("group_dir and checkpoint_path are "
                             "exclusive (the grouped layout snapshots "
                             "per group)")
        if not cfg.sweep_chunk or cfg.sweep_chunk >= cfg.n_sweeps:
            raise ValueError("group_dir needs sweep_chunk grouping "
                             "(sweep_chunk in (0, n_sweeps)); use "
                             "checkpoint_path for an ungrouped run")
    if telemetry and cfg.engine != "tpu":
        raise ValueError("telemetry is reduced inside the tpu engine's "
                         f"scan body (cfg.engine={cfg.engine!r} has no "
                         "on-device counters)")
    if progress is not None and cfg.engine != "tpu":
        raise ValueError("progress reports the tpu engine's per-chunk "
                         f"round/ETA signal (cfg.engine={cfg.engine!r} "
                         "runs as one oracle call and would silently "
                         "never call it)")
    if oracle_delivery != "auto" and cfg.engine != "cpu":
        raise ValueError("oracle_delivery is a cpu-oracle execution knob "
                         f"(cfg.engine={cfg.engine!r}); simulator.run would "
                         "reject it on every attempt")

    report = RunReport(retries=retries)
    t_start = time.monotonic()
    last_exc: BaseException | None = None
    rng = jitter_rng if jitter_rng is not None else random.Random()

    for attempt in range(retries + 1):
        if deadline_s is not None and time.monotonic() - t_start >= deadline_s:
            report.deadline_exceeded = True
            break
        # Each attempt's true start round comes from the run's own stats
        # (runner.run records it right after loading, before advancing),
        # so even a failed attempt reports where it resumed — without a
        # separate peek re-reading and re-verifying the snapshot.
        stats: dict = {}
        kw = {}
        if oracle_delivery != "auto":
            kw["oracle_delivery"] = oracle_delivery
        if cfg.engine == "tpu":
            kw["stats"] = stats
            if telemetry:
                kw["telemetry"] = True
            if checkpoint_path:
                kw.update(checkpoint_path=checkpoint_path, resume=True,
                          keep_checkpoints=keep_checkpoints,
                          fsync_checkpoints=fsync_checkpoints,
                          sync_checkpoints=sync_checkpoints)
            if group_dir:
                kw.update(group_dir=group_dir, resume=True,
                          keep_checkpoints=keep_checkpoints,
                          fsync_checkpoints=fsync_checkpoints,
                          sync_checkpoints=sync_checkpoints)
            if mesh is not None:
                kw["mesh"] = mesh
            if seeds is not None:
                kw["seeds"] = seeds
            if progress is not None:
                kw["progress"] = progress
        t0 = time.monotonic()
        try:
            with obs_trace.span("supervised_attempt", index=attempt,
                                engine=cfg.engine) as sp:
                result = simulator.run(cfg, warmup=warmup, **kw)
                if sp is not None:
                    sp["start_round"] = stats.get("start_round", 0)
        except Exception as exc:  # noqa: BLE001 — classified below
            wall = time.monotonic() - t0
            if not is_transient(exc):
                raise
            report.attempts.append(Attempt(attempt,
                                           stats.get("start_round", 0),
                                           wall, error=repr(exc)))
            obs_metrics.counter("supervisor_retries_total").inc()
            obs_trace.event("attempt_failed", index=attempt,
                            start_round=stats.get("start_round", 0),
                            error=repr(exc))
            last_exc = exc
            if attempt < retries:
                # Bounded multiplicative jitter BEFORE the cap, so the
                # cap stays a hard ceiling on the actual sleep.
                delay = backoff_s * (2 ** attempt)
                if backoff_jitter > 0:
                    delay *= 1.0 + backoff_jitter * rng.random()
                delay = min(backoff_cap_s, delay)
                if deadline_s is not None:
                    delay = min(delay, max(
                        0.0, deadline_s - (time.monotonic() - t_start)))
                if delay > 0:
                    obs_trace.event("backoff", delay_s=delay)
                    sleep(delay)
            continue
        start_round = stats.get("start_round", 0)
        report.attempts.append(Attempt(attempt, start_round,
                                       time.monotonic() - t0))
        report.resumed_from_round = start_round
        result.extras["run_report"] = report.to_dict()
        return result

    if fallback_cpu:
        # Degrade to the scalar oracle: same Config schema, same decided
        # logs byte-for-byte (the framework's acceptance criterion), so
        # the caller still gets a correct result — just slowly. A fresh
        # run: the oracle has no checkpoint/resume surface. The flight
        # recorder degrades WITH the telemetry it windows (the oracle
        # has neither; Config would reject telemetry_window > 0 on the
        # cpu engine) — digest-neutral, so the payload contract holds.
        report.fallback_used = True
        obs_metrics.counter("supervisor_fallbacks_total").inc()
        with obs_trace.span("oracle_fallback", protocol=cfg.protocol):
            result = simulator.run(dataclasses.replace(cfg, engine="cpu",
                                                       telemetry_window=0),
                                   warmup=False)
        result.extras["run_report"] = report.to_dict()
        return result
    why = ("wall-clock deadline exceeded" if report.deadline_exceeded
           else f"all {retries + 1} attempts failed")
    raise SupervisorError(
        f"supervised run gave up: {why} (last error: {last_exc!r}); "
        "pass fallback_cpu=True to degrade to the CPU oracle",
        report) from last_exc
