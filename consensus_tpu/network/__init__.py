"""Execution layer: the round-loop runner (scan/vmap/mesh + verified
checkpoints), the engine-agnostic simulator front door, the retry/resume
supervisor, and the test-only fault-injection harness.

Submodules are imported lazily by callers (`from consensus_tpu.network
import simulator`) — importing this package must stay free of jax work
so the CLI can validate flags before any backend probe.
"""
