"""Sweepd: the persistent multi-tenant simulation service.

    python -m consensus_tpu.service --port P --state-dir DIR

One long-lived process accepts queued sweep jobs over a local HTTP API
(mounted on the PR 11 introspection server, obs/serve.py), schedules
them through the existing runner, and survives restarts:

  * **throughput** — the compatibility batcher (service/batcher.py)
    merges tenants sharing a (protocol, static shape) onto the sweep
    axis of ONE compiled program, runs knob-only-differing tenants as
    traced lanes of one ``run_knob_batch`` dispatch, and never
    recompiles a repeat shape (seed-normalized configs hit jax's jit
    cache; the hit is witnessed by ``service_exec_cache_hits_total``);
  * **availability** — the durable queue (service/jobs.py) journals
    every transition atomically, each solo job checkpoints into its own
    ``<state_dir>/jobs/<id>/`` rotation set (the ``--group-dir`` layout
    when the job asks for sweep grouping) and each merged batch into
    ``<state_dir>/batches/<ids>/``, so a SIGKILLed daemon restarts,
    re-admits queued jobs and resumes in-flight ones from their
    snapshots with bit-identical results (the PR 1/4/12 resume
    contract);
  * **observability** — /jobs (submit + list), /jobs/<id> (status,
    live ``rounds_completed``/ETA off per-job labeled gauges, digest,
    RunReport, scenario verdict), /metrics (the process registry incl.
    the per-job gauge families), /status (fleet counts); completed-job
    report rows fold into ``benchmarks/LEDGER.json`` via
    ``tools/ledger.py`` when published.

Execution is ONE worker thread: jax dispatch wants a single driver, and
the batcher — not thread-count — is the concurrency story (tenants
share programs, not cores). HTTP handlers only touch the journal and
the metrics registry.
"""
from __future__ import annotations

import json
import os
import pathlib
import threading
import time
from typing import Any

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import serve as obs_serve
from ..obs import trace as obs_trace
from . import batcher
from .jobs import Job, JobQueue, job_order

_JSON = "application/json"


def _body(doc: Any) -> bytes:
    return (json.dumps(doc, indent=2) + "\n").encode()


class SweepService:
    """The daemon object: queue + batcher + worker + HTTP front door.
    Usable in-process (tests construct it directly) or via
    ``python -m consensus_tpu.service`` (one per machine/state-dir).
    """

    def __init__(self, state_dir, *, port: int = 0, platform: str = "cpu",
                 retries: int = 1, publish=None,
                 poll_s: float = 0.05,
                 batch_window_s: float = 0.25) -> None:
        self.queue = JobQueue(state_dir)
        self.cache = batcher.ExecutableCache()
        self.platform = platform
        self.retries = int(retries)
        self.publish = publish
        self._poll_s = poll_s
        # Admission window: after a submission the worker waits for the
        # queue to go quiet this long before planning, so co-arriving
        # compatible tenants COALESCE into one batch instead of the
        # first one racing into a solo run. Capped (see _settle) so a
        # steady submission stream can never starve execution. 0 = plan
        # immediately (tests that pre-populate the journal).
        self.batch_window_s = batch_window_s
        self._last_submit = 0.0
        self._t0 = time.time()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._closed = False
        if self.queue.readmitted:
            obs_metrics.counter("service_jobs_readmitted_total").inc(
                len(self.queue.readmitted))
        self._gauge_depth()
        # The HTTP front door rides the PR 11 introspection server —
        # same shutdown path, same PortInUseError policy.
        self._server = obs_serve.MetricsServer(
            port, status=self._status,
            routes={"/jobs": self._route_jobs})
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="sweepd-worker", daemon=True)
        self._worker.start()

    # --- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.port

    def close(self, wait_s: float = 30.0) -> None:
        """Graceful shutdown: stop admitting work, let the worker
        finish (bounded wait — an overrunning batch's jobs stay
        ``running`` in the journal and re-admit on the next start),
        close+join the HTTP thread, flush the report artifact.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout=wait_s)
        self._server.close()
        self._write_reports()

    def __enter__(self) -> "SweepService":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def wait_idle(self, timeout_s: float = 120.0) -> bool:
        """Block until no job is queued or running (tests/smokes)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            c = self.queue.counts()
            if not c["queued"] and not c["running"]:
                return True
            time.sleep(0.02)
        return False

    # --- HTTP ---------------------------------------------------------------

    def _status(self) -> dict[str, Any]:
        return {"service": "sweepd", "pid": os.getpid(),
                "platform": self.platform,
                "state_dir": str(self.queue.path.parent),
                "jobs": self.queue.counts(),
                "executable_cache": {"hits": self.cache.hits,
                                     "misses": self.cache.misses}}

    def _job_doc(self, job: Job) -> dict[str, Any]:
        doc = job.to_dict()
        if job.status == "running":
            for field, gname in (("rounds_completed",
                                  "service_job_rounds_completed"),
                                 ("eta_s", "service_job_eta_s")):
                v = obs_metrics.labeled_gauge(gname).get(job=job.id)
                if v is not None:
                    doc[field] = v
        return doc

    def _route_jobs(self, method: str, path: str,
                    body: bytes) -> tuple[int, str, bytes]:
        try:
            if path == "/jobs" and method == "POST":
                return self._submit(body)
            if path == "/jobs" and method == "GET":
                rows = [{"id": j.id, "name": j.name, "status": j.status,
                         "protocol": j.config.get("protocol"),
                         "n_sweeps": (len(j.seeds) if j.seeds
                                      else j.config.get("n_sweeps")),
                         "batch": j.batch,
                         "digest": (j.result or {}).get("digest")}
                        for j in sorted(self.queue.jobs(),
                                        key=lambda j: job_order(j.id))]
                return 200, _JSON, _body({"jobs": rows})
            if path.startswith("/jobs/") and method == "GET":
                job = self.queue.get(path[len("/jobs/"):])
                if job is None:
                    return 404, _JSON, _body({"error": "unknown job id"})
                return 200, _JSON, _body(self._job_doc(job))
            return 405, _JSON, _body({"error": f"{method} {path} is not "
                                      "part of the /jobs API"})
        except (ValueError, KeyError) as exc:
            # Admission-time validation failures (Config/seeds/scenario)
            # are the CLIENT's 400, never a worker crash later.
            return 400, _JSON, _body({"error": str(exc)})

    def _submit(self, body: bytes) -> tuple[int, str, bytes]:
        try:
            doc = json.loads(body.decode() or "{}")
        except ValueError:
            return 400, _JSON, _body({"error": "request body must be "
                                      "JSON ({'config': {...}, ...})"})
        if not isinstance(doc, dict) or not isinstance(doc.get("config"),
                                                       dict):
            return 400, _JSON, _body({"error": "missing 'config' object "
                                      "(a Config JSON, docs/SERVICE.md)"})
        job = self.queue.submit(doc["config"], name=doc.get("name"),
                                seeds=doc.get("seeds"),
                                scenario=doc.get("scenario"))
        obs_metrics.counter("service_jobs_submitted_total").inc()
        self._last_submit = time.monotonic()
        self._gauge_depth()
        self._wake.set()
        return 200, _JSON, _body({"id": job.id, "status": job.status,
                                  "name": job.name})

    # --- worker -------------------------------------------------------------

    def _gauge_depth(self) -> None:
        obs_metrics.gauge("service_queue_depth").set(
            self.queue.counts()["queued"])

    def _settle(self) -> None:
        """Wait out the admission window: until no submission landed
        for ``batch_window_s`` — or 10 windows total, whichever comes
        first (a steady stream must not starve the jobs already
        queued)."""
        if self.batch_window_s <= 0:
            return
        deadline = time.monotonic() + 10 * self.batch_window_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            quiet = time.monotonic() - self._last_submit
            if quiet >= self.batch_window_s:
                return
            time.sleep(min(self.batch_window_s - quiet, 0.05))

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            queued = self.queue.queued()
            if not queued:
                self._wake.wait(self._poll_s)
                self._wake.clear()
                continue
            self._settle()
            queued = self.queue.queued()  # re-snapshot after the window
            batch = batcher.plan(queued)[0]
            now = time.time()
            ids = [j.id for j in batch.jobs]
            for j in batch.jobs:
                j.status = "running"
                j.started_unix = now
                j.batch = ids if len(ids) > 1 else None
            self.queue.update(*batch.jobs)
            self._gauge_depth()
            try:
                with obs_trace.span("service_batch", kind=batch.kind,
                                    n_jobs=len(batch.jobs)):
                    if batch.kind == "merged":
                        self._execute_merged(list(batch.jobs))
                    elif batch.kind == "knobs":
                        self._execute_knobs(list(batch.jobs))
                    else:
                        self._execute_solo(batch.jobs[0])
                obs_metrics.counter("service_batches_total").inc()
                obs_metrics.counter("service_jobs_completed_total").inc(
                    len(batch.jobs))
            except Exception as exc:  # noqa: BLE001 — job-scoped failure
                now = time.time()
                for j in batch.jobs:
                    j.status = "failed"
                    j.error = repr(exc)
                    j.finished_unix = now
                self.queue.update(*batch.jobs)
                obs_metrics.counter("service_jobs_failed_total").inc(
                    len(batch.jobs))
            finally:
                # Both per-job families stay bounded on a long-lived
                # daemon: finished jobs' live numbers move into the
                # durable job doc, so the children can go.
                for j in batch.jobs:
                    for gname in ("service_job_eta_s",
                                  "service_job_rounds_completed"):
                        obs_metrics.labeled_gauge(gname).remove(job=j.id)
            self._write_reports()

    def _write_reports(self) -> None:
        self.queue.write_reports(
            self.queue.path.parent / "job_reports.json", self.platform)
        if self.publish:
            self.queue.write_reports(self.publish, self.platform)

    def _retrying(self, fn):
        """Bounded transient-failure retry around a dispatch (merged /
        knob batches drive the runner directly; solo jobs get the full
        supervisor instead). Resume comes from the batch's own
        checkpoints, so a retry costs one chunk, not the batch."""
        from ..network import supervisor
        last: BaseException | None = None
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                if not supervisor.is_transient(exc) \
                        or attempt >= self.retries:
                    raise
                last = exc
                obs_trace.event("attempt_failed", index=attempt,
                                error=repr(exc))
                time.sleep(min(2.0, 0.2 * (2 ** attempt)))
        raise last  # unreachable; keeps the type checker honest

    def _progress_cb(self, job_ids: list[str]):
        rg = obs_metrics.labeled_gauge("service_job_rounds_completed")
        eg = obs_metrics.labeled_gauge("service_job_eta_s")

        def cb(info: dict) -> None:
            for jid in job_ids:
                rg.set(info["round"], job=jid)
                eg.set(round(info["eta_s"], 3), job=jid)
        return cb

    # --- execution paths ----------------------------------------------------

    def _finish(self, job: Job, cfg, *, payload: bytes, wall: float,
                steps: int, extras: dict | None = None) -> None:
        """``steps`` must count the rounds the execution ACTUALLY ran
        (a resumed run skipped its checkpointed prefix) — the row feeds
        the perf ledger, and full-run steps over a resumed wall clock
        would fake a throughput gain."""
        from ..core import serialize
        job.result = {
            "digest": serialize.digest(payload),
            "payload_bytes": len(payload),
            "wall_s": round(wall, 6), "steps": steps,
            "steps_per_sec": round(steps / wall, 1) if wall > 0 else 0.0,
            **(extras or {})}
        job.status = "done"
        job.finished_unix = time.time()

    def _execute_merged(self, jobs: list[Job]) -> None:
        """Sweep-axis batch: one runner.run over the concatenated seed
        vectors — every dispatch span covers the WHOLE batch (the
        acceptance witness that concurrent tenants share one compiled
        program), one checkpoint rotation set per batch."""
        from ..network import runner, simulator
        cfgs = [j.cfg() for j in jobs]
        seed_vecs = [batcher.effective_seeds(j) for j in jobs]
        sizes = [len(s) for s in seed_vecs]
        seeds = np.concatenate(seed_vecs)
        cfg_run = batcher.normalized(cfgs[0], int(seeds.shape[0]))
        hit = self.cache.admit(batcher.ExecutableCache.key("run", cfg_run))
        self._account_cache(jobs, hit)
        eng = simulator.engine_def(cfg_run)
        ckpt = self.queue.batch_dir([j.id for j in jobs]) / "ck.npz"
        stats: dict = {}
        t0 = time.perf_counter()
        out = self._retrying(lambda: runner.run(
            cfg_run, eng, seeds=seeds, stats=stats,
            checkpoint_path=str(ckpt), resume=True, final_checkpoint=True,
            telemetry=cfg_run.telemetry_window > 0,
            progress=self._progress_cb([j.id for j in jobs])))
        wall = time.perf_counter() - t0
        executed = stats.get("executed_rounds", cfg_run.n_rounds)
        start = stats.get("start_round", 0)
        off = 0
        for job, cfg, size in zip(jobs, cfgs, sizes):
            sub = {k: v[off:off + size] for k, v in out.items()}
            off += size
            *_, payload = simulator.decided_payload(cfg, sub)
            self._finish(job, cfg, payload=payload, wall=wall,
                         steps=size * cfg.n_nodes * executed,
                         extras={"resumed_from_round": start})
        self.queue.update(*jobs)

    def _execute_knobs(self, jobs: list[Job]) -> None:
        """Knob-lane batch: tenants differing only in adversary knob
        values run as traced lanes of ONE run_knob_batch dispatch
        (PR 12's generation program; lanes bit-identical to per-config
        runs). No checkpoint surface — a restart recomputes the batch
        deterministically."""
        from ..network import runner, simulator
        cfgs = [j.cfg() for j in jobs]
        seed_vecs = [batcher.effective_seeds(j) for j in jobs]
        sizes = [len(s) for s in seed_vecs]
        seeds = np.concatenate(seed_vecs)
        base = batcher.normalized(cfgs[0], int(seeds.shape[0]))
        hit = self.cache.admit(batcher.ExecutableCache.key("knob", base))
        self._account_cache(jobs, hit)
        eng = simulator.engine_def(base)
        kmat = batcher.lane_matrix(cfgs, sizes)
        t0 = time.perf_counter()
        out, _flight = self._retrying(
            lambda: runner.run_knob_batch(base, eng, seeds, kmat))
        wall = time.perf_counter() - t0
        off = 0
        for job, cfg, size in zip(jobs, cfgs, sizes):
            sub = {k: v[off:off + size] for k, v in out.items()}
            off += size
            *_, payload = simulator.decided_payload(cfg, sub)
            self._finish(job, cfg, payload=payload, wall=wall,
                         steps=size * cfg.n_nodes * cfg.n_rounds)
        self.queue.update(*jobs)

    def _execute_solo(self, job: Job) -> None:
        """One job through the supervised front door: bounded retry +
        resume from the job's OWN snapshot directory (the --group-dir
        layout when the job asks for sweep grouping), the structured
        RunReport in the job doc, scenario verdicts evaluated exactly
        like the CLI's --scenario."""
        from ..network import supervisor
        cfg = job.cfg()
        sdef = None
        if job.scenario:
            from .. import scenarios
            sdef = scenarios.get(job.scenario)
            cfg = scenarios.apply(cfg, sdef)
        kw: dict[str, Any] = {}
        if cfg.engine == "tpu":
            seeds = (batcher.effective_seeds(job) if job.scenario is None
                     else None)
            if seeds is not None:
                # Seed-normalized dispatch: the executable cache's whole
                # mechanism (same static config value == jit cache hit).
                norm = batcher.normalized(cfg, cfg.n_sweeps)
                hit = self.cache.admit(
                    batcher.ExecutableCache.key("run", norm))
                self._account_cache([job], hit)
                cfg = norm
                kw["seeds"] = seeds
            jobdir = self.queue.job_dir(job.id)
            if cfg.sweep_chunk and cfg.sweep_chunk < cfg.n_sweeps:
                kw["group_dir"] = str(jobdir / "groups")
            else:
                kw["checkpoint_path"] = str(jobdir / "ck.npz")
            kw["telemetry"] = cfg.telemetry_window > 0
            kw["progress"] = self._progress_cb([job.id])
        t0 = time.perf_counter()
        result = supervisor.supervised_run(cfg, retries=self.retries, **kw)
        wall = time.perf_counter() - t0
        extras: dict[str, Any] = {}
        rr = result.extras.get("run_report")
        if rr is not None:
            extras["run_report"] = rr
            extras["resumed_from_round"] = rr["resumed_from_round"]
        if sdef is not None:
            from .. import scenarios
            extras["scenario"] = scenarios.evaluate(sdef, result)
        # node_round_steps already counts only the rounds this
        # execution ran (a resumed attempt skips its prefix).
        self._finish(job, cfg, payload=result.payload, wall=wall,
                     steps=result.node_round_steps, extras=extras)
        self.queue.update(job)

    def _account_cache(self, jobs: list[Job], hit: bool) -> None:
        for j in jobs:
            j.cache_hit = hit
        if hit:
            obs_metrics.counter("service_exec_cache_hits_total").inc()
