"""Sweepd — the persistent multi-tenant simulation service.

    python -m consensus_tpu.service --port P --state-dir DIR

Assembles the bricks the ROADMAP's sweep-as-a-service item named: the
PR 11 live endpoints (obs/serve.py, here grown a /jobs API), the PR 12
grouped-sweep resume and knob-batched dispatch (the compatibility
batcher's two sharing seams), and the PR 1/2 supervised retry with
structured RunReports (the solo execution path). See docs/SERVICE.md.
"""
from .batcher import Batch, ExecutableCache, knob_key, plan, sweep_key
from .daemon import SweepService
from .jobs import JOB_REPORT_FIELDS, Job, JobQueue, job_report_row

__all__ = ["Batch", "ExecutableCache", "Job", "JobQueue",
           "JOB_REPORT_FIELDS", "SweepService", "job_report_row",
           "knob_key", "plan", "sweep_key"]
