"""Compatibility batcher: which queued jobs can share one XLA program.

The multi-tenant throughput story (ROADMAP "heavy traffic") rests on
two proven seams of the runner:

  * **the sweep axis** — per-sweep trajectories are pure functions of
    the per-sweep seed (docs/SPEC.md §1; tests/test_runner.py pins that
    grouping/slicing the sweep axis never changes any sweep), so jobs
    whose configs agree on EVERYTHING but ``(seed, n_sweeps)`` can run
    as one batched program over the concatenated seed vectors — one
    compile, one dispatch per chunk, for the whole batch;
  * **traced knob lanes** — jobs that additionally differ only in
    adversary knob VALUES (the ``core.knobs.KNOB_COLUMNS`` cutoffs)
    share one compiled program through ``runner.run_knob_batch``: the
    cutoffs are operands, not constants, so the lanes vmap (PR 12's
    generation dispatch, bit-identical to per-config runs).

Everything else runs solo — but still through the **executable cache**:
solo/merged runs are dispatched under a seed-NORMALIZED config
(``seed=0`` + the explicit per-sweep seed vector), so two tenants
submitting the same shape with different seeds hash to the SAME static
jit argument and the second never recompiles. The cache key is the
hlocheck-style identity: the full normalized config JSON (every field
that selects the compiled program) — what tools/hlocheck registers a
target by, minus the trajectory-only seed.
"""
from __future__ import annotations

import dataclasses
import json

import numpy as np

from ..core.config import Config
from .jobs import job_order

# Config fields that select TRAJECTORIES, not the compiled program or
# the protocol semantics: jobs differing only here merge onto the
# sweep axis.
SWEEP_AXIS_FIELDS = frozenset({"seed", "n_sweeps"})

# The adversary knob VALUES `runner.run_knob_batch` traces as operands
# (core/knobs.KNOB_COLUMNS, in Config-field terms). Jobs differing only
# here (and on the sweep axis) share one knob-batched program —
# PROVIDED the static gates agree (a gated-off adversary is untraced;
# see knob_key).
KNOB_VALUE_FIELDS = frozenset({
    "drop_rate", "partition_rate", "churn_rate", "crash_prob",
    "recover_prob", "miss_rate", "suppress_rate", "attack_rate",
    "attack_target",
})


def _identity(cfg: Config, *, minus: frozenset) -> tuple:
    d = json.loads(cfg.to_json())
    d.pop("_cutoffs", None)
    return tuple(sorted((k, json.dumps(v)) for k, v in d.items()
                        if k not in minus))


def sweep_key(job) -> tuple | None:
    """Sweep-axis compatibility key, or None when the job cannot merge:
    only plain tpu-engine jobs qualify (a scenario job's overrides and
    verdict are its own; the cpu oracle loops sweeps host-side; a
    sweep_chunk/mesh request asks for its own execution geometry, which
    the solo path honors via the per-job --group-dir layout)."""
    cfg = job.cfg()
    if (job.scenario or cfg.engine != "tpu" or cfg.sweep_chunk
            or cfg.mesh_shape):
        return None
    return ("sweep",) + _identity(cfg, minus=SWEEP_AXIS_FIELDS)


def knob_key(job) -> tuple | None:
    """Knob-lane compatibility key, or None. On top of the sweep-axis
    conditions this requires the flight recorder (run_knob_batch reads
    fitness off it — and more to the point its lane program always
    records it, so recorder-off jobs would pay for series they never
    asked for) and encodes the static adversary GATES: crash/miss/
    partition on-ness and the attack kind select WHAT is traced, so
    lanes can only share a program when they agree on them."""
    cfg = job.cfg()
    if sweep_key(job) is None or cfg.telemetry_window <= 0:
        return None
    gates = ("gates", cfg.crash_on, cfg.miss_on, cfg.suppress_on,
             cfg.no_partition, cfg.attack)
    return ("knob", gates) + _identity(
        cfg, minus=SWEEP_AXIS_FIELDS | KNOB_VALUE_FIELDS)


@dataclasses.dataclass(frozen=True)
class Batch:
    """One schedulable unit: ``kind`` is "merged" (sweep-axis batch,
    one runner.run), "knobs" (one run_knob_batch dispatch), or "solo"
    (one job through the simulator front door)."""
    kind: str
    jobs: tuple


def plan(jobs: list) -> list[Batch]:
    """Group queued jobs (submit order preserved within and across
    groups) into shared-program batches. Deterministic in the job list
    — a restarted daemon re-forms the same plan from the re-admitted
    journal, which is what lets a merged batch find its own snapshots
    again (jobs.JobQueue.batch_dir)."""
    sweep_groups: dict[tuple, list] = {}
    rest: list = []
    for job in jobs:
        key = sweep_key(job)
        if key is None:
            rest.append(job)
        else:
            sweep_groups.setdefault(key, []).append(job)

    batches: list[Batch] = []
    singles: list = []
    for group in sweep_groups.values():
        if len(group) > 1:
            batches.append(Batch("merged", tuple(group)))
        else:
            singles.extend(group)

    knob_groups: dict[tuple, list] = {}
    for job in singles:
        key = knob_key(job)
        if key is None:
            rest.append(job)
        else:
            knob_groups.setdefault(key, []).append(job)
    for group in knob_groups.values():
        if len(group) > 1:
            batches.append(Batch("knobs", tuple(group)))
        else:
            rest.extend(group)

    batches.extend(Batch("solo", (job,)) for job in rest)
    # Schedule in submit order of each batch's FIRST member, so one
    # tenant's late incompatible job never starves an earlier one
    # (numeric id order — the counter outlives the zero padding).
    batches.sort(key=lambda b: job_order(b.jobs[0].id))
    return batches


def effective_seeds(job) -> np.ndarray:
    """The job's per-sweep u32 seed vector: the explicit one when
    submitted, else SPEC §1 lo32(seed + b) — computed HERE (not on
    device) so merged batches can concatenate before normalizing the
    config's seed away."""
    if job.seeds is not None:
        return np.asarray(job.seeds, dtype=np.uint32)
    cfg = job.cfg()
    return ((np.uint64(cfg.seed)
             + np.arange(cfg.n_sweeps, dtype=np.uint64))
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def normalized(cfg: Config, n_sweeps: int) -> Config:
    """The dispatch form of a (possibly merged) config: ``seed=0`` —
    trajectories come from the explicit seed vector, so the seed field
    must not fragment the jit cache — and the batch's total sweep
    count. THIS value is the executable-cache identity: equal
    normalized configs are equal static jit arguments, and jax
    guarantees the second dispatch reuses the compiled program."""
    return dataclasses.replace(cfg, seed=0, n_sweeps=n_sweeps)


class ExecutableCache:
    """Process-lifetime bookkeeping of which compiled-program shapes
    this service has already paid for. The cache that actually holds
    the executables is jax's jit cache (keyed by the same normalized
    config, by construction — see :func:`normalized`); this records
    hits/misses so tenants and tests can SEE the reuse
    (``service_exec_cache_hits_total``, the /jobs/<id> ``cache_hit``
    field)."""

    def __init__(self) -> None:
        self._seen: set[tuple] = set()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(kind: str, cfg: Config) -> tuple:
        return (kind,) + _identity(cfg, minus=frozenset({"seed"}))

    def admit(self, key: tuple) -> bool:
        """Record one execution under ``key``; returns True when the
        shape was seen before (the dispatch reuses the executable)."""
        hit = key in self._seen
        self._seen.add(key)
        self.hits += int(hit)
        self.misses += int(not hit)
        return hit


def lane_matrix(cfgs: list[Config], sizes: list[int]) -> np.ndarray:
    """The run_knob_batch kmat for a knob batch: each job's cutoff row
    repeated once per sweep, in KNOB_COLUMNS order."""
    from ..core import knobs as knobslib
    rows = []
    for cfg, size in zip(cfgs, sizes):
        row = [int(getattr(cfg, name)) for name in knobslib.KNOB_COLUMNS]
        rows.extend([row] * size)
    return np.asarray(rows, dtype=np.uint32)
