"""Daemon entry point: ``python -m consensus_tpu.service``.

    python -m consensus_tpu.service --port 8787 --state-dir sweepd-state
    python -m consensus_tpu.service --port 0 --port-file /tmp/port \\
        --platform cpu            # ephemeral port, script-discoverable

Runs until SIGTERM/SIGINT, then shuts down gracefully (the current
batch finishes within the close budget; anything still running
re-admits on the next start — docs/SERVICE.md §"Durability"). Submit
jobs with ``python -m consensus_tpu ... --submit http://127.0.0.1:P``
or a plain ``curl -X POST .../jobs``.
"""
from __future__ import annotations

import argparse
import pathlib
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m consensus_tpu.service",
        description="Sweepd: persistent multi-tenant simulation service "
                    "(docs/SERVICE.md).")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port on 127.0.0.1 (0 = ephemeral; the "
                         "bound port is printed and, with --port-file, "
                         "written to disk)")
    ap.add_argument("--state-dir", default="sweepd-state",
                    help="durable state root: the atomic job journal "
                         "plus per-job/per-batch snapshot directories — "
                         "restart with the same dir to re-admit and "
                         "resume")
    ap.add_argument("--platform", default="auto",
                    choices=["auto", "cpu", "tpu", "tpu-trust"],
                    help="JAX backend selection, same semantics as the "
                         "CLI's --platform (auto probes hang-proof and "
                         "falls back to CPU)")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--retries", type=int, default=1,
                    help="bounded transient-failure retries per job "
                         "batch (solo jobs run fully supervised; "
                         "resume comes from the job's own snapshots)")
    ap.add_argument("--batch-window", type=float, default=0.25,
                    metavar="S",
                    help="admission window in seconds: the worker waits "
                         "for the queue to go quiet this long before "
                         "planning, so co-arriving compatible tenants "
                         "coalesce into one batch (capped at 10 windows "
                         "under a steady stream; 0 = plan immediately)")
    ap.add_argument("--publish", default="",
                    help="also mirror completed-job report rows to this "
                         "path (e.g. benchmarks/parts/service_jobs.json "
                         "— the artifact `make ledger` folds into "
                         "benchmarks/LEDGER.json)")
    ap.add_argument("--port-file", default="",
                    help="write the bound port here once listening "
                         "(ephemeral-port discovery for scripts/CI)")
    args = ap.parse_args(argv)
    if not 0 <= args.port <= 65535:
        ap.error(f"--port must be in [0, 65535] (0 = ephemeral), "
                 f"got {args.port}")
    if args.retries < 0:
        ap.error(f"--retries must be >= 0, got {args.retries}")

    if args.platform == "tpu-trust":
        tag = "tpu-trust"  # no probe; init may hang if the tunnel is down
    else:
        from ..utils.platform import ensure_platform
        tag = ensure_platform(args.platform,
                              probe_timeout=args.probe_timeout)

    from ..obs.serve import PortInUseError
    from .daemon import SweepService
    try:
        svc = SweepService(args.state_dir, port=args.port, platform=tag,
                           retries=args.retries,
                           batch_window_s=args.batch_window,
                           publish=args.publish or None)
    except PortInUseError as exc:
        print(f"sweepd: {exc}", file=sys.stderr, flush=True)
        return 2
    print(f"sweepd: listening on http://127.0.0.1:{svc.port} "
          f"(/jobs, /metrics, /status; state: {args.state_dir})",
          file=sys.stderr, flush=True)
    if args.port_file:
        pf = pathlib.Path(args.port_file)
        tmp = pf.with_suffix(pf.suffix + ".tmp")
        tmp.write_text(str(svc.port))
        tmp.replace(pf)

    stop = threading.Event()

    def _sig(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        svc.close()
    print("sweepd: shut down cleanly", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
