"""Durable job queue for the sweep service (docs/SERVICE.md §"Jobs").

One job = one tenant's sweep request: a full :class:`Config` JSON (the
seed range is the SPEC §1 ``(seed, n_sweeps)`` pair, or an explicit
``seeds`` vector), an optional scripted scenario, and a display name.
The queue is DURABLE: every transition rewrites ``<state_dir>/
queue.json`` atomically (tmp + rename, the checkpoint-manifest
discipline from network/runner.py), so a SIGKILLed daemon restarts
with the exact queue it died with — jobs it never started are
re-admitted as queued, jobs it was executing revert to queued and
resume from their own snapshots under ``<state_dir>/jobs/<id>/``
(bit-identical by the checkpoint layer's contract).

The completed-job report row (:data:`JOB_REPORT_FIELDS`, exactly these
keys) is the artifact ``tools/ledger.py`` folds into
``benchmarks/LEDGER.json`` as ``service-job`` rows; the field tuple is
mirrored import-free in ``tools/validate_trace.py``
(``SERVICE_JOB_FIELDS``) and lint-synced both ways like the telemetry
counter registry (tools/lint/registry_sync.py).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib
import threading
import time
from typing import Any

from ..core.config import Config

QUEUE_VERSION = 1
JOB_STATES = ("queued", "running", "done", "failed")

# One completed-job report row = exactly these keys (nulls where a job
# has no value). Mirrored import-free in tools/validate_trace.py
# (SERVICE_JOB_FIELDS) and lint-synced both ways.
JOB_REPORT_FIELDS = ("schema", "id", "name", "protocol", "engine",
                     "platform", "n_nodes", "n_rounds", "n_sweeps",
                     "submitted_unix", "finished_unix", "wall_s", "steps",
                     "steps_per_sec", "digest", "status", "batch",
                     "cache_hit", "scenario_passed", "error")
JOB_REPORT_SCHEMA = 1


def job_order(job_id: str) -> tuple:
    """Submit-order sort key for a job id: NUMERIC on the counter part,
    because a persistent state-dir outlives the zero padding
    ('j10000' must sort after 'j9999', not between 'j0999' and
    'j2000' — the batcher's anti-starvation ordering and the /jobs
    listing both rest on this)."""
    digits = job_id.lstrip("j")
    return (0, int(digits)) if digits.isdigit() else (1, job_id)


@dataclasses.dataclass
class Job:
    """One queued sweep request plus everything the service learned
    about it. ``config`` stays the submitted JSON dict (the durable
    form); :meth:`cfg` revalidates it through the one Config schema."""
    id: str
    name: str
    config: dict
    status: str = "queued"
    seeds: list | None = None          # explicit per-sweep seed vector
    scenario: str | None = None        # scripted-attack name, applied at run
    submitted_unix: float = 0.0
    started_unix: float | None = None
    finished_unix: float | None = None
    batch: list | None = None          # job ids sharing the compiled program
    cache_hit: bool = False            # executable-shape seen before?
    readmissions: int = 0              # times re-admitted after a restart
    result: dict | None = None         # digest/wall/steps/... once done
    error: str | None = None

    def cfg(self) -> Config:
        return Config.from_json(json.dumps(self.config))

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def job_report_row(job: Job, platform: str) -> dict[str, Any]:
    """The completed-job ledger row (exactly :data:`JOB_REPORT_FIELDS`
    keys) for a done/failed job."""
    res = job.result or {}
    row = {k: None for k in JOB_REPORT_FIELDS}
    row.update(
        schema=JOB_REPORT_SCHEMA, id=job.id, name=job.name,
        protocol=job.config.get("protocol"),
        engine=job.config.get("engine"), platform=platform,
        n_nodes=job.config.get("n_nodes"),
        n_rounds=job.config.get("n_rounds"),
        n_sweeps=(len(job.seeds) if job.seeds
                  else job.config.get("n_sweeps")),
        submitted_unix=job.submitted_unix,
        finished_unix=job.finished_unix,
        wall_s=res.get("wall_s"), steps=res.get("steps"),
        steps_per_sec=res.get("steps_per_sec"),
        digest=res.get("digest"), status=job.status, batch=job.batch,
        cache_hit=job.cache_hit,
        scenario_passed=(res.get("scenario") or {}).get("passed"),
        error=job.error)
    assert set(row) == set(JOB_REPORT_FIELDS), \
        f"job report keys drifted: {sorted(row)}"
    return row


class JobQueue:
    """The durable queue: an atomic JSON journal plus per-job snapshot
    directories. Thread-safe (the HTTP handlers submit while the worker
    transitions); every mutation is persisted before it is visible."""

    def __init__(self, state_dir) -> None:
        self._dir = pathlib.Path(state_dir)
        self._dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: dict[str, Job] = {}
        self._next = 1
        self.readmitted: list[str] = []
        self._load()

    # --- journal ------------------------------------------------------------

    @property
    def path(self) -> pathlib.Path:
        return self._dir / "queue.json"

    def job_dir(self, job_id: str) -> pathlib.Path:
        """The job's own snapshot directory (``--checkpoint`` rotation
        set, or the ``--group-dir`` layout for sweep-grouped jobs)."""
        return self._dir / "jobs" / job_id

    def batch_dir(self, job_ids: list[str]) -> pathlib.Path:
        """Snapshot directory for a MERGED batch: keyed by the member
        ids, so the deterministically re-formed batch of a restarted
        daemon finds its own snapshots (a changed membership simply
        misses — the checkpoint layer's config/seed identity check
        would refuse the stale snapshot anyway)."""
        return self._dir / "batches" / "+".join(job_ids)

    def _load(self) -> None:
        try:
            doc = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return
        if doc.get("version") != QUEUE_VERSION:
            return
        self._next = int(doc.get("next_id", 1))
        for jd in doc.get("jobs", []):
            job = Job(**jd)
            if job.status == "running":
                # The previous daemon died mid-execution: its snapshots
                # (if any) are on disk, so re-admit and let the run
                # resume from them (or recompute — never wrong results,
                # the checkpoint layer validates identity).
                job.status = "queued"
                job.batch = None
                job.readmissions += 1
                self.readmitted.append(job.id)
            self._jobs[job.id] = job
        if self.readmitted:
            self._save_locked()

    def _save_locked(self) -> None:
        doc = {"version": QUEUE_VERSION, "next_id": self._next,
               "jobs": [j.to_dict() for j in self._jobs.values()]}
        tmp = self.path.with_suffix(".tmp.json")
        tmp.write_text(json.dumps(doc, indent=2))
        tmp.replace(self.path)

    # --- API ----------------------------------------------------------------

    def submit(self, config: dict, *, name: str | None = None,
               seeds: list | None = None,
               scenario: str | None = None) -> Job:
        """Validate and enqueue one job; returns the persisted record.
        Raises ValueError on an invalid config / seeds / scenario —
        admission is the validation boundary, not execution (a bad
        request must 400 at submit, never fail a worker later)."""
        cfg = Config.from_json(json.dumps(config))  # validates
        if seeds is not None:
            seeds = [int(s) for s in seeds]
            if len(seeds) != cfg.n_sweeps:
                raise ValueError(
                    f"seeds has {len(seeds)} entries but config.n_sweeps "
                    f"= {cfg.n_sweeps} (the explicit seed vector must "
                    "cover exactly the sweep axis)")
        if scenario:
            if cfg.engine != "tpu":
                raise ValueError(
                    "a scenario job needs engine='tpu': the scripted "
                    "attacks are judged against the flight recorder, "
                    "which only the TPU engine records (the CLI's "
                    "--scenario has the same rule)")
            if seeds is not None:
                raise ValueError(
                    "a scenario job cannot carry an explicit seeds "
                    "vector: the scenario's overrides may reshape the "
                    "sweep geometry, and a stale vector would silently "
                    "simulate different trajectories")
            from .. import scenarios
            scenarios.get(scenario)  # ValueError -> unknown name
        if not name:
            # Default names carry a shape-identity hash (config minus
            # the trajectory seed): the name keys a LEDGER series, and
            # two different workloads under one default name would
            # cross-compare into fake regression verdicts. Same shape
            # + different seed = same name = one honest series.
            d = json.loads(cfg.to_json())
            d.pop("_cutoffs", None)
            d.pop("seed", None)
            shape = hashlib.sha256(
                json.dumps(d, sort_keys=True).encode()).hexdigest()[:6]
            name = (f"{cfg.protocol}-{cfg.n_nodes}n-{cfg.n_rounds}r-"
                    f"{shape}")
        with self._lock:
            job = Job(id=f"j{self._next:04d}",
                      name=name,
                      config=json.loads(cfg.to_json()),
                      seeds=seeds, scenario=scenario,
                      submitted_unix=time.time())
            self._next += 1
            self._jobs[job.id] = job
            self._save_locked()
            return job

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())

    def queued(self) -> list[Job]:
        with self._lock:
            return [j for j in self._jobs.values() if j.status == "queued"]

    def counts(self) -> dict[str, int]:
        with self._lock:
            out = {s: 0 for s in JOB_STATES}
            for j in self._jobs.values():
                out[j.status] += 1
            return out

    def update(self, *jobs: Job) -> None:
        """Persist one transition for the given (already-mutated) jobs
        — one atomic journal write covers the whole batch."""
        with self._lock:
            for job in jobs:
                if job.status not in JOB_STATES:
                    raise ValueError(f"unknown job status {job.status!r}")
                self._jobs[job.id] = job
            self._save_locked()

    # --- reports ------------------------------------------------------------

    def finished(self) -> list[Job]:
        with self._lock:
            return [j for j in self._jobs.values()
                    if j.status in ("done", "failed")]

    def report_doc(self, platform: str) -> dict[str, Any]:
        """All finished jobs as the ledger-ingestable artifact
        (``{"version": 1, "rows": [JOB_REPORT_FIELDS...]}``)."""
        return {"version": 1,
                "rows": [job_report_row(j, platform)
                         for j in sorted(self.finished(),
                                         key=lambda j: job_order(j.id))]}

    def write_reports(self, path, platform: str) -> None:
        """Atomically write (replace) the job-report artifact — the
        file ``tools/ledger.py`` ingests as ``service-job`` rows when
        published at ``benchmarks/parts/service_jobs.json``."""
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp.json")
        tmp.write_text(json.dumps(self.report_doc(platform), indent=2,
                                  sort_keys=True) + "\n")
        tmp.replace(path)
