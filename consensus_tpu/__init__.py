"""consensus_tpu — a TPU-native distributed-consensus simulation framework.

Re-designed from scratch with the capabilities of ``2892931976/consensus-rs``
(see SURVEY.md; the reference mount was empty, so parity targets come from
BASELINE.json and the public protocol specs): Raft, PBFT, multi-decree
Paxos, and DPoS engines behind one engine seam, driven by a round-based
simulator with seeded adversarial fault injection, plus a C++ scalar
oracle for decided-log byte-equivalence.

TPU-first design: the whole node population's state is a struct-of-arrays
pytree; each protocol round is a pure branchless jnp kernel; rounds advance
under ``lax.scan``; sweeps are batch axes; quorum tallies ``psum`` across a
``shard_map`` device mesh.
"""

__version__ = "0.1.0"

from .core.config import Config  # noqa: F401
