"""ctypes bindings to the C++ scalar oracle (cpp/liboracle.so).

The oracle is the framework's CPU reference engine — the analog of the
reference's Rust implementation (SURVEY.md §2, "native-component
checklist"). pybind11 is not available in this environment, so the bridge
is a plain C ABI + ctypes (task environment notes).

The library is built on demand with `make -C cpp` the first time it is
imported, so `pip`-less checkouts and CI just work.
"""
from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_CPP_DIR = pathlib.Path(__file__).resolve().parents[2] / "cpp"
_LIB_PATH = _CPP_DIR / "liboracle.so"
_lib = None

# SimConfig::oracle_delivery (cpp/engine.h): how the oracle's Net answers
# delivery queries. Execution strategy only — decided logs are
# byte-identical for every value (tests/test_oracle_delivery.py):
#   auto  — per-engine choice (edge-wise for the capped engines);
#   dense — materialize the [N, N] matrix per round (the historic path);
#   edge  — on-demand per-edge draws, O(live edges) per round: what makes
#           the 100k-node capped configs oracle-tractable (docs/PERF.md).
DELIVERY = {"auto": 0, "dense": 1, "edge": 2}


def _delivery_code(delivery) -> int:
    try:
        return DELIVERY[delivery]
    except KeyError:
        raise ValueError(f"unknown oracle delivery {delivery!r} "
                         f"(expected one of {sorted(DELIVERY)})")


def _build() -> None:
    subprocess.run(["make", "-C", str(_CPP_DIR), "-s"], check=True)


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    src_mtime = max((_CPP_DIR / f).stat().st_mtime
                    for f in ("oracle.cpp", "engine.h", "threefry.h"))
    if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < src_mtime:
        _build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64
    p32 = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
    lib.ctpu_random_u32.restype = u32
    lib.ctpu_random_u32.argtypes = [u64, u32, u32, u32, u32]
    lib.ctpu_delivery_u32.restype = u32
    lib.ctpu_delivery_u32.argtypes = [u64, u32, u32, u32]
    lib.ctpu_raft_run.restype = ctypes.c_int
    lib.ctpu_raft_run.argtypes = [u64] + [u32] * 22 + [p32] * 5
    p8 = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    lib.ctpu_paxos_run.restype = ctypes.c_int
    lib.ctpu_paxos_run.argtypes = [u64] + [u32] * 17 + [p32, p8, p32, p32, p32]
    lib.ctpu_pbft_run.restype = ctypes.c_int
    lib.ctpu_pbft_run.argtypes = [u64] + [u32] * 26 + [p8, p32, p32]
    pi32 = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    lib.ctpu_dpos_run.restype = ctypes.c_int
    lib.ctpu_dpos_run.argtypes = [u64] + [u32] * 16 + [p32] * 3 + [pi32]
    lib.ctpu_hotstuff_run.restype = ctypes.c_int
    lib.ctpu_hotstuff_run.argtypes = [u64] + [u32] * 24 + [p8, p32, p32, p32]
    _lib = lib
    return lib


def random_u32(seed: int, stream: int, ctx: int, c0: int, c1: int) -> int:
    return int(get_lib().ctpu_random_u32(seed, stream, ctx, c0, c1))


def delivery_u32(seed: int, r: int, i: int, j: int) -> int:
    """SPEC §2 delivery-mixer draw (C++ twin), for parity tests."""
    return int(get_lib().ctpu_delivery_u32(seed, r, i, j))


def raft_run(cfg, sweep: int = 0, delivery: str = "auto"):
    """Run one Raft sweep in the oracle. Returns dict of final arrays."""
    lib = get_lib()
    N, L = cfg.n_nodes, cfg.log_capacity
    out = {
        "commit": np.zeros(N, np.uint32),
        "log_term": np.zeros((N, L), np.uint32),
        "log_val": np.zeros((N, L), np.uint32),
        "term": np.zeros(N, np.uint32),
        "role": np.zeros(N, np.uint32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_raft_run(
        seed, N, cfg.n_rounds, L, cfg.max_entries, cfg.t_min, cfg.t_max,
        cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        cfg.max_active,
        cfg.n_byzantine, 1 if cfg.byz_mode == "equivocate" else 0,
        _delivery_code(delivery),
        cfg.crash_cutoff, cfg.recover_cutoff, cfg.max_crashed,
        cfg.max_delay_rounds,
        1 if cfg.net_model == "switch" else 0, cfg.n_aggregators,
        cfg.agg_fail_cutoff, cfg.agg_stale_cutoff, cfg.agg_max_stale,
        out["commit"], out["log_term"].reshape(-1), out["log_val"].reshape(-1),
        out["term"], out["role"])
    if rc != 0:
        raise RuntimeError(f"oracle raft_run failed rc={rc}")
    return out


def paxos_run(cfg, sweep: int = 0, delivery: str = "auto"):
    """Run one Paxos sweep in the oracle. Returns dict of final arrays."""
    lib = get_lib()
    N, S = cfg.n_nodes, cfg.log_capacity
    out = {
        "learned_val": np.zeros((N, S), np.uint32),
        "learned_mask": np.zeros((N, S), np.uint8),
        "promised": np.zeros((N, S), np.uint32),
        "acc_bal": np.zeros((N, S), np.uint32),
        "acc_val": np.zeros((N, S), np.uint32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_paxos_run(
        seed, N, cfg.n_rounds, S, cfg.n_proposers,
        cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        _delivery_code(delivery),
        cfg.crash_cutoff, cfg.recover_cutoff, cfg.max_crashed,
        cfg.max_delay_rounds,
        1 if cfg.net_model == "switch" else 0, cfg.n_aggregators,
        cfg.agg_fail_cutoff, cfg.agg_stale_cutoff, cfg.agg_max_stale,
        out["learned_val"].reshape(-1), out["learned_mask"].reshape(-1),
        out["promised"].reshape(-1), out["acc_bal"].reshape(-1),
        out["acc_val"].reshape(-1))
    if rc != 0:
        raise RuntimeError(f"oracle paxos_run failed rc={rc}")
    return out


def pbft_run(cfg, sweep: int = 0, delivery: str = "auto"):
    """Run one PBFT sweep in the oracle. Returns dict of final arrays."""
    lib = get_lib()
    N, S = cfg.n_nodes, cfg.log_capacity
    out = {
        "committed": np.zeros((N, S), np.uint8),
        "dval": np.zeros((N, S), np.uint32),
        "view": np.zeros(N, np.uint32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_pbft_run(
        seed, N, cfg.n_rounds, S, cfg.f, cfg.view_timeout, cfg.n_byzantine,
        1 if cfg.byz_mode == "equivocate" else 0,
        1 if cfg.fault_model == "bcast" else 0,
        cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        _delivery_code(delivery),
        cfg.crash_cutoff, cfg.recover_cutoff, cfg.max_crashed,
        cfg.max_delay_rounds,
        1 if cfg.net_model == "switch" else 0, cfg.n_aggregators,
        cfg.agg_fail_cutoff, cfg.agg_stale_cutoff, cfg.agg_max_stale,
        cfg.agg_byz, cfg.agg_poison_cutoff, cfg.byz_uplink_cutoff,
        cfg.desync_cutoff, cfg.max_skew_rounds,
        out["committed"].reshape(-1), out["dval"].reshape(-1), out["view"])
    if rc != 0:
        raise RuntimeError(f"oracle pbft_run failed rc={rc}")
    return out


def hotstuff_run(cfg, sweep: int = 0):
    """Run one chained-HotStuff sweep in the oracle (SPEC §7b). Returns
    dict of final arrays. No ``delivery`` knob: the oracle queries only
    the leader's O(N) star edges — already edge-wise, like dpos."""
    lib = get_lib()
    N, S = cfg.n_nodes, cfg.log_capacity
    out = {
        "committed": np.zeros((N, S), np.uint8),
        "dval": np.zeros((N, S), np.uint32),
        "clen": np.zeros(N, np.uint32),
        "view": np.zeros(N, np.uint32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_hotstuff_run(
        seed, N, cfg.n_rounds, S, cfg.f, cfg.view_timeout, cfg.n_byzantine,
        1 if cfg.byz_mode == "equivocate" else 0,
        cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        cfg.crash_cutoff, cfg.recover_cutoff, cfg.max_crashed,
        cfg.max_delay_rounds,
        1 if cfg.net_model == "switch" else 0, cfg.n_aggregators,
        cfg.agg_fail_cutoff, cfg.agg_stale_cutoff, cfg.agg_max_stale,
        cfg.agg_byz, cfg.agg_poison_cutoff, cfg.byz_uplink_cutoff,
        cfg.desync_cutoff, cfg.max_skew_rounds,
        out["committed"].reshape(-1), out["dval"].reshape(-1),
        out["clen"], out["view"])
    if rc != 0:
        raise RuntimeError(f"oracle hotstuff_run failed rc={rc}")
    return out


def dpos_run(cfg, sweep: int = 0):
    """Run one DPoS sweep in the oracle. Returns dict of final arrays."""
    lib = get_lib()
    V, L = cfg.n_nodes, cfg.log_capacity
    out = {
        "chain_r": np.zeros((V, L), np.uint32),
        "chain_p": np.zeros((V, L), np.uint32),
        "chain_len": np.zeros(V, np.uint32),
        "lib": np.zeros(V, np.int32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_dpos_run(
        seed, V, cfg.n_rounds, L, cfg.n_candidates, cfg.n_producers,
        cfg.epoch_len, cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        cfg.crash_cutoff, cfg.recover_cutoff, cfg.max_crashed,
        cfg.miss_cutoff, cfg.max_delay_rounds,
        cfg.suppress_cutoff, cfg.suppress_window,
        out["chain_r"].reshape(-1), out["chain_p"].reshape(-1),
        out["chain_len"], out["lib"])
    if rc != 0:
        raise RuntimeError(f"oracle dpos_run failed rc={rc}")
    return out
