"""ctypes bindings to the C++ scalar oracle (cpp/liboracle.so).

The oracle is the framework's CPU reference engine — the analog of the
reference's Rust implementation (SURVEY.md §2, "native-component
checklist"). pybind11 is not available in this environment, so the bridge
is a plain C ABI + ctypes (task environment notes).

The library is built on demand with `make -C cpp` the first time it is
imported, so `pip`-less checkouts and CI just work.
"""
from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_CPP_DIR = pathlib.Path(__file__).resolve().parents[2] / "cpp"
_LIB_PATH = _CPP_DIR / "liboracle.so"
_lib = None


def _build() -> None:
    subprocess.run(["make", "-C", str(_CPP_DIR), "-s"], check=True)


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    src_mtime = max((_CPP_DIR / f).stat().st_mtime for f in ("oracle.cpp", "threefry.h"))
    if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < src_mtime:
        _build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64
    p32 = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
    lib.ctpu_random_u32.restype = u32
    lib.ctpu_random_u32.argtypes = [u64, u32, u32, u32, u32]
    lib.ctpu_raft_run.restype = ctypes.c_int
    lib.ctpu_raft_run.argtypes = [u64] + [u32] * 9 + [p32] * 5
    _lib = lib
    return lib


def random_u32(seed: int, stream: int, ctx: int, c0: int, c1: int) -> int:
    return int(get_lib().ctpu_random_u32(seed, stream, ctx, c0, c1))


def raft_run(cfg, sweep: int = 0):
    """Run one Raft sweep in the oracle. Returns dict of final arrays."""
    lib = get_lib()
    N, L = cfg.n_nodes, cfg.log_capacity
    out = {
        "commit": np.zeros(N, np.uint32),
        "log_term": np.zeros((N, L), np.uint32),
        "log_val": np.zeros((N, L), np.uint32),
        "term": np.zeros(N, np.uint32),
        "role": np.zeros(N, np.uint32),
    }
    seed = (cfg.seed + sweep) & 0xFFFFFFFFFFFFFFFF
    rc = lib.ctpu_raft_run(
        seed, N, cfg.n_rounds, L, cfg.max_entries, cfg.t_min, cfg.t_max,
        cfg.drop_cutoff, cfg.partition_cutoff, cfg.churn_cutoff,
        out["commit"], out["log_term"].reshape(-1), out["log_val"].reshape(-1),
        out["term"], out["role"])
    if rc != 0:
        raise RuntimeError(f"oracle raft_run failed rc={rc}")
    return out
