"""Hang-proof JAX backend selection.

The TPU chip is reached through a remote-tunnel PJRT plugin whose backend
init can block indefinitely when the tunnel is down — and in-process init
cannot be timed out (it blocks in C++). Every entry point that might run
on the accelerator therefore selects its platform through
:func:`ensure_platform`, which probes backend init in a *subprocess* with
a hard timeout and falls back to the XLA CPU backend instead of hanging
(VERDICT.md round 1, weak #1: a down tunnel must cost a label, not the run).

The container's sitecustomize force-sets ``JAX_PLATFORMS`` at interpreter
startup, so pinning CPU requires both the env var (for XLA CPU client
flags) and a ``jax.config`` override on the already-imported module —
the same dance as tests/conftest.py.
"""
from __future__ import annotations

import contextlib
import os
import subprocess
import sys
import threading
import time

CPU_FALLBACK_TAG = "cpu-fallback:accelerator-unavailable"


@contextlib.contextmanager
def watchdog(timeout_s: float, on_timeout):
    """Hard deadline for a block that may hang in native code.

    A daemon thread calls ``on_timeout()`` and then ``os._exit(0)`` if the
    block does not finish in time. Signal- or exception-based timeouts
    cannot interrupt a PJRT call stuck in C++; process exit can. Use only
    around terminal work (e.g. an entire benchmark) where the emergency
    path is "emit the failure as data and stop".
    """
    def fire():
        try:
            on_timeout()
        finally:
            os._exit(0)

    t = threading.Timer(timeout_s, fire)
    t.daemon = True
    t.start()
    try:
        yield
    finally:
        t.cancel()


def log(msg: str) -> None:
    print(f"platform: {msg}", file=sys.stderr, flush=True)


def pin_cpu() -> None:
    """Force this process onto the XLA CPU backend."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def probe_accelerator(timeout_s: float = 90.0) -> str | None:
    """Initialize the default backend in a subprocess with a hard timeout
    and run one op; return its platform name if it is a real accelerator.
    """
    code = ("import jax; d = jax.devices(); "
            "x = (jax.numpy.ones((128,128)) @ jax.numpy.ones((128,128)))"
            ".block_until_ready(); print('PLATFORM=' + d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"probe: backend init exceeded {timeout_s:.0f}s (hung tunnel)")
        return None
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM="):
            plat = line.split("=", 1)[1]
            if plat != "cpu":
                return plat
            log("probe: default backend is cpu (no accelerator registered)")
            return None
    tail = (p.stderr or p.stdout).strip().splitlines()
    log(f"probe: init failed rc={p.returncode}: {tail[-1] if tail else '?'}")
    return None


def ensure_platform(requested: str = "auto", *, probe_timeout: float = 90.0,
                    retries: int = 1, backoff_s: float = 15.0) -> str:
    """Select and pin the JAX platform for this process; return its tag.

    requested:
      * ``"cpu"``  — pin the CPU backend, no probe.
      * ``"auto"`` — if the environment already pins CPU, keep it; else
        probe the accelerator (with retries) and fall back to CPU with
        the tag :data:`CPU_FALLBACK_TAG` when it is unreachable.
      * anything else (``"tpu"``/``"axon"``) — require the accelerator;
        raise RuntimeError (instead of hanging) when the probe fails.
    """
    if requested == "cpu":
        pin_cpu()
        return "cpu"
    if requested == "auto" and os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu()  # idempotent; also covers a sitecustomize re-override
        return "cpu"

    plat = None
    for attempt in range(max(1, retries)):
        plat = probe_accelerator(probe_timeout)
        if plat:
            break
        if attempt + 1 < retries:
            wait = backoff_s * (attempt + 1)
            log(f"probe: retrying in {wait:.0f}s ({attempt + 1}/{retries} failed)")
            time.sleep(wait)

    if plat:
        return plat
    if requested == "auto":
        log("accelerator unreachable — falling back to the CPU backend")
        pin_cpu()
        return CPU_FALLBACK_TAG
    raise RuntimeError(
        f"accelerator platform {requested!r} requested but backend init "
        f"failed/hung (probe timeout {probe_timeout:.0f}s, {retries} tries)")
