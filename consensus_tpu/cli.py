"""Python CLI — same flag surface as the native `cpp/consensus-sim` binary.

`consensus-sim --engine tpu ...` execs into this module, so both engines
are driven through one front door (SURVEY.md §2 component 13). Emits the
same JSON report shape as the native CLI; `digest` values are comparable
across engines because both serialize through the canonical decided-log
spec (docs/SPEC.md §4).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys


# flag name -> (Config field, default). Precedence: defaults < --config
# file < flags the user actually typed (argparse SUPPRESS tells us which).
_FLAG_FIELDS = {
    "protocol": ("protocol", "raft"),
    "engine": ("engine", "tpu"),
    "nodes": ("n_nodes", None),       # None ⇒ protocol-dependent default
    "rounds": ("n_rounds", 64),
    "sweeps": ("n_sweeps", 1),
    "seed": ("seed", 0),
    "log_capacity": ("log_capacity", 128),
    "max_entries": ("max_entries", 100),
    "t_min": ("t_min", 3),
    "t_max": ("t_max", 8),
    "max_active": ("max_active", 0),
    "drop_rate": ("drop_rate", 0.0),
    "partition_rate": ("partition_rate", 0.0),
    "churn_rate": ("churn_rate", 0.0),
    "crash_prob": ("crash_prob", 0.0),
    "recover_prob": ("recover_prob", 0.0),
    "max_crashed": ("max_crashed", 0),
    "miss_rate": ("miss_rate", 0.0),
    "suppress_rate": ("suppress_rate", 0.0),
    "suppress_window": ("suppress_window", 16),
    "max_delay_rounds": ("max_delay_rounds", 0),
    "net_model": ("net_model", "flat"),
    "n_aggregators": ("n_aggregators", 0),
    "agg_fail_rate": ("agg_fail_rate", 0.0),
    "agg_stale_rate": ("agg_stale_rate", 0.0),
    "agg_max_stale": ("agg_max_stale", 1),
    "agg_byz": ("agg_byz", 0),
    "agg_poison_rate": ("agg_poison_rate", 0.0),
    "byz_uplink_rate": ("byz_uplink_rate", 0.0),
    "desync_rate": ("desync_rate", 0.0),
    "max_skew_rounds": ("max_skew_rounds", 1),
    "attack": ("attack", "none"),
    "attack_rate": ("attack_rate", 1.0),
    "attack_target": ("attack_target", 0),
    "f": ("f", 1),
    "view_timeout": ("view_timeout", 8),
    "n_byzantine": ("n_byzantine", 0),
    "byz_mode": ("byz_mode", "silent"),
    "fault_model": ("fault_model", "edge"),
    "n_proposers": ("n_proposers", 0),
    "candidates": ("n_candidates", 16),
    "producers": ("n_producers", 4),
    "epoch_len": ("epoch_len", 16),
    "scan_chunk": ("scan_chunk", 0),
    "sweep_chunk": ("sweep_chunk", 0),
    "telemetry_window": ("telemetry_window", 0),
}
_FLAG_TYPES = {"protocol": str, "engine": str, "byz_mode": str,
               "fault_model": str, "drop_rate": float,
               "partition_rate": float, "churn_rate": float,
               "crash_prob": float, "recover_prob": float,
               "miss_rate": float, "suppress_rate": float,
               "attack": str, "attack_rate": float,
               "net_model": str, "agg_fail_rate": float,
               "agg_stale_rate": float, "agg_poison_rate": float,
               "byz_uplink_rate": float, "desync_rate": float}

# Config fields with NO native-CLI flag (cpp/consensus_sim.cpp): TPU-
# engine execution/adversary knobs. The native front door still reaches
# them for --engine tpu because it re-execs `python3 -m consensus_tpu`
# BEFORE strict flag parsing; for --engine cpu they are rejected (here
# or by Config validation — the SPEC §A.3 targeted attacks are the one
# remaining tpu-only adversary; §6c crash/§A.1 miss/§A.2 delay are
# mirrored in the oracle and natively flagged) rather than silently
# ignored. Machine-checked against both flag surfaces by tools/lint
# (check `cli`): removing an entry demands a native flag, adding one
# demands the field really has none.
NATIVE_CLI_TPU_ONLY = frozenset({
    "mesh_shape", "scan_chunk", "sweep_chunk",
    "attack", "attack_rate", "attack_target",
    "telemetry_window",
})


def build_parser() -> argparse.ArgumentParser:
    # Config-field flags default to SUPPRESS so args_to_config can tell
    # "user typed --rounds 64" from "argparse default 64" — only typed
    # flags may override a --config file (the review's precedence bug).
    ap = argparse.ArgumentParser(prog="consensus-sim")
    for flag, (_, _default) in _FLAG_FIELDS.items():
        typ = _FLAG_TYPES.get(flag, int)
        kw = dict(type=typ, default=argparse.SUPPRESS)
        if flag == "protocol":
            kw["choices"] = ["raft", "pbft", "paxos", "dpos", "hotstuff"]
        if flag == "engine":
            kw["choices"] = ["cpu", "tpu"]
        if flag == "net_model":
            kw["choices"] = ["flat", "switch"]
        ap.add_argument("--" + flag.replace("_", "-"), **kw)
    ap.add_argument("--mesh", default=argparse.SUPPRESS,
                    help="device mesh, e.g. '8' (sweep-parallel) or '2x4' "
                         "(sweep x node); TPU engine only")
    ap.add_argument("--oracle-delivery", default="auto",
                    choices=["auto", "dense", "edge"],
                    help="cpu engine only: how the oracle answers delivery "
                         "queries — dense materializes the [N,N] matrix per "
                         "round, edge evaluates per-edge draws on demand "
                         "(O(live edges)/round; what makes 100k-node capped "
                         "configs oracle-tractable). Digests are identical "
                         "for every value (docs/PERF.md)")
    ap.add_argument("--checkpoint", default="",
                    help="checkpoint file; resumes from the newest valid "
                         "(checksum-verified) rotation if present. "
                         "Snapshots are written by a double-buffered "
                         "background writer so the chunk loop never waits "
                         "on IO (docs/PERF.md; --sync-checkpoints opts out)")
    ap.add_argument("--group-dir", default="",
                    help="grouped-sweep resumable layout (requires "
                         "--sweep-chunk grouping; exclusive with "
                         "--checkpoint): each sweep group snapshots into "
                         "its own subdirectory plus a completed-group "
                         "manifest, and an interrupted run resumes by "
                         "skipping completed groups and continuing the "
                         "first incomplete one mid-scan "
                         "(docs/RESILIENCE.md)")
    ap.add_argument("--sync-checkpoints", action="store_true",
                    help="write each snapshot synchronously on the chunk "
                         "loop (the pre-async behavior) instead of the "
                         "default background double-buffered writer; "
                         "bit-identical results and on-disk bytes either "
                         "way — this only trades hot-path stall for "
                         "zero writer concurrency; requires --checkpoint")
    ap.add_argument("--fsync-checkpoints", action="store_true",
                    help="fsync each snapshot's bytes before (and its "
                         "directory entry after) the atomic rename, making "
                         "checkpoints durable against power loss, not just "
                         "process death (docs/RESILIENCE.md §2b); requires "
                         "--checkpoint")
    ap.add_argument("--keep-checkpoints", type=int,
                    default=argparse.SUPPRESS,
                    help="retain the last K checkpoint rotations "
                         "(ckpt.npz, ckpt.1.npz, ...; default 2) so a "
                         "torn latest snapshot still leaves a valid "
                         "fallback; requires --checkpoint")
    ap.add_argument("--retries", type=int, default=0,
                    help="supervised execution: retry transient failures "
                         "up to N times with exponential backoff, resuming "
                         "from the newest valid checkpoint between "
                         "attempts (docs/RESILIENCE.md)")
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="supervised execution: wall-clock budget in "
                         "seconds — no new attempt starts past it "
                         "(0 = unlimited)")
    ap.add_argument("--fallback-cpu", action="store_true",
                    help="supervised execution: once retries/deadline are "
                         "exhausted, degrade to the CPU oracle engine "
                         "(sound: both engines are decided-log "
                         "digest-equivalent by contract)")
    ap.add_argument("--out", default="", help="dump raw payload bytes")
    ap.add_argument("--profile", default="",
                    help="write a jax.profiler trace to this directory "
                         "(TPU engine only); our span boundaries are "
                         "mirrored into the profiler timeline "
                         "(docs/OBSERVABILITY.md)")
    ap.add_argument("--trace-out", default="",
                    help="write span/event JSONL (dispatches, checkpoint "
                         "IO, supervisor attempts) to this file; schema "
                         "in docs/OBSERVABILITY.md, checked by "
                         "tools/validate_trace.py")
    ap.add_argument("--metrics-out", default="",
                    help="write the metrics snapshot (dispatch histogram, "
                         "checkpoint counters, retries) to this file — "
                         "JSON, or Prometheus text format when the path "
                         "ends in .prom; a supervised run also dumps its "
                         "RunReport next to it as <stem>.run_report.json")
    ap.add_argument("--telemetry", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="accumulate on-device protocol counters (leader "
                         "elections, quorum hits, promises/nacks, ...) "
                         "alongside the scan carry and add their totals "
                         "to the report (TPU engine only; digest-neutral "
                         "— docs/OBSERVABILITY.md)")
    ap.add_argument("-v", "--verbose", action="count", default=0,
                    help="print checkpoint-IO timings and telemetry "
                         "totals to stderr, plus a live per-chunk "
                         "progress line (current-window commit rate + "
                         "ETA, backed by the rounds_completed/sim_eta_s "
                         "gauges)")
    ap.add_argument("--scenario", default="",
                    help="run a named scripted-attack scenario from the "
                         "SPEC Appendix A library "
                         "(consensus_tpu/scenarios; e.g. "
                         "repeated-election-disruption, "
                         "rolling-producer-outage, delay-storm, "
                         "crash-churn-under-partition): overrides the "
                         "adversary knobs + protocol, turns the flight "
                         "recorder on, evaluates the scenario's timeline "
                         "assertions (availability dip, bounded recovery, "
                         "DPoS LIB stall) and exits nonzero if they fail; "
                         "verdict lands in the report under 'scenario'. "
                         "TPU engine only (the assertions read the flight "
                         "recorder)")
    ap.add_argument("--serve-port", type=int, default=None, metavar="PORT",
                    help="serve live run introspection over localhost HTTP "
                         "while the run executes: /metrics (Prometheus "
                         "text of the process registry) and /status (run "
                         "identity + live rounds_completed/sim_eta_s "
                         "gauges, plus the RunReport when supervised) — "
                         "docs/OBSERVABILITY.md §'Observatory'. 0 binds an "
                         "ephemeral port; the bound port is printed to "
                         "stderr. TPU engine only (the gauges are the "
                         "chunk loop's)")
    ap.add_argument("--submit", default="", metavar="URL",
                    help="client mode: instead of running locally, POST "
                         "the flag-built config (plus --scenario, when "
                         "given) as a job to a sweepd service at URL "
                         "(e.g. http://127.0.0.1:8787, `python -m "
                         "consensus_tpu.service`) and print the job id; "
                         "execution-local flags (--checkpoint, "
                         "--retries, --f-sweep, ...) are rejected — the "
                         "service owns execution (docs/SERVICE.md)")
    ap.add_argument("--submit-wait", action="store_true",
                    help="with --submit: poll /jobs/<id> until the job "
                         "finishes and print its final document; exit 0 "
                         "on done (3 on a failed scenario verdict, 1 on "
                         "a failed job)")
    ap.add_argument("--job-name", default="",
                    help="with --submit: display name for the job "
                         "(default: derived from the config shape)")
    ap.add_argument("--config", default="",
                    help="JSON config file; typed flags override its values")
    ap.add_argument("--platform", default="auto",
                    choices=["auto", "cpu", "tpu", "tpu-trust"],
                    help="JAX backend for the tpu engine: auto probes the "
                         "accelerator in a subprocess (hang-proof, costs "
                         "one extra backend init ~seconds) and falls back "
                         "to the XLA CPU backend; cpu pins CPU; tpu "
                         "requires the accelerator or fails fast; "
                         "tpu-trust skips the probe entirely (fastest, "
                         "but hangs if the tunnel is down)")
    ap.add_argument("--probe-timeout", type=float, default=90.0,
                    help="accelerator probe timeout in seconds")
    ap.add_argument("--f-sweep", default="",
                    help="pbft + tpu engine only: run a whole f ladder "
                         "('1..128' or '1,2,4') as ONE compiled padded "
                         "program (engines/pbft_sweep.py), under either "
                         "fault model (--fault-model bcast runs the §6b "
                         "aggregate round with traced per-rung f) and "
                         "with --sweeps K independent instances per rung; "
                         "rung k sweep j uses f=fs[k], seed=seed+k+j. "
                         "Reports real-node steps/sec + per-rung digests "
                         "and the digest of the concatenated per-rung "
                         "canonical payloads (byte-equal to running each "
                         "f alone)")
    return ap


def _parse_fsweep(spec: str) -> list[int]:
    """Parse '1..128' / '1,2,4' into a validated list of f values."""
    try:
        if ".." in spec:
            lo, hi = spec.split("..")
            fs = list(range(int(lo), int(hi) + 1))
        else:
            fs = [int(x) for x in spec.split(",")]
    except ValueError:
        raise ValueError(f"malformed --f-sweep spec {spec!r} "
                         "(expected 'LO..HI' or comma-separated ints)")
    if not fs:
        raise ValueError(f"--f-sweep {spec!r} is an empty range")
    if min(fs) < 1:
        raise ValueError(f"--f-sweep values must be >= 1, got {min(fs)}")
    return fs


def _run_fsweep(cfg, args, platform_tag: str) -> int:
    """Run the padded single-program PBFT f-sweep and report one JSON line."""
    from .core import serialize
    from .engines.pbft_sweep import pbft_fsweep_timed, rung_payloads

    from .obs import trace as obs_trace

    fs = args.parsed_fs
    with obs_trace.span("pbft_fsweep", n_elements=len(fs),
                        n_rounds=cfg.n_rounds,
                        fault_model=cfg.fault_model):
        out, compile_s, wall, steps = pbft_fsweep_timed(cfg, fs)
    per_rung = rung_payloads(out)
    payload = b"".join(per_rung)
    if args.out:
        with open(args.out, "wb") as fp:
            fp.write(payload)

    print(json.dumps({
        "protocol": "pbft", "engine": "tpu", "platform": platform_tag,
        "f_sweep": args.f_sweep, "n_elements": len(fs),
        "n_rounds": cfg.n_rounds, "n_sweeps": cfg.n_sweeps,
        "fault_model": cfg.fault_model, "seed": cfg.seed,
        "steps": steps, "wall_s": round(wall, 6),
        "steps_per_sec": round(steps / wall, 1) if wall > 0 else 0.0,
        "compile_s_one_program": round(compile_s, 3),
        "payload_bytes": len(payload),
        # Per-rung digests == the digests of standalone f=fs[k],
        # seed=seed+k, n_sweeps=K runs (engines/pbft_sweep.rung_payloads
        # — the carve-out-lifting equivalence, pinned by both front
        # doors in tests/test_cli.py).
        "rung_digests": [serialize.digest(p) for p in per_rung],
        "digest": serialize.digest(payload),
    }))
    return 0


def args_to_config(args):
    import dataclasses

    from .core.config import Config

    fields = {}
    if getattr(args, "config", ""):
        with open(args.config) as fp:
            # from_json filters unknown keys and normalizes mesh_shape.
            fields = dataclasses.asdict(Config.from_json(fp.read()))
    given = vars(args)
    for flag, (field, default) in _FLAG_FIELDS.items():
        if flag in given:
            fields[field] = given[flag]
        elif field not in fields and default is not None:
            fields[field] = default
    if "mesh" in given:
        fields["mesh_shape"] = tuple(int(x) for x in given["mesh"].split("x"))
    elif "mesh_shape" in fields:
        fields["mesh_shape"] = tuple(fields["mesh_shape"])
    if fields.get("n_nodes") is None:
        fields["n_nodes"] = 3 * fields["f"] + 1 \
            if fields["protocol"] in ("pbft", "hotstuff") else 5
    return Config(**fields)


def _submit_job(cfg, args, parser) -> int:
    """--submit: the sweepd client mode. Validation is the service's
    job (admission 400s come back as clean one-liners); this side only
    refuses flags that ask for LOCAL execution machinery the service
    owns (no silent ignores)."""
    import urllib.error
    import urllib.request

    rejected = [name for name, on in [
        ("--checkpoint", args.checkpoint),
        ("--group-dir", args.group_dir),
        ("--f-sweep", bool(args.f_sweep)),
        ("--retries/--deadline/--fallback-cpu",
         bool(args.retries or args.deadline or args.fallback_cpu)),
        ("--profile", args.profile),
        ("--serve-port", args.serve_port is not None),
        ("--trace-out", args.trace_out),
        ("--metrics-out", args.metrics_out),
        ("--out", args.out),
        ("--oracle-delivery", args.oracle_delivery != "auto"),
    ] if on]
    if rejected:
        parser.error(f"{', '.join(rejected)}: local-execution flags do "
                     "not apply to --submit (the service owns "
                     "checkpoints, supervision and artifacts — "
                     "docs/SERVICE.md)")

    base = args.submit.rstrip("/")
    body: dict = {"config": json.loads(cfg.to_json())}
    if args.scenario:
        body["scenario"] = args.scenario
    if args.job_name:
        body["name"] = args.job_name

    def _call(url: str, data: bytes | None = None) -> dict:
        req = urllib.request.Request(
            url, data=data, method="POST" if data else "GET",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read().decode())

    try:
        doc = _call(base + "/jobs", json.dumps(body).encode())
    except urllib.error.HTTPError as exc:
        try:
            msg = json.loads(exc.read().decode()).get("error", str(exc))
        except ValueError:
            msg = str(exc)
        print(f"submit: service rejected the job: {msg}", file=sys.stderr)
        return 2
    except urllib.error.URLError as exc:
        print(f"submit: cannot reach {base}: {exc.reason} (is sweepd "
              "running? `python -m consensus_tpu.service --port P`)",
              file=sys.stderr)
        return 2
    if not args.submit_wait:
        print(json.dumps({"id": doc["id"], "status": doc["status"],
                          "name": doc["name"],
                          "url": f"{base}/jobs/{doc['id']}"}))
        return 0
    import time as _time
    # No overall deadline (jobs are legitimately long; the durable
    # queue means the job outlives this client anyway) — but transient
    # poll failures get a bounded tolerance instead of a raw traceback,
    # and a persistently-gone service is a clean exit, not a hang.
    failing_since = None
    while True:
        try:
            job = _call(f"{base}/jobs/{doc['id']}")
            failing_since = None
        except urllib.error.URLError as exc:
            now = _time.monotonic()
            failing_since = failing_since or now
            if now - failing_since > 30.0:
                reason = getattr(exc, "reason", exc)
                print(f"submit: lost {base} while waiting on "
                      f"{doc['id']} ({reason}); the job survives in "
                      "the service's durable queue — poll "
                      f"{base}/jobs/{doc['id']} once it is back",
                      file=sys.stderr)
                return 2
            _time.sleep(1.0)
            continue
        if job.get("status") in ("done", "failed"):
            break
        _time.sleep(0.2)
    print(json.dumps(job))
    if job["status"] != "done":
        return 1
    verdict = (job.get("result") or {}).get("scenario")
    return 0 if verdict is None or verdict.get("passed") else 3


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    cfg = args_to_config(args)

    if args.submit_wait and not args.submit:
        parser.error("--submit-wait requires --submit")
    if args.job_name and not args.submit:
        parser.error("--job-name requires --submit (it names the "
                     "service-side job, nothing local)")
    if args.submit:
        # Client mode: the scenario (if any) is applied — and the
        # config re-validated — by the service at admission, so the
        # flag-built config ships as-is.
        return _submit_job(cfg, args, parser)

    if args.scenario:
        from . import scenarios
        if cfg.engine != "tpu":
            parser.error("--scenario pairs a scripted attack with "
                         "flight-recorder timeline assertions, which only "
                         "the TPU engine records (got --engine "
                         f"{cfg.engine})")
        if args.fallback_cpu:
            parser.error("--scenario cannot degrade to the CPU oracle "
                         "(--fallback-cpu): the oracle records no flight "
                         "series, so the scenario's timeline assertions "
                         "would be unjudgeable")
        # Config fields the user actually typed (SUPPRESS defaults make
        # them detectable): a scenario protocol switch must reject —
        # not silently discard — an explicit shape flag.
        typed = {field for flag, (field, _) in _FLAG_FIELDS.items()
                 if hasattr(args, flag)}
        try:
            args.scenario_def = scenarios.get(args.scenario)
            cfg = scenarios.apply(cfg, args.scenario_def, explicit=typed)
        except ValueError as exc:
            parser.error(str(exc))

    if cfg.telemetry_window > 0 and not args.telemetry:
        # The window ring IS the telemetry counters, windowed —
        # --telemetry-window implies --telemetry rather than silently
        # recording nothing (docs/OBSERVABILITY.md §"Flight recorder").
        args.telemetry = True

    if cfg.engine != "tpu":
        # TPU-engine-only features must not be silently ignored. Name the
        # actual source: a typed flag, or a field inherited via --config.
        typed = vars(args)
        rejected = [name for name, on in [
            ("--mesh" if "mesh" in typed else "config field mesh_shape",
             "mesh" in typed or cfg.mesh_shape),
            ("--checkpoint", args.checkpoint),
            ("--group-dir", args.group_dir),
            ("--sync-checkpoints", args.sync_checkpoints),
            ("--fsync-checkpoints", args.fsync_checkpoints),
            ("--keep-checkpoints", "keep_checkpoints" in typed),
            ("--retries", args.retries),
            ("--deadline", args.deadline),
            ("--fallback-cpu", args.fallback_cpu),
            ("--profile", args.profile),
            ("--telemetry", args.telemetry),
            ("--scan-chunk" if "scan_chunk" in typed
             else "config field scan_chunk",
             cfg.scan_chunk),
            ("--sweep-chunk" if "sweep_chunk" in typed
             else "config field sweep_chunk",
             cfg.sweep_chunk),
            ("--serve-port", args.serve_port is not None),
        ] if on]
        if rejected:
            parser.error(f"{', '.join(rejected)}: only valid with "
                         f"--engine tpu (got --engine {cfg.engine})")
    if args.oracle_delivery != "auto":
        if cfg.engine != "cpu":
            parser.error("--oracle-delivery is a cpu-oracle execution knob "
                         "(cpp/oracle.cpp Net); the tpu engine has no [N,N] "
                         "materialization to switch")
        if cfg.protocol in ("dpos", "hotstuff"):
            parser.error(f"--oracle-delivery does not apply to "
                         f"{cfg.protocol} (its oracle queries one "
                         "leader/producer row per round — already "
                         "edge-wise)")

    # Usage errors must fail fast — before any accelerator probe.
    if args.checkpoint and cfg.sweep_chunk and cfg.sweep_chunk < cfg.n_sweeps:
        parser.error("--checkpoint is not supported with sweep_chunk "
                     "grouping (one rotation set cannot hold N groups' "
                     "snapshots); use --group-dir for the per-group "
                     "resumable layout, or --scan-chunk for mid-run "
                     "snapshots of an ungrouped run")
    if args.group_dir:
        if args.checkpoint:
            parser.error("--group-dir and --checkpoint are exclusive "
                         "(the grouped layout snapshots per group)")
        if not cfg.sweep_chunk or cfg.sweep_chunk >= cfg.n_sweeps:
            parser.error("--group-dir needs --sweep-chunk grouping "
                         "(sweep_chunk in (0, n_sweeps)); use "
                         "--checkpoint for an ungrouped run")
    if args.serve_port is not None and not 0 <= args.serve_port <= 65535:
        parser.error(f"--serve-port must be in [0, 65535] (0 = ephemeral), "
                     f"got {args.serve_port}")
    keep = getattr(args, "keep_checkpoints", 2)
    snapshots_on = args.checkpoint or args.group_dir
    if "keep_checkpoints" in vars(args) and not snapshots_on:
        parser.error("--keep-checkpoints requires --checkpoint or "
                     "--group-dir (it is the snapshot rotation depth)")
    if args.fsync_checkpoints and not snapshots_on:
        parser.error("--fsync-checkpoints requires --checkpoint or "
                     "--group-dir (there is nothing to make durable "
                     "without snapshots)")
    if args.sync_checkpoints and not snapshots_on:
        parser.error("--sync-checkpoints requires --checkpoint or "
                     "--group-dir (it selects HOW snapshots are written; "
                     "nothing is saved without one)")
    if keep < 1:
        parser.error(f"--keep-checkpoints must be >= 1, got {keep}")
    if args.retries < 0:
        parser.error(f"--retries must be >= 0, got {args.retries}")
    if args.deadline < 0:
        parser.error(f"--deadline must be >= 0, got {args.deadline}")
    supervise = bool(args.retries or args.deadline or args.fallback_cpu)
    if supervise and args.profile:
        parser.error("--profile is not supported with supervised execution "
                     "(--retries/--deadline/--fallback-cpu): a retried "
                     "attempt would overwrite the trace mid-stream")
    if args.f_sweep:
        if cfg.protocol != "pbft" or cfg.engine != "tpu":
            parser.error("--f-sweep requires --protocol pbft --engine tpu")
        unsupported = [name for name, on in [
            ("--checkpoint", args.checkpoint),
            ("--group-dir", args.group_dir),
            ("--profile", args.profile),
            ("--retries/--deadline/--fallback-cpu", supervise),
            ("--crash-prob", cfg.crash_prob > 0),
            ("--scenario", bool(args.scenario)),
            ("--telemetry", args.telemetry),
            ("--telemetry-window", cfg.telemetry_window > 0),
        ] if on]
        if unsupported:
            parser.error(f"{', '.join(unsupported)}: not supported with "
                         "--f-sweep (no checkpoint/profile hooks on this "
                         "path yet; §6c is unmodeled by the padded rounds)")
        try:
            args.parsed_fs = _parse_fsweep(args.f_sweep)
            if cfg.n_byzantine > min(args.parsed_fs):
                parser.error(
                    f"--n-byzantine {cfg.n_byzantine} exceeds the smallest "
                    f"--f-sweep rung f={min(args.parsed_fs)}; every rung "
                    f"must satisfy the pbft n_byzantine <= f invariant")
        except ValueError as exc:
            parser.error(str(exc))

    platform_tag = "oracle"
    if cfg.engine == "tpu":
        if args.platform == "tpu-trust":
            platform_tag = "tpu-trust"  # no probe; init may hang if down
        else:
            from .utils.platform import ensure_platform
            platform_tag = ensure_platform(
                args.platform, probe_timeout=args.probe_timeout)

    from .obs import trace as obs_trace
    if args.trace_out or args.profile:
        # One sink for the whole run; with --profile our span boundaries
        # are mirrored into the jax.profiler timeline so both traces
        # line up (docs/OBSERVABILITY.md).
        obs_trace.configure(args.trace_out or None,
                            annotate_jax=bool(args.profile))
    # _execute parks the supervised RunReport (success or give-up) here
    # so the finally below can dump it next to the metrics snapshot.
    report_holder: dict = {}
    server = None
    if args.serve_port is not None:
        server = _start_server(cfg, args, platform_tag, report_holder)
    try:
        return _execute(cfg, args, platform_tag, keep, supervise,
                        report_holder)
    finally:
        if server is not None:
            server.close()
        # Written on EVERY exit path — a run that died mid-flight still
        # leaves its partial dispatch/checkpoint data and (when
        # supervised) the per-attempt record: the diagnosis artifacts
        # matter most exactly when the run gave up. An artifact-write
        # failure on that path must not replace the exception being
        # diagnosed or skip the trace close; on a successful run it
        # still fails loudly (a requested artifact went missing).
        in_flight = sys.exc_info()[0] is not None
        try:
            if args.metrics_out:
                _write_metrics(args, report_holder.get("run_report"),
                               report_holder.get("flight"))
        except OSError as exc:
            if not in_flight:
                raise
            print(f"metrics: failed to write {args.metrics_out}: {exc}",
                  file=sys.stderr)
        finally:
            obs_trace.close()


def _start_server(cfg, args, platform_tag: str, report_holder: dict):
    """--serve-port: the live-introspection endpoint (obs/serve.py),
    started BEFORE compile/execution so /metrics and /status answer for
    the whole run, not just the post-warmup stretch. Also stamps the
    run_info info-metric so a scrape self-identifies its run."""
    import os

    from .obs import metrics as obs_metrics
    from .obs import serve as obs_serve
    obs_metrics.info("run_info").set(
        protocol=cfg.protocol, engine=cfg.engine, platform=platform_tag)
    static = {"protocol": cfg.protocol, "engine": cfg.engine,
              "platform": platform_tag, "n_nodes": cfg.n_nodes,
              "n_rounds": cfg.n_rounds, "n_sweeps": cfg.n_sweeps,
              "seed": cfg.seed, "pid": os.getpid()}

    def status():
        doc = dict(static)
        rr = report_holder.get("run_report")
        if rr is not None:
            doc["run_report"] = rr
        return doc

    try:
        server = obs_serve.MetricsServer(args.serve_port, status=status)
    except OSError as exc:
        # A busy port arrives as obs_serve.PortInUseError (an OSError)
        # whose str() is already the actionable one-liner; any other
        # bind failure gets the same clean-diagnostic treatment — no
        # traceback, and no simulation ran, so nothing is half-done.
        print(f"serve: {exc}", file=sys.stderr, flush=True)
        raise SystemExit(2)
    print(f"serve: listening on http://127.0.0.1:{server.port} "
          f"(/metrics, /status)", file=sys.stderr, flush=True)
    return server


def _write_metrics(args, run_report: dict | None,
                   flight: dict | None = None) -> None:
    """--metrics-out: snapshot the registry (JSON, or Prometheus text
    for a .prom path); a supervised run's RunReport lands next to it.
    A flight-recorder run's windowed series + latency histograms are
    embedded as the ``"flight"`` block — the artifact
    ``tools/teleview`` (obs/timeline.py) loads. Prometheus text cannot
    carry the series, so a ``.prom`` path writes them to a
    ``<stem>.flight.json`` sidecar instead of silently dropping what
    the run spent device time recording. Called from main's finally,
    so failing runs get their artifacts too."""
    from .obs import metrics as obs_metrics
    path = pathlib.Path(args.metrics_out)
    if path.suffix == ".prom":
        path.write_text(obs_metrics.to_prometheus())
        if flight is not None:
            path.with_name(path.stem + ".flight.json").write_text(
                json.dumps({"version": obs_metrics.SCHEMA_VERSION,
                            "metrics": {}, "flight": flight}, indent=2))
    else:
        doc = {"version": obs_metrics.SCHEMA_VERSION,
               "metrics": obs_metrics.snapshot()}
        if flight is not None:
            doc["flight"] = flight
        path.write_text(json.dumps(doc, indent=2))
    if run_report is not None:
        rpath = path.with_name(path.stem + ".run_report.json")
        rpath.write_text(json.dumps(run_report, indent=2))
        print(f"run report written to {rpath}", file=sys.stderr)


def _print_verbose(result) -> None:
    io = result.extras.get("checkpoint_io")
    if io is not None:
        # The hidden-vs-blocking split is the async pipeline's whole
        # point: blocking is what the chunk loop still paid (enqueue +
        # backpressure + final drain; the full save wall under
        # --sync-checkpoints), hidden is writer-thread time overlapped
        # with the next chunk's compute (pull = device→host transfer,
        # write = container + rename [+ fsync]).
        print(f"checkpoint io: {io['saves']} saves "
              f"({io['bytes_written']} B), "
              f"blocking {io['save_s']:.3f}s, "
              f"hidden {io['save_hidden_s']:.3f}s "
              f"(pull {io['pull_s']:.3f}s, write {io['write_s']:.3f}s), "
              f"{io['loads']} loads "
              f"({io['bytes_read']} B, {io['load_s']:.3f}s)",
              file=sys.stderr)
    tel = result.extras.get("telemetry")
    if tel is not None:
        totals = " ".join(f"{k}={v}" for k, v in tel["totals"].items())
        print(f"telemetry: {totals}", file=sys.stderr)
    fl = result.extras.get("flight")
    if fl is not None:
        print(f"flight: {fl['n_windows']} windows x "
              f"{fl['window_rounds']} rounds recorded — inspect with "
              f"`python -m tools.teleview --metrics <metrics-out>`",
              file=sys.stderr)


def _progress_printer():
    """The -v live progress line (one per chunk, stderr). Rate comes
    from the flight recorder's live window when on, else from the
    chunk's telemetry delta (None until the second chunk), else it is
    omitted (plain runs still get round/ETA)."""
    def emit(info: dict) -> None:
        parts = [f"progress: r={info['round']}/{info['n_rounds']} "
                 f"({100 * info['round'] // info['n_rounds']}%)"]
        if info.get("window") is not None:
            wi, nw = info["window"]
            parts.append(f"window {wi + 1}/{nw}")
        rate = info.get("commit_rate")
        if rate is not None:
            parts.append(f"commit_rate={rate:.1f}/round")
        parts.append(f"eta={info['eta_s']:.1f}s")
        print(" ".join(parts), file=sys.stderr, flush=True)
    return emit


def _execute(cfg, args, platform_tag: str, keep: int, supervise: bool,
             report_holder: dict) -> int:
    if args.f_sweep:
        return _run_fsweep(cfg, args, platform_tag)

    from .network import simulator

    run_kw = {}
    if args.checkpoint:
        run_kw = dict(checkpoint_path=args.checkpoint, resume=True,
                      keep_checkpoints=keep,
                      fsync_checkpoints=args.fsync_checkpoints,
                      sync_checkpoints=args.sync_checkpoints)
    elif args.group_dir:
        run_kw = dict(group_dir=args.group_dir, resume=True,
                      keep_checkpoints=keep,
                      fsync_checkpoints=args.fsync_checkpoints,
                      sync_checkpoints=args.sync_checkpoints)
    if args.telemetry:
        run_kw["telemetry"] = True
    if args.oracle_delivery != "auto":
        run_kw["oracle_delivery"] = args.oracle_delivery
    if args.verbose and cfg.engine == "tpu" and not supervise:
        # The live per-chunk line (supervised runs keep their own
        # per-attempt reporting; the gauges update regardless).
        run_kw["progress"] = _progress_printer()

    if supervise:
        from .network import supervisor
        try:
            result = supervisor.supervised_run(
                cfg, retries=args.retries,
                deadline_s=args.deadline or None,
                fallback_cpu=args.fallback_cpu,
                checkpoint_path=args.checkpoint or None,
                group_dir=args.group_dir or None,
                keep_checkpoints=keep,
                fsync_checkpoints=args.fsync_checkpoints,
                sync_checkpoints=args.sync_checkpoints,
                telemetry=args.telemetry,
                oracle_delivery=args.oracle_delivery)
        except supervisor.SupervisorError as exc:
            # Park the give-up report for main's finally to dump.
            report_holder["run_report"] = exc.report.to_dict()
            raise
    elif args.profile and cfg.engine == "tpu":
        import jax
        with jax.profiler.trace(args.profile):
            result = simulator.run(cfg, **run_kw)
        print(f"profile trace written to {args.profile}", file=sys.stderr)
    else:
        result = simulator.run(cfg, **run_kw)

    if args.out:
        with open(args.out, "wb") as f:
            f.write(result.payload)

    report = {
        # result.config.engine, not cfg.engine: a supervised run may have
        # degraded to the CPU oracle (fallback_used below says so).
        "protocol": cfg.protocol, "engine": result.config.engine,
        "platform": platform_tag,
        "n_nodes": cfg.n_nodes, "n_rounds": cfg.n_rounds,
        "n_sweeps": cfg.n_sweeps, "seed": cfg.seed,
        "steps": result.node_round_steps,
        "wall_s": round(result.wall_s, 6),
        "steps_per_sec": round(result.steps_per_sec, 1),
        "payload_bytes": len(result.payload),
        "digest": result.digest,
    }
    if result.timing_includes_compile:
        # steps/sec includes jit+compile (checkpoint runs skip warmup) —
        # flag it so the number isn't read as steady-state throughput.
        report["timing_includes_compile"] = True
    tel = result.extras.get("telemetry")
    if tel is not None:
        report["telemetry"] = tel["totals"]
    io = result.extras.get("checkpoint_io")
    if io is not None:
        # The hidden/blocking/pull/write split in the machine-readable
        # report (schema-checked by tools/validate_trace.py
        # --cli-report), not just the -v stderr line.
        report["checkpoint_io"] = {
            k: round(v, 6) if isinstance(v, float) else v
            for k, v in io.items()}
    fl = result.extras.get("flight")
    if fl is not None:
        from .obs import timeline as obs_timeline
        tl = obs_timeline.from_flight_dict(fl)
        derived = obs_timeline.derive(tl)
        # Derived liveness gauges (timeline_*) land in the process
        # registry BEFORE main's finally snapshots --metrics-out.
        obs_timeline.export_metrics(derived)
        # The full series goes into the metrics artifact (teleview's
        # input) — only when one will be written: the .tolist()
        # boxing is O(n_windows · K) Python objects, real heap at
        # W=1 flagship scale. The one-line report carries the
        # headline liveness numbers + the (small) latency
        # histograms, schema-checked by validate_trace --cli-report.
        if args.metrics_out:
            report_holder["flight"] = {
                "engine": fl["engine"],
                "window_rounds": int(fl["window_rounds"]),
                "n_windows": int(fl["n_windows"]),
                "n_rounds": int(fl["n_rounds"]),
                "bucket_lo": [int(b) for b in fl["bucket_lo"]],
                "windows": {k: v.tolist()
                            for k, v in fl["windows"].items()},
                "latency": {k: v.tolist()
                            for k, v in fl["latency"].items()},
            }
        report["flight"] = {
            "window_rounds": int(fl["window_rounds"]),
            "n_windows": int(fl["n_windows"]),
            "availability": derived["availability"]["mean"],
            "stall_windows": derived["stall_windows"]["total"],
            "latency": {k: [int(x) for x in v.sum(axis=0)]
                        for k, v in fl["latency"].items()},
        }
    rr = result.extras.get("run_report")
    if rr is not None:
        report_holder["run_report"] = rr
        report["attempts"] = rr["n_attempts"]
        report["resumed_from_round"] = rr["resumed_from_round"]
        report["fallback_used"] = rr["fallback_used"]
        if rr["fallback_used"]:
            report["platform"] = "oracle"
    verdict = None
    if args.scenario:
        # Judge the run against the scenario's timeline bounds; the
        # verdict rides the report AND the exit status — a failed
        # assertion is a red build, not a log line.
        from . import scenarios
        verdict = scenarios.evaluate(args.scenario_def, result)
        report["scenario"] = verdict
        if not verdict["passed"]:
            failed = [k for k, c in verdict["checks"].items()
                      if not c["ok"]]
            print(f"scenario {args.scenario}: FAILED checks: "
                  f"{', '.join(failed)}", file=sys.stderr)
            off = scenarios.off_tuned(args.scenario_def, cfg)
            if off:
                # The bounds assert a liveness SHAPE, which depends on
                # population/schedule geometry — off the verified shape
                # a red verdict is a tuning signal, not proof of a bug.
                diffs = ", ".join(f"{k}={got} (tuned at {want})"
                                  for k, (got, want) in sorted(off.items()))
                print(f"scenario {args.scenario}: note: bounds were "
                      f"verified at a different shape — {diffs}; at this "
                      "shape the attack may legitimately show a weaker "
                      "dip or different recovery", file=sys.stderr)
    if args.verbose:
        _print_verbose(result)
    print(json.dumps(report))
    return 0 if verdict is None or verdict["passed"] else 3


if __name__ == "__main__":
    sys.exit(main())
