"""Flight-recorder device kernels (docs/OBSERVABILITY.md §"Flight recorder").

On-device protocol *latency* histograms: each engine reduces a handful
of per-round duration observations (election waits, slot time-to-commit,
rounds-to-learn, ...) into fixed power-of-two buckets INSIDE the scan
body, so the time structure of a 100k-round run survives without ever
shipping per-round data to the host. Like the telemetry counters the
observations are read off the round's own intermediates and never feed
back into state — enabling them is digest-neutral by construction
(tests/test_flight.py pins bit-identity per engine).

Bucket semantics (``N_BUCKETS`` = 16, shared by every engine and by the
``tools/validate_trace.py`` schema): bucket 0 holds observations <= 0,
bucket i (1 <= i <= 14) holds values in [2^(i-1), 2^i), and the last
bucket is the >= 2^14 overflow. All-integer compares — no float log2,
so bucket placement can never drift across backends.
"""
from __future__ import annotations

import jax.numpy as jnp

N_BUCKETS = 16
# Lower-inclusive bucket edges: (0, 1, 2, 4, ..., 2^14); the last bucket
# is open-ended. Exported so the host-side schema (validate_trace /
# obs/timeline) states the same integers the device compares against.
BUCKET_LO = (0,) + tuple(2 ** i for i in range(N_BUCKETS - 1))


def bucket_counts(values, mask):
    """Histogram of ``values`` where ``mask``, as ``i32[N_BUCKETS]``.

    ``values`` is any-shape i32 observations; ``mask`` broadcasts
    against it (False lanes contribute nothing). Computed as 15 masked
    threshold reductions + differencing — vectorized fused passes, never
    a one-hot ``[..., N_BUCKETS]`` materialization (at the pbft [N, S]
    shapes that intermediate would be ~100s of MB per round) and never
    a scatter-add (the serial scatter unit, docs/PERF.md).
    """
    v, m = jnp.broadcast_arrays(jnp.asarray(values, jnp.int32), mask)
    v = v.astype(jnp.int32)
    total = jnp.sum(m.astype(jnp.int32))
    ge = jnp.stack([jnp.sum((m & (v >= t)).astype(jnp.int32))
                    for t in BUCKET_LO[1:]])          # [N_BUCKETS-1]
    lo = jnp.concatenate([total[None], ge])
    hi = jnp.concatenate([ge, jnp.zeros((1,), jnp.int32)])
    return lo - hi
