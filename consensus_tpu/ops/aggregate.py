"""SPEC §9 in-network vote aggregation — the shared switch delivery layer.

PAPERS.md 1605.05619 moves consensus vote aggregation into programmable
network hardware; ``Config.net_model="switch"`` is that model as a
delivery layer between send and receive, shared by every vote/quorum
path (1905.10786's lesson: optimizations expressed at the right layer
port across protocols). K aggregator vertices partition the population
into contiguous segments (``agg_of(i) = i // ceil(N/K)``); a sender's
SPEC §2 edge draw lands on its aggregator (uplink), aggregators combine
per-segment — masked sums for counts, max/min for order-statistic
quantities — and receivers see K pre-aggregated values instead of N
messages (downlink).

Draw keying (all counter-based; scalar twin ``cpp/oracle.cpp AggNet``):

  * Aggregator ``a`` of phase ``ph`` is the synthetic vertex
    ``g = N + ph*K + a`` — outside the node id range, so switch-path
    draws can never collide with the flat §2 edge draws that still
    carry requests/proposals. The PARTITION side of an aggregator is
    keyed on the phase-independent vertex ``N + a`` (one physical
    switch, one side).
  * Uplink (edge engines): the §2 mixer draw ``(q, i, g)`` + §A.2
    delayed retransmission + the §2 bipartition at round ``q``
    (``side_q(i) == side_q(N + a)``). The §6b bcast engine's uplink is
    its per-sender broadcast key ``(q, i, i)`` instead — one atomic
    broadcast into the switch per round.
  * Downlink: ``(r, g, j)`` + delay + partition at the CURRENT round r.
  * Fault axes (STREAM_AGG, per (round, aggregator)): failure — a down
    aggregator silently drops its whole segment, both directions — and
    STALE state: the aggregator serves the segment it combined from
    round ``q = r - d``'s delivery pattern, ``d in [1, agg_max_stale]``
    drawn per (round, aggregator). Staleness is a pure re-draw against
    shifted round keys (contributions/values stay current-round — a
    "previous combined value" would be a queue riding the carry, which
    SPEC §A.2 already forbids); only the uplink shifts, the downlink
    stays at ``r``.

Self votes never travel: each receiver counts itself locally, and its
own switch-delivered copy (if the two-hop delivered it back) is
subtracted — the factorized two-hop keeps that exact per receiver.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import rng
from .adversary import cutoff as _lt
from .adversary import delayed_open, draw

I32_MAX = jnp.iinfo(jnp.int32).max
I32_MIN = jnp.iinfo(jnp.int32).min

# SPEC §9 telemetry tail shared by every switch-capable engine's counter
# vector (zeros when net_model="flat", like the §6c CRASH_TELEMETRY).
AGG_TELEMETRY = ("agg_down_rounds",   # Σ per-round failed aggregators
                 "stale_serves",      # Σ per-round stale-serving (alive) aggs
                 "poisoned_serves")   # Σ per-round forged combines (§9b)

# Phase table (documented in SPEC §9; phases are per-protocol, so ids
# may repeat across protocols — one run never mixes them):
#   raft / raft_sparse : 0 = election vote responses (P2c)
#   pbft (both models) : 0 = prepare votes (P4), 1 = commit votes (P5),
#                        2 = decide gossip (P6)
#   paxos              : 0 = promises, 1 = accept responses
#   hotstuff           : 0 = votes


def n_segments(N: int, K: int) -> int:
    """Segment width B = ceil(N/K); ids i // B land in [0, ceil(N/B))."""
    return -(-N // K)


def agg_ids(N: int, K: int):
    """Static node → aggregator partition: [N] i32, i // ceil(N/K)."""
    B = n_segments(N, K)
    return jnp.arange(N, dtype=jnp.int32) // jnp.int32(B)


class AggRound(NamedTuple):
    """Per-round aggregator fault state (pure draws; nothing rides the
    carry). ``alive`` is None when agg_fail_rate == 0 (static no-draw);
    ``q`` is the per-aggregator effective UPLINK round — the scalar
    round ``r`` itself when agg_stale_rate == 0."""
    alive: jnp.ndarray | None   # [K] bool or None
    q: jnp.ndarray              # [] or [K] uint32
    down_count: jnp.ndarray     # [] i32 (telemetry)
    stale_count: jnp.ndarray    # [] i32 (telemetry)


def agg_round(cfg, seed, r) -> AggRound:
    """Draw the round's STREAM_AGG fault state for all K aggregators."""
    K = cfg.n_aggregators
    ur = jnp.asarray(r, jnp.uint32)
    ua = jnp.arange(K, dtype=jnp.uint32)
    z = jnp.int32(0)
    if cfg.agg_fail_on:
        alive = ~(draw(seed, rng.STREAM_AGG, ur, 0, ua)
                  < _lt(cfg.agg_fail_cutoff))
        down_count = jnp.sum((~alive).astype(jnp.int32))
    else:
        alive, down_count = None, z
    if cfg.agg_stale_on:
        stale = draw(seed, rng.STREAM_AGG, ur, 1, ua) \
            < _lt(cfg.agg_stale_cutoff)
        d = jnp.uint32(1) + (draw(seed, rng.STREAM_AGG, ur, 2, ua)
                             % jnp.uint32(cfg.agg_max_stale))
        serving = stale & (ur >= d)   # round keys must not wrap (§A.2)
        q = jnp.where(serving, ur - d, ur)
        live_serving = serving if alive is None else (serving & alive)
        stale_count = jnp.sum(live_serving.astype(jnp.int32))
    else:
        q, stale_count = ur, z
    return AggRound(alive, q, down_count, stale_count)


def agg_counts(agg: AggRound | None = None, poisoned=None):
    """The :data:`AGG_TELEMETRY` tail of an engine's counter vector —
    call with no args for the flat-model zeros. ``poisoned`` is the
    engine's :func:`poison_count` accumulation across the round's
    phases (None when the §9b knob is off)."""
    if agg is None:
        return (jnp.int32(0),) * 3
    pz = jnp.int32(0) if poisoned is None else poisoned
    return (agg.down_count, agg.stale_count, pz)


# --- SPEC §9b poisoned combines --------------------------------------------

def agg_poison(cfg, seed, r, phase: int):
    """SPEC §9b: [K] mask of aggregators serving FORGED combines this
    (round, phase) — or None when the knob is off (static no-draw, so
    zero-rate configs compile the §9 program unchanged).

    The LAST ``agg_byz`` aggregator ids are byzantine (mirrors the
    node-side convention: byzantine ids are the tail of the range);
    each fires independently per (round, phase-qualified vertex) via
    STREAM_POISON c0 = 0 with c1 = ph*K + a — the same phase
    qualification as the vertex's edge draws, so the two pbft vote
    phases equivocate independently. Scalar twin: cpp/oracle.cpp
    ``AggNet::poisoned``."""
    if not cfg.agg_poison_on:
        return None
    K = cfg.n_aggregators
    ua = jnp.arange(K, dtype=jnp.uint32)
    byz_a = jnp.arange(K, dtype=jnp.int32) >= jnp.int32(K - cfg.agg_byz)
    fire = draw(seed, rng.STREAM_POISON, jnp.asarray(r, jnp.uint32), 0,
                jnp.uint32(phase * K) + ua) < _lt(cfg.agg_poison_cutoff)
    return byz_a & fire


def uplink_lies(cfg, seed, r, byz):
    """SPEC §9b byzantine-uplink lies: ``(lie, fval)`` — [N] mask of
    byzantine senders forging their uplink claim this round, and the
    [N] i32 forged value each serves — or ``(None, None)`` when the
    knob is off. STREAM_POISON c0 = 1 is the activation draw (per
    (round, node)); c0 = 2 is the forged value (bitcast to i32, the
    same 32-bit payload discipline as STREAM_VALUE blocks). ``byz`` is
    the engine's byzantine-SENDER mask (``real & ~honest`` in the
    padded f-ladder — padding never lies; both draws key on absolute
    node ids, so the ladder's lies are byte-equal to each rung's
    standalone run). The lie is one claim per node per round — every
    phase and slot sees the same forged (vote, value), which is what
    makes a single liar able to break a whole segment's
    value-uniformity (vote suppression) or, in an all-byzantine
    segment, serve a forged value outright."""
    if not cfg.uplink_lies_on:
        return None, None
    from .adversary import bitcast_i32
    N = byz.shape[0]
    ui = jnp.arange(N, dtype=jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    lie = byz & (draw(seed, rng.STREAM_POISON, ur, 1, ui)
                 < _lt(cfg.byz_uplink_cutoff))
    fval = bitcast_i32(draw(seed, rng.STREAM_POISON, ur, 2, ui))
    return lie, fval


def seg_widths(valid, seg_ids, K: int, traced: bool = False):
    """[K] i32 segment populations — the forged full-support count a
    poisoned aggregator serves (§9b claims its ENTIRE segment voted for
    the receiver's value). ``valid`` masks real node ids (all-ones for
    the static engines; the lane's live prefix in the padded f-ladder,
    so padding ids never inflate a forged claim)."""
    return seg_sum(valid.astype(jnp.int32), seg_ids, K, traced)


def poison_count(agg: AggRound, *masks):
    """Telemetry: Σ poisoned-serving aggregators across the round's
    phases (alive ones only — a failed aggregator serves nothing, so a
    dead-and-poisoned draw is not a serve). ``masks`` are the per-phase
    :func:`agg_poison` results; None entries (phase knob off) skip."""
    tot = jnp.int32(0)
    for m in masks:
        if m is None:
            continue
        live = m if agg.alive is None else (m & agg.alive)
        tot = tot + jnp.sum(live.astype(jnp.int32))
    return tot


def take_seg(table, seg_ids, K: int):
    """``table[seg_ids]`` for a [K, ...] table with STATIC tiny K: a
    K-deep fused select chain (no gather unit; works with traced
    ``seg_ids`` — the padded f-ladder's traced segmentation)."""
    tail = (1,) * (table.ndim - 1)
    sel = seg_ids.reshape(seg_ids.shape + tail)
    out = jnp.broadcast_to(table[0][None], seg_ids.shape + table.shape[1:])
    for k in range(1, K):
        out = jnp.where(sel == k, table[k][None], out)
    return out


def _seg_reduce(x, seg_ids, K: int, kind: str, identity, traced: bool):
    """Per-segment reduce of [N, ...] → [K, ...]. Static segmentation
    reshapes into [K, B, ...] (pure reduction, no scatter); the traced
    path (padded f-ladder: B depends on the traced n_real) goes through
    jax.ops.segment_*."""
    if traced:
        fn = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
              "min": jax.ops.segment_min}[kind]
        out = fn(x, seg_ids, num_segments=K)
        if kind != "sum":
            # segment_max/min fill EMPTY segments with dtype extrema of
            # the wrong sign; normalize to the caller's identity.
            counts = jax.ops.segment_sum(
                jnp.ones(x.shape[0], jnp.int32), seg_ids, num_segments=K)
            tail = (1,) * (x.ndim - 1)
            out = jnp.where((counts > 0).reshape((K,) + tail), out,
                            identity)
        return out
    N = x.shape[0]
    B = n_segments(N, K)
    pad = K * B - N
    if pad:
        fill = jnp.full((pad,) + x.shape[1:], identity, x.dtype)
        x = jnp.concatenate([x, fill], axis=0)
    x = x.reshape((K, B) + x.shape[1:])
    op = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[kind]
    return op(x, axis=1)


def seg_sum(x, seg_ids, K: int, traced: bool = False):
    return _seg_reduce(x, seg_ids, K, "sum", jnp.asarray(0, x.dtype),
                       traced)


def seg_max(x, seg_ids, K: int, identity, traced: bool = False):
    return _seg_reduce(x, seg_ids, K, "max", identity, traced)


def seg_min(x, seg_ids, K: int, identity, traced: bool = False):
    return _seg_reduce(x, seg_ids, K, "min", identity, traced)


# --- the two-hop delivery masks --------------------------------------------

def _open_edge(cfg, seed, q, src, dst):
    """§2 drop leg + §A.2 delayed retransmission on (q, src, dst)."""
    open_ = ~(rng.delivery_u32_jnp(seed, q, src, dst)
              < _lt(cfg.drop_cutoff))
    if cfg.max_delay_rounds > 0:
        open_ |= delayed_open(seed, q, src, dst, cfg.drop_cutoff,
                              cfg.max_delay_rounds)
    return open_


def _part_pair_ok(cfg, seed, q, id_a, id_b):
    """§2 bipartition check at round key(s) ``q`` for vertex ids
    ``id_a``/``id_b`` (nodes or N+a switch vertices; broadcasts)."""
    part_active = draw(seed, rng.STREAM_PARTITION, q, 0, 0) \
        < _lt(cfg.partition_cutoff)
    side_a = draw(seed, rng.STREAM_PARTITION, q, 1, id_a) & jnp.uint32(1)
    side_b = draw(seed, rng.STREAM_PARTITION, q, 1, id_b) & jnp.uint32(1)
    return (side_a == side_b) | ~part_active


def _uplink(cfg, seed, agg: AggRound, seg_ids, K: int, n_vert,
            dst_kind: str, phase: int, traced: bool):
    """Shared uplink body: [N] bool. ``dst_kind`` picks the edge-model
    synthetic vertex ("edge") or the §6b broadcast key ("bcast");
    ``n_vert`` is the vertex base N (traced n_real in the ladder)."""
    N = seg_ids.shape[0]
    ui = jnp.arange(N, dtype=jnp.uint32)
    base = jnp.asarray(n_vert, jnp.uint32)
    ua = seg_ids.astype(jnp.uint32)
    q = agg.q if agg.q.ndim == 0 else take_seg(agg.q, seg_ids, K)
    if dst_kind == "edge":
        dst = base + jnp.uint32(phase * K) + ua
    else:
        dst = ui
    open_ = _open_edge(cfg, seed, q, ui, dst)
    if not cfg.no_partition:
        open_ &= _part_pair_ok(cfg, seed, q, ui, base + ua)
    return open_


def uplink_edge(cfg, seed, agg: AggRound, phase: int, *, seg_ids=None,
                n_vert=None, traced: bool = False):
    """Edge-model uplink mask [N]: sender i's §2 draw to its aggregator
    vertex, at the aggregator's effective (possibly stale) round."""
    K = cfg.n_aggregators
    if seg_ids is None:
        seg_ids = agg_ids(cfg.n_nodes, K)
    if n_vert is None:
        n_vert = cfg.n_nodes
    return _uplink(cfg, seed, agg, seg_ids, K, n_vert, "edge", phase,
                   traced)


def uplink_bcast(cfg, seed, agg: AggRound, *, seg_ids=None, n_vert=None,
                 traced: bool = False):
    """§6b uplink mask [N]: the sender's one atomic broadcast draw
    (key (q, i, i)) lands on its aggregator — shared by every phase of
    the round, exactly the §6b fault granularity."""
    K = cfg.n_aggregators
    if seg_ids is None:
        seg_ids = agg_ids(cfg.n_nodes, K)
    if n_vert is None:
        n_vert = cfg.n_nodes
    return _uplink(cfg, seed, agg, seg_ids, K, n_vert, "bcast", 0, traced)


def downlink(cfg, seed, r, agg: AggRound, phase: int, dst, *, n_vert=None):
    """Downlink mask [K, len(dst)]: aggregator a → receiver id dst[j] at
    the CURRENT round r. Dead aggregators (fail draw) deliver nothing;
    negative dst ids (masked lanes) receive nothing."""
    K = cfg.n_aggregators
    if n_vert is None:
        n_vert = cfg.n_nodes
    base = jnp.asarray(n_vert, jnp.uint32)
    ua = jnp.arange(K, dtype=jnp.uint32)[:, None]
    valid = jnp.asarray(dst, jnp.int32) >= 0
    udst = jnp.clip(jnp.asarray(dst, jnp.int32), 0, None) \
        .astype(jnp.uint32)[None, :]
    ur = jnp.asarray(r, jnp.uint32)
    g = base + jnp.uint32(phase * K) + ua
    open_ = _open_edge(cfg, seed, ur, g, udst)
    if not cfg.no_partition:
        open_ &= _part_pair_ok(cfg, seed, ur, base + ua, udst)
    if agg.alive is not None:
        open_ &= agg.alive[:, None]
    return open_ & valid[None, :]


def downlink_self(cfg, seed, r, agg: AggRound, phase: int, *, seg_ids=None,
                  n_vert=None):
    """[N] mask: does node j's OWN aggregator deliver back to j this
    round/phase? The self-duplicate subtraction term — a receiver
    counts its own vote locally, so the switch-returned copy must be
    discounted. Elementwise draws (a(j) is a pure function of j)."""
    K = cfg.n_aggregators
    if seg_ids is None:
        seg_ids = agg_ids(cfg.n_nodes, K)
    if n_vert is None:
        n_vert = cfg.n_nodes
    N = seg_ids.shape[0]
    base = jnp.asarray(n_vert, jnp.uint32)
    ua = seg_ids.astype(jnp.uint32)
    uj = jnp.arange(N, dtype=jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    g = base + jnp.uint32(phase * K) + ua
    open_ = _open_edge(cfg, seed, ur, g, uj)
    if not cfg.no_partition:
        open_ &= _part_pair_ok(cfg, seed, ur, base + ua, uj)
    if agg.alive is not None:
        open_ &= take_seg(agg.alive, seg_ids, K)
    return open_


# --- pbft value-matched tallies --------------------------------------------

def value_votes(vals, contrib, up, down, down_own, seg_ids, K: int, *,
                eq_up=None, lie=None, lie_val=None, poison=None,
                widths=None, traced: bool = False):
    """SPEC §9 switch tally for value-matched votes (pbft P4/P5): each
    aggregator combines its segment's live contributions into
    ``(count, vmax, vmin)`` — it SERVES ``(count, value)`` iff the
    segment is value-UNIFORM (vmax == vmin; a mixed segment is the
    switch-vs-replica inconsistency a receiver can detect but not
    resolve, so it serves nothing). Receivers total the counts of
    delivered serving segments whose value matches their own.

    ``vals``/``contrib``: [N, S]; ``up``: [N] uplink mask (sender
    crash/withhold already folded by the caller); ``down``: [K, N]
    downlink; ``down_own``: [N] own-aggregator return mask; ``eq_up``:
    optional [N] value-blind equivocating-support senders (byz & stance
    & uplink) — their count rides any SERVING segment (the switch has
    no value to pin a byz claim to, so an all-byz segment serves
    nothing). Returns [N, S] i32 switch-delivered counts with the
    receiver's own returned copy subtracted — the caller adds the local
    self vote.

    SPEC §9b adversary axes (both compile away when off):

    ``lie``/``lie_val`` ([N] bool / [N] i32, :func:`uplink_lies`): a
    lying sender's forged (vote, value) claim joins the combine —
    its count rides the segment total and its value folds into the
    uniformity check, so a single liar in a segment with honest
    contributors breaks uniformity and suppresses the WHOLE segment,
    while an all-liar segment serves the forged value outright. A
    forged claim is not a local vote, so it is never self-subtracted.

    ``poison``/``widths`` ([K] bool / [K] i32, :func:`agg_poison` /
    :func:`seg_widths`): a poisoned (byzantine) aggregator overrides
    its serve entirely — it claims its FULL segment population voted
    for whatever value the receiver itself holds (the forged combine a
    receiver cannot cross-check without the raw votes, PAPERS.md
    1605.05619's trust gap). Failed aggregators stay silent (``down``
    already folds ``alive``). The receiver's own forged slot is
    discounted iff it contributes locally (the caller adds that self
    vote), keeping the total ≤ the segment population."""
    live = contrib & up[:, None]                                   # [N, S]
    cnt = seg_sum(live.astype(jnp.int32), seg_ids, K, traced)      # [K, S]
    vmax = seg_max(jnp.where(live, vals, I32_MIN), seg_ids, K,
                   I32_MIN, traced)
    vmin = seg_min(jnp.where(live, vals, I32_MAX), seg_ids, K,
                   I32_MAX, traced)
    if lie is not None:
        liar = lie & up                                            # [N]
        cnt = cnt + seg_sum(liar.astype(jnp.int32), seg_ids, K,
                            traced)[:, None]
        lmax = seg_max(jnp.where(liar, lie_val, I32_MIN), seg_ids, K,
                       I32_MIN, traced)                            # [K]
        lmin = seg_min(jnp.where(liar, lie_val, I32_MAX), seg_ids, K,
                       I32_MAX, traced)
        vmax = jnp.maximum(vmax, lmax[:, None])
        vmin = jnp.minimum(vmin, lmin[:, None])
    serve = (cnt > 0) & (vmax == vmin)                             # [K, S]
    total = cnt
    if eq_up is not None:
        eqc = seg_sum(eq_up.astype(jnp.int32), seg_ids, K, traced)  # [K]
        total = cnt + eqc[:, None]
    # Receiver combine as a static K-deep accumulation of [N, S]
    # fusions — a [K, N, S] broadcast would materialize K copies of
    # the population grid per phase (measured: +2.4 GB/round on the
    # pbft-100k-bcast-switch card); per-aggregator terms read only
    # [N]- and [S]-shaped operands against ``vals`` and fuse into one
    # elementwise chain.
    c = jnp.zeros(vals.shape, jnp.int32)
    for a in range(K):
        hit = (down[a][:, None] & serve[a][None, :]
               & (vmax[a][None, :] == vals))
        term = jnp.where(hit, total[a][None, :], 0)
        if poison is not None:
            term = jnp.where(poison[a] & down[a][:, None], widths[a],
                             term)
        c = c + term
    serve_own = take_seg(serve, seg_ids, K)                        # [N, S]
    val_own = take_seg(vmax, seg_ids, K)
    hit_own = serve_own & (val_own == vals) & down_own[:, None]
    sub = (live & hit_own).astype(jnp.int32)
    eq_sub = None
    if eq_up is not None:
        eq_sub = ((eq_up & down_own)[:, None] & serve_own
                  & (val_own == vals)).astype(jnp.int32)
    if poison is not None:
        pz_own = (take_seg(poison, seg_ids, K) & down_own)[:, None]
        sub = jnp.where(pz_own, contrib.astype(jnp.int32), sub)
        if eq_sub is not None:
            # The forged width already counts every segment id once;
            # an equivocating claim never rode the poisoned serve.
            eq_sub = jnp.where(pz_own, 0, eq_sub)
    c = c - sub
    if eq_sub is not None:
        c = c - eq_sub
    return c


def min_id_votes(dec, dval, up, down, seg_ids, K: int, N_pad: int, *,
                 traced: bool = False):
    """SPEC §9 switch form of the lowest-id decide gossip (pbft P6):
    each aggregator serves the MIN id of its live deciding senders plus
    that sender's value (max/min order-statistic combine); a receiver
    adopts from the lowest id across its delivered segments. Returns
    ``(imin, vadopt)``: [N, S] (imin == N_pad ⇒ no decider reached)."""
    idx = jnp.arange(dec.shape[0], dtype=jnp.int32)
    live = dec & up[:, None]
    src = jnp.where(live, idx[:, None], N_pad)
    mid = seg_min(src, seg_ids, K, jnp.int32(N_pad), traced)       # [K, S]
    mid_own = take_seg(mid, seg_ids, K)                            # [N, S]
    win = live & (idx[:, None] == mid_own)
    sval = seg_max(jnp.where(win, dval, I32_MIN), seg_ids, K,
                   I32_MIN, traced)                                # [K, S]
    # Static K-deep accumulation (see value_votes: a [K, N, S]
    # broadcast would materialize the grid K times).
    imin = jnp.full(dec.shape, N_pad, jnp.int32)
    for a in range(K):
        cand = jnp.where(down[a][:, None] & (mid[a][None, :] < N_pad),
                         mid[a][None, :], N_pad)
        imin = jnp.minimum(imin, cand)
    vadopt = jnp.full(dec.shape, I32_MIN, jnp.int32)
    for a in range(K):
        hit = (down[a][:, None] & (mid[a][None, :] == imin)
               & (imin < N_pad))
        vadopt = jnp.maximum(
            vadopt, jnp.where(hit, sval[a][None, :], I32_MIN))
    return imin, vadopt
