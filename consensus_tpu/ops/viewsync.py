"""SPEC §B per-node view-synchronizer ops shared by the BFT engines.

Since the per-node pacemaker PR, pbft, pbft_bcast, the padded f-ladder
and hotstuff all advance *per-node* (view, timer) pairs — views only
ever re-align through delivered messages (pbft's P1 view catch-up,
hotstuff's highest-view gossip), so every §2 fault axis naturally
desynchronizes them. This module holds the two pieces those engines
share:

  * the STREAM_DESYNC timer-skew adversary (:func:`desync_skew`) — the
    direct injection knob for the PAPERS.md 2601.00273 attack class:
    per (round, node), an up node's local timer jumps ahead by
    d ∈ [1, max_skew_rounds] with desync_rate, firing premature local
    timeouts. Keys are absolute node ids, so the padded f-ladder's
    draws are byte-identical to the dedicated engines' (the padding
    invisibility argument of engines/pbft_sweep.py). Mirrored
    scalar-for-scalar in cpp/oracle.cpp.
  * the desync telemetry tail (:data:`SYNC_TELEMETRY` /
    :func:`sync_counts`) — how far apart the honest live views actually
    drifted, and how much sync traffic got through to pull them back.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import rng
from .adversary import cutoff, draw

# SPEC §B desync telemetry tail shared by the BFT engines' counter
# vectors (after the SAFETY tail): per-round gauges/counts that stay
# meaningful under the flight recorder's window SUM — `view_spread_max`
# sums per-round spreads (like nodes_down), `desync_rounds` counts
# rounds with any disagreement, `sync_msgs_delivered` counts receivers
# whose view advanced via a delivered view-sync message.
SYNC_TELEMETRY = ("view_spread_max",      # Σ per-round max-min honest live view
                  "desync_rounds",        # rounds with view disagreement
                  "sync_msgs_delivered")  # receivers caught up via sync msgs


def desync_skew(seed, r, ids, desync_cut: int, max_skew: int):
    """SPEC §B: per-node timer skew for round r — 0 when the activation
    draw misses, else the depth draw d ∈ [1, max_skew]. ``ids`` are
    ABSOLUTE node ids (uint32), so padded-lane draws match the
    dedicated engines byte-for-byte. Callers add the result to the
    local timer BEFORE the round's timeout check and discard it for
    down nodes (the oracle's ``!is_down`` guard / the §6c freeze).
    Pure counter function — nothing rides the carry."""
    ur = jnp.asarray(r, jnp.uint32)
    ui = jnp.asarray(ids, jnp.uint32)
    fire = draw(seed, rng.STREAM_DESYNC, ur, 0, ui) < cutoff(desync_cut)
    depth = 1 + (draw(seed, rng.STREAM_DESYNC, ur, 1, ui)
                 % jnp.uint32(max_skew)).astype(jnp.int32)
    return jnp.where(fire, depth, 0)


def sync_counts(view=None, mask=None, delivered=None):
    """The :data:`SYNC_TELEMETRY` tail of an engine's counter vector —
    call with no args for the pacemaker-free engines' zeros. ``view``
    is the end-of-round per-node view, ``mask`` the honest-and-up
    population whose disagreement counts (an empty mask reads as
    spread 0), ``delivered`` the per-node caught-up-via-sync-message
    flags this round."""
    if view is None:
        return (jnp.int32(0),) * 3
    any_ = jnp.any(mask)
    vmax = jnp.max(jnp.where(mask, view, jnp.iinfo(jnp.int32).min))
    vmin = jnp.min(jnp.where(mask, view, jnp.iinfo(jnp.int32).max))
    spread = jnp.where(any_, vmax - vmin, 0).astype(jnp.int32)
    return (spread, (spread > 0).astype(jnp.int32),
            jnp.sum(delivered.astype(jnp.int32)))
