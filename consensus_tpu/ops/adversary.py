"""Shared device-side adversary + RNG draw kernels (docs/SPEC.md §§1-2).

The reference's `network::Simulator` decides message delivery, partitions
and leader churn online with a seeded RNG [B:5]; here those decisions are
pure counter-based threefry functions of (seed, round, edge), evaluated
on device as vectorized draws — no RNG state threads through the scan, so
any (round, sweep, edge) decision can be recomputed anywhere (including
scalar-by-scalar in the C++ oracle) without shared iteration order.

Used by every protocol engine; the DPoS engine uses a single-row variant
(only the scheduled producer sends, so materializing [V, V] for 100k
validators would be absurd — see engines/dpos.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import rng


def draw(seed, stream, ctx, c0, c1):
    """Device-side threefry draw — see core.rng.random_u32_jnp."""
    return rng.random_u32_jnp(seed, stream, ctx, c0, c1)


def cutoff(cut: int):
    """u32 probability cutoff as a jnp constant (draw < cutoff ⇔ fire)."""
    return jnp.uint32(cut)


def bitcast_i32(x):
    """Reinterpret u32 draws as i32 payload values (byte-stable across
    engines; the oracle stores the same 32 bits)."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def delivery(seed, N: int, r, drop_cut: int, part_cut: int):
    """SPEC §2: [i, j] True iff a message i→j is delivered in round r.

    Composition of per-edge drops, an optional per-round bipartition
    (nodes on different sides can't talk), and no self-delivery. The
    drop draw is the SPEC §2 murmur-style mixer (see core.rng delivery
    mixer notes); the absorb chain hoists itself through broadcasting —
    (seed, r) is a scalar, the i-absorb is [N, 1] — so only the
    j-absorb + finalizer touch all N^2 edges.
    """
    i = jnp.arange(N, dtype=jnp.uint32)[:, None]
    j = jnp.arange(N, dtype=jnp.uint32)[None, :]
    dropped = rng.delivery_u32_jnp(seed, r, i, j) < cutoff(drop_cut)
    part_active = draw(seed, rng.STREAM_PARTITION, r, 0, 0) < cutoff(part_cut)
    side = (draw(seed, rng.STREAM_PARTITION, r, 1, jnp.arange(N, dtype=jnp.uint32))
            & jnp.uint32(1))
    same_side = side[:, None] == side[None, :]
    off_diag = i != j
    return (~dropped) & (same_side | ~part_active) & off_diag


def churn(seed, r, churn_cut: int):
    """SPEC §2: True iff the per-round leader-churn event fires."""
    return draw(seed, rng.STREAM_CHURN, r, 0, 0) < cutoff(churn_cut)


def delivery_edges(seed, r, src, dst, drop_cut: int, part_cut: int):
    """SPEC §2 delivery evaluated on explicit (src, dst) edge id arrays.

    Broadcasts ``src`` against ``dst`` (e.g. src [A, 1] x dst [1, N]) and
    returns the delivery mask for exactly those edges. Draw keys are the
    absolute (round, src id, dst id) — identical to the full [N, N] mask's
    entries, so evaluating only live edges (the large-N engines' O(A*N)
    path, SURVEY.md §7 "never materialize full N^2") is byte-invisible.
    Negative ids are allowed (masked-out lanes) and return False.
    """
    valid = (src >= 0) & (dst >= 0)
    usrc = jnp.asarray(src, jnp.int32).astype(jnp.uint32)
    udst = jnp.asarray(dst, jnp.int32).astype(jnp.uint32)
    dropped = rng.delivery_u32_jnp(seed, r, usrc, udst) < cutoff(drop_cut)
    part_active = draw(seed, rng.STREAM_PARTITION, r, 0, 0) < cutoff(part_cut)
    side_s = draw(seed, rng.STREAM_PARTITION, r, 1, usrc) & jnp.uint32(1)
    side_d = draw(seed, rng.STREAM_PARTITION, r, 1, udst) & jnp.uint32(1)
    same_side = side_s == side_d
    off_diag = usrc != udst
    return valid & (~dropped) & (same_side | ~part_active) & off_diag
