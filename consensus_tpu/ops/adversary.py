"""Shared device-side adversary + RNG draw kernels (docs/SPEC.md §§1-2).

The reference's `network::Simulator` decides message delivery, partitions
and leader churn online with a seeded RNG [B:5]; here those decisions are
pure counter-based threefry functions of (seed, round, edge), evaluated
on device as vectorized draws — no RNG state threads through the scan, so
any (round, sweep, edge) decision can be recomputed anywhere (including
scalar-by-scalar in the C++ oracle) without shared iteration order.

Used by every protocol engine; the DPoS engine uses a single-row variant
(only the scheduled producer sends, so materializing [V, V] for 100k
validators would be absurd — see engines/dpos.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import rng


def draw(seed, stream, ctx, c0, c1):
    """Device-side threefry draw — see core.rng.random_u32_jnp."""
    return rng.random_u32_jnp(seed, stream, ctx, c0, c1)


def cutoff(cut: int):
    """u32 probability cutoff as a jnp constant (draw < cutoff ⇔ fire)."""
    return jnp.uint32(cut)


def bitcast_i32(x):
    """Reinterpret u32 draws as i32 payload values (byte-stable across
    engines; the oracle stores the same 32 bits)."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def delayed_open(seed, r, i, j, drop_cut: int, max_delay: int):
    """SPEC §A.2: does a flight dropped on edge i→j at some round
    q ∈ [r − max_delay, r) arrive at r via a successful retransmission?

    Pure function of (seed, r, edge) — no queue rides the carry. For
    each static delay d: the base delivery draw at q = r − d must have
    DROPPED (draw < drop_cut) and the delay-mixer re-draw must survive
    the same cutoff (the retransmitted copy is itself subject to drop).
    The ``r >= d`` guard keeps uint32 round keys from wrapping in the
    first ``max_delay`` rounds. Scalar twin: cpp/threefry.h
    ``delayed_open``."""
    ur = jnp.asarray(r, jnp.uint32)
    open_ = None
    for d in range(1, max_delay + 1):
        q = ur - jnp.uint32(d)
        hit = ((ur >= jnp.uint32(d))
               & (rng.delivery_u32_jnp(seed, q, i, j) < cutoff(drop_cut))
               & (rng.delay_u32_jnp(seed, q, jnp.uint32(d), i, j)
                  >= cutoff(drop_cut)))
        open_ = hit if open_ is None else (open_ | hit)
    return open_


def delivery(seed, N: int, r, drop_cut: int, part_cut: int,
             max_delay: int = 0):
    """SPEC §2: [i, j] True iff a message i→j is delivered in round r.

    Composition of per-edge drops, an optional per-round bipartition
    (nodes on different sides can't talk), and no self-delivery. The
    drop draw is the SPEC §2 murmur-style mixer (see core.rng delivery
    mixer notes); the absorb chain hoists itself through broadcasting —
    (seed, r) is a scalar, the i-absorb is [N, 1] — so only the
    j-absorb + finalizer touch all N^2 edges. ``max_delay > 0`` adds
    the SPEC §A.2 delayed-retransmission term to the drop leg
    (partitions are topology faults — never repaired by retransmission);
    0 compiles to the byte-identical §2 program.
    """
    i = jnp.arange(N, dtype=jnp.uint32)[:, None]
    j = jnp.arange(N, dtype=jnp.uint32)[None, :]
    open_drop = ~(rng.delivery_u32_jnp(seed, r, i, j) < cutoff(drop_cut))
    if max_delay > 0:
        open_drop |= delayed_open(seed, r, i, j, drop_cut, max_delay)
    part_active = draw(seed, rng.STREAM_PARTITION, r, 0, 0) < cutoff(part_cut)
    side = (draw(seed, rng.STREAM_PARTITION, r, 1, jnp.arange(N, dtype=jnp.uint32))
            & jnp.uint32(1))
    same_side = side[:, None] == side[None, :]
    off_diag = i != j
    return open_drop & (same_side | ~part_active) & off_diag


def churn(seed, r, churn_cut: int):
    """SPEC §2: True iff the per-round leader-churn event fires."""
    return draw(seed, rng.STREAM_CHURN, r, 0, 0) < cutoff(churn_cut)


# SPEC §6c telemetry tail shared by every engine's counter vector: the
# crash-recover adversary reports through the same round_telem path as
# the protocol counters (zeros when crash_prob = 0).
CRASH_TELEMETRY = ("crashes",      # nodes newly crashed this round
                   "recoveries",   # nodes rejoining this round
                   "nodes_down")   # Σ per-round down-node count


def crash_transition(seed, r, down, crash_cut: int, recover_cut: int,
                     max_crashed: int):
    """SPEC §6c: advance the per-node down mask for round r.

    Both draws are pure counter functions of (seed, round, node) —
    STREAM_CRASH with c0 = 0 (crash) / 1 (recover) — so any round's
    events can be recomputed anywhere; only the ``down`` mask itself is
    history (it rides each engine's carry, so the cap can bind).
    Order within the round: recoveries are decided on the start-of-round
    down set; crashes on the post-recovery up set (a node may recover
    and re-crash in one round — it re-enters with volatile state reset,
    then freezes again). ``max_crashed > 0`` caps the simultaneously-
    down count by admitting would-be crashers in ascending id order.

    Returns ``(down', recovered, crashed)`` — the end-of-round mask and
    this round's transition masks (telemetry + volatile-reset inputs).
    """
    N = down.shape[0]
    ui = jnp.arange(N, dtype=jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    rec = down & (draw(seed, rng.STREAM_CRASH, ur, 1, ui)
                  < cutoff(recover_cut))
    still_down = down & ~rec
    want = ~still_down & (draw(seed, rng.STREAM_CRASH, ur, 0, ui)
                          < cutoff(crash_cut))
    if max_crashed > 0:
        base = jnp.sum(still_down.astype(jnp.int32))
        rank = jnp.cumsum(want.astype(jnp.int32))
        want = want & (base + rank <= max_crashed)
    return still_down | want, rec, want


def freeze_down(down, frozen, new_leaves):
    """SPEC §6c freeze: leaf-wise ``where(down, frozen, new)``, with the
    per-node mask broadcast over each leaf's trailing axes — a down
    node's state holds its post-volatile-reset value no matter what the
    round computed. Shared by every engine so the idiom can't drift."""
    return tuple(
        jnp.where(down.reshape(down.shape + (1,) * (n.ndim - 1)), o, n)
        for o, n in zip(frozen, new_leaves))


def crash_counts(crashed=None, rec=None, down=None):
    """The :data:`CRASH_TELEMETRY` tail of an engine's counter vector:
    (crashes, recoveries, nodes_down) this round — call with no args
    for the adversary-off zeros."""
    if crashed is None:
        return (jnp.int32(0),) * 3
    return (jnp.sum(crashed.astype(jnp.int32)),
            jnp.sum(rec.astype(jnp.int32)),
            jnp.sum(down.astype(jnp.int32)))


# SPEC §7c vote-certificate safety-invariant tail shared by the BFT
# engines' counter vectors (pbft, pbft_bcast, the padded f-ladder,
# hotstuff): the agreement violations the per-receiver equivocation and
# poisoned-combine adversaries can actually cause, reduced on device
# from the round's own tallies. All three are zeros when the knobs are
# off — safety counters never fire under crash/drop/partition alone,
# which is exactly the invariant scenarios assert on.
SAFETY_TELEMETRY = ("forked_qc",          # conflicting quorums certified
                    "conflict_commits",   # node-slots committed in conflict
                    "safety_violations")  # per-round agreement-violation flag


def safety_counts(forked=None, conflicts=None):
    """The :data:`SAFETY_TELEMETRY` tail of an engine's counter vector —
    call with no args for the knobs-off zeros. ``forked``/``conflicts``
    are masks or counts; ``safety_violations`` is derived (0/1 per
    round) so the flag can never disagree with the conflict count."""
    if forked is None:
        return (jnp.int32(0),) * 3
    nf = jnp.sum(jnp.asarray(forked, jnp.int32))
    nc = jnp.sum(jnp.asarray(conflicts, jnp.int32))
    return (nf, nc, (nc > 0).astype(jnp.int32))


def delivery_edges(seed, r, src, dst, drop_cut: int, part_cut: int,
                   max_delay: int = 0):
    """SPEC §2 delivery evaluated on explicit (src, dst) edge id arrays.

    Broadcasts ``src`` against ``dst`` (e.g. src [A, 1] x dst [1, N]) and
    returns the delivery mask for exactly those edges. Draw keys are the
    absolute (round, src id, dst id) — identical to the full [N, N] mask's
    entries, so evaluating only live edges (the large-N engines' O(A*N)
    path, SURVEY.md §7 "never materialize full N^2") is byte-invisible.
    Negative ids are allowed (masked-out lanes) and return False.
    ``max_delay`` adds the SPEC §A.2 delayed-retransmission term exactly
    as :func:`delivery` does (same absolute keys — byte-invisible).
    """
    valid = (src >= 0) & (dst >= 0)
    usrc = jnp.asarray(src, jnp.int32).astype(jnp.uint32)
    udst = jnp.asarray(dst, jnp.int32).astype(jnp.uint32)
    open_drop = ~(rng.delivery_u32_jnp(seed, r, usrc, udst)
                  < cutoff(drop_cut))
    if max_delay > 0:
        open_drop |= delayed_open(seed, r, usrc, udst, drop_cut, max_delay)
    part_active = draw(seed, rng.STREAM_PARTITION, r, 0, 0) < cutoff(part_cut)
    side_s = draw(seed, rng.STREAM_PARTITION, r, 1, usrc) & jnp.uint32(1)
    side_d = draw(seed, rng.STREAM_PARTITION, r, 1, udst) & jnp.uint32(1)
    same_side = side_s == side_d
    off_diag = usrc != udst
    return valid & open_drop & (same_side | ~part_active) & off_diag


def slot_missed(seed, r, p, miss_cut: int):
    """SPEC §A.1: does round r's scheduled producer ``p`` miss its slot?
    One threefry draw per (round, producer) — the per-producer keying is
    the point: failures correlate with the schedule, so an unlucky
    producer vanishes from the distinct-producer suffix and LIB stalls."""
    return draw(seed, rng.STREAM_SLOTMISS, jnp.asarray(r, jnp.uint32), 0,
                jnp.asarray(p, jnp.int32).astype(jnp.uint32)) \
        < cutoff(miss_cut)


def attack_fires(seed, r, attack_cut: int):
    """SPEC §A.3: the per-round targeted-attack activation draw."""
    return draw(seed, rng.STREAM_ATTACK, jnp.asarray(r, jnp.uint32), 0, 0) \
        < cutoff(attack_cut)
