"""Multi-decree Paxos as a JAX array kernel (docs/SPEC.md §5).

The reference's `paxos::acceptor` promise/accept hot loop [B:5] becomes
elementwise max/where updates over a `[acceptor, slot]` ballot grid
(SURVEY.md §2 component 7), with per-round proposer contention resolved by
segment-max scatters — each proposer touches one slot per round, so the
kernel is O(N·P) per round, never O(N·S·P).

The synchronous-round collapse of the two phases is safe: a proposer only
sends Accepts after a majority of Promises, and within a round the accept
set of a lower ballot is disjoint from the prepare-reach of any higher
ballot on the same slot (same per-edge delivery decision for both flights),
so two values can never both reach accept-majority — the classic Paxos
argument carries over; see SPEC §5.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import (CRASH_TELEMETRY, crash_counts,
                             crash_transition, freeze_down)
from ..ops.aggregate import AGG_TELEMETRY, agg_counts
from .raft import _delivery, _draw, _i32, _lt  # shared SPEC §2 adversary


class PaxosState(NamedTuple):
    seed: jnp.ndarray          # [] uint32
    promised: jnp.ndarray      # [N, S] i32 (0 = none)
    acc_bal: jnp.ndarray       # [N, S] i32
    acc_val: jnp.ndarray       # [N, S] i32
    learned_val: jnp.ndarray   # [N, S] i32
    learned_mask: jnp.ndarray  # [N, S] bool
    down: jnp.ndarray          # [N] bool — SPEC §6c crashed mask


# SPEC §6c persistent/volatile carry split (tools/lint check `registry`):
# promised[] is volatile — safe because ballots r·N+p+1 strictly
# increase across rounds, so no later prepare can be outbid by a
# forgotten promise (SPEC §6c); acc_bal/acc_val (the accepted-value
# history Paxos safety rests on) and the learner state persist.
# Compiled-program contract (tools/hlocheck): sort-free AND scan-free
# (quorum counts and slot brackets are plain reductions over the [N, S]
# grid — reduction cascades file under the reduce class, tools/hlocheck/
# hlo.py `_scan_window`). No node-sharded claim (digest-tested only,
# like dense raft).
PROGRAM_CONTRACT = dict(sort_budget=0, cumsum_budget=0, node_sharded=None)

CRASH_SPLIT = {
    "seed": "meta",
    "promised": "volatile",
    "acc_bal": "persistent",
    "acc_val": "persistent",
    "learned_val": "persistent",
    "learned_mask": "persistent",
    "down": "meta",
}


def paxos_init(cfg: Config, seed) -> PaxosState:
    N, S = cfg.n_nodes, cfg.log_capacity
    z = jnp.zeros((N, S), jnp.int32)
    return PaxosState(jnp.asarray(seed, jnp.uint32), z, z, z, z,
                      jnp.zeros((N, S), bool), jnp.zeros(N, bool))


# On-device protocol telemetry (docs/OBSERVABILITY.md). "nacks" counts
# prepares that were delivered AND whose response would have been
# delivered, but whose ballot lost to an already-promised higher one —
# the synchronous-round analog of an explicit reject message.
PAXOS_TELEMETRY = ("promises",           # promise responses delivered
                   "nacks",              # delivered prepares outbid
                   "accepts",            # accepted responses delivered
                   "proposals_decided",  # proposers reaching majority
                   "values_learned",     # (node, slot) newly learned
                   ) + CRASH_TELEMETRY \
                   + AGG_TELEMETRY       # SPEC §9 (zeros when flat)

# Flight-recorder latency histogram (docs/OBSERVABILITY.md §"Flight
# recorder"): rounds_to_learn — at each newly learned (node, slot),
# the observation r + 1: every slot is contendable from round 0
# (proposers pick slots uniformly per round), so r + 1 is exactly the
# ballot rounds elapsed before this learner held the slot's value.
PAXOS_LATENCY = ("rounds_to_learn",)


def paxos_round(cfg: Config, st: PaxosState, r, *, telem: bool = False,
                flight: bool = False):
    N, S = cfg.n_nodes, cfg.log_capacity
    P = cfg.n_proposers or N
    majority = N // 2 + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    eye = jnp.eye(N, dtype=bool)

    deliver = _delivery(seed, N, ur, cfg.drop_cutoff, cfg.partition_cutoff,
                        cfg.max_delay_rounds)
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)

    # SPEC §6c crash-recover adversary. Volatile on recovery: promised[]
    # (safe here because ballots r·N+p+1 strictly increase across rounds,
    # so no later prepare can be outbid by a forgotten promise — see SPEC
    # §6c); durable: acc_bal/acc_val (the accepted-value history Paxos
    # safety rests on) and the learner state.
    crash_on = cfg.crash_on
    down = st.down
    promised0 = st.promised
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        deliver = deliver & up[:, None] & up[None, :]
        promised0 = jnp.where(rec[:, None], 0, promised0)
        frozen = (promised0, st.acc_bal, st.acc_val, st.learned_val,
                  st.learned_mask)

    is_prop = (idx < P) & ~churn
    slot_p = (_draw(seed, rng.STREAM_VALUE, ur, 1, idx.astype(jnp.uint32))
              % jnp.uint32(S)).astype(jnp.int32)
    ballot = r * N + idx + 1
    v_own = _i32(_draw(seed, rng.STREAM_VALUE, ur, 0, idx.astype(jnp.uint32)))

    prep_del = deliver.T        # [a, p]: prepare/accept p→a delivered
    resp_del = deliver          # [a, p]: response a→p delivered

    # Row-wise per-slot segment reductions. seg_max clamps at 0 (ballots
    # are positive; empty slots read 0); the raw variants keep the
    # iinfo fill for arbitrary-valued payloads, masked by the caller.
    seg_max0 = jax.vmap(
        lambda d: jax.ops.segment_max(d, slot_p, num_segments=S))
    seg_max = lambda d: jnp.maximum(seg_max0(d), 0)

    # Phase 1: prepares → per-slot max delivered ballot at each acceptor.
    data1 = jnp.where(is_prop[None, :] & prep_del, ballot[None, :], 0)  # [A, P]
    p_max = seg_max(data1)                                              # [A, S]
    new_promised = jnp.maximum(promised0, p_max)

    # Phase 2: promises (only the highest delivered ballot per slot wins).
    # Gather columns by slot_p directly — st.promised[:, slot_p] lowers to
    # one XLA gather; the earlier take_along_axis(slot_p.repeat(N, 0))
    # form materialized three [N, P] i32 index matrices (~400 MB each at
    # the BASELINE.json:10 10k x 10k shape) before gathering.
    po = promised0[:, slot_p]                                           # [A, P]
    npo = new_promised[:, slot_p]
    switch = cfg.switch_on
    if switch:
        # SPEC §9: the promise responses route through the K
        # aggregators (phase 0) — proposers see K pre-aggregated
        # segment counts instead of A per-acceptor responses; the
        # promise-carried accepted value is the switch's max/min
        # order-statistic combine (max ballot, lowest-id tie-break —
        # identical to the flat argmax), read off the two-hop mask.
        from ..ops.aggregate import (agg_ids, agg_round, downlink,
                                     seg_sum, take_seg, uplink_edge)
        K_agg = cfg.n_aggregators
        aggst = agg_round(cfg, seed, ur)
        sids = agg_ids(N, K_agg)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            up0 &= up
        prom_c = (is_prop[None, :] & prep_del
                  & (ballot[None, :] > po) & (ballot[None, :] == npo)
                  & up0[:, None])                                       # [A, P]
        down0 = downlink(cfg, seed, ur, aggst, 0, idx)                  # [K, P]
        seg_prom = seg_sum(prom_c.astype(jnp.int32), sids, K_agg)       # [K, P]
        n_prom = jnp.sum(jnp.where(down0, seg_prom, 0), axis=0)
        prom = prom_c & take_seg(down0, sids, K_agg)    # delivered [A, P]
    else:
        prom = (is_prop[None, :] & prep_del & resp_del
                & (ballot[None, :] > po) & (ballot[None, :] == npo))    # [A, P]
        n_prom = jnp.sum(prom, axis=0, dtype=jnp.int32)
    rep_bal = jnp.where(prom, st.acc_bal[:, slot_p], 0)
    best_a = jnp.argmax(rep_bal, axis=0).astype(jnp.int32)  # first max ⇒ lowest id
    best_bal = jnp.max(rep_bal, axis=0)
    rep_val = st.acc_val[best_a, slot_p]                                # [P]

    # Phase 3: proposer gate + value choice.
    proceed = is_prop & (n_prom >= majority)
    v_chosen = jnp.where(best_bal > 0, rep_val, v_own)

    # Phase 4: accepts. The winning value is NOT gathered as
    # v_chosen[a_max - (r·N+1)] — a [A, S] arbitrary-index gather from a
    # [P] vector costs ~780 ms/round at 10k×10k on v5 lite (97% of the
    # round, measured 2026-07-30). Ballots are distinct across p, so
    # exactly one proposer per (acceptor, slot) matches the slot's max
    # ballot: select it with an equality mask and reduce — same result,
    # rides the fast segment path.
    I32_MIN = jnp.iinfo(jnp.int32).min
    acc_cond = proceed[None, :] & prep_del & (ballot[None, :] >= npo)   # [A, P]
    a_max = seg_max(jnp.where(acc_cond, ballot[None, :], 0))            # [A, S]
    amax_at = a_max[:, slot_p]                                          # [A, P]
    win = acc_cond & (ballot[None, :] == amax_at)   # ≤1 true per (a, slot)
    val_w = seg_max0(jnp.where(win, v_chosen[None, :], I32_MIN))        # [A, S]
    has_acc = a_max > 0
    acc_bal2 = jnp.where(has_acc, a_max, st.acc_bal)
    acc_val2 = jnp.where(has_acc, val_w, st.acc_val)
    promised2 = jnp.where(has_acc, a_max, new_promised)

    # Phase 5: accepted responses → decide. Switch: phase-1 two-hop,
    # segment-summed per proposer (SPEC §9).
    if switch:
        up1 = uplink_edge(cfg, seed, aggst, 1)
        if crash_on:
            up1 &= up
        acc_c = win & up1[:, None]
        down1 = downlink(cfg, seed, ur, aggst, 1, idx)                  # [K, P]
        seg_acc = seg_sum(acc_c.astype(jnp.int32), sids, K_agg)
        n_acc = jnp.sum(jnp.where(down1, seg_acc, 0), axis=0)
        accd = acc_c & take_seg(down1, sids, K_agg)  # telemetry mask
    else:
        accd = win & resp_del
        n_acc = jnp.sum(accd, axis=0, dtype=jnp.int32)
    decided = proceed & (n_acc >= majority)

    # Phase 6: decide broadcast; learn from lowest-id decider, first
    # wins. Built directly in [n, p] orientation (prep_del[n, p] IS
    # p→n delivery) — the [p, n] formulation transposed a [N, N]
    # matrix per round — and the learned value uses the same
    # equality-match reduction as phase 4 (the min-id decider is
    # unique per (receiver, slot)) instead of a v_chosen[pmin] gather.
    reach_np = decided[None, :] & (prep_del | eye)                      # [n, p]
    seg_min = jax.vmap(lambda d: jnp.minimum(
        jax.ops.segment_min(d, slot_p, num_segments=S), N))
    pmin = seg_min(jnp.where(reach_np, idx[None, :], N))                # [n, S]
    pmin_at = pmin[:, slot_p]                                           # [n, P]
    winp = reach_np & (idx[None, :] == pmin_at)
    lv_in = seg_max0(jnp.where(winp, v_chosen[None, :], I32_MIN))       # [n, S]
    found = pmin < N
    learn_now = found & ~st.learned_mask
    learned_val = jnp.where(learn_now, lv_in, st.learned_val)
    learned_mask = st.learned_mask | found

    if crash_on:
        # SPEC §6c freeze: a down node's acceptor + learner state holds
        # its post-reset value (delivery masking already kept its
        # flights out of every tally).
        (promised2, acc_bal2, acc_val2, learned_val, learned_mask) = \
            freeze_down(down, frozen, (promised2, acc_bal2, acc_val2,
                                       learned_val, learned_mask))

    new = PaxosState(seed, promised2, acc_bal2, acc_val2, learned_val,
                     learned_mask, down)
    if not telem:
        return new
    cnt = lambda m: jnp.sum(m.astype(jnp.int32))  # noqa: E731
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    az = agg_counts(aggst) if switch else agg_counts()
    nack = is_prop[None, :] & prep_del & resp_del & ~prom
    vec = jnp.stack([cnt(prom), cnt(nack), cnt(accd), cnt(decided),
                     cnt(learn_now), *cz, *az])
    if not flight:
        return new, vec
    from ..ops.flight import bucket_counts
    lat = jnp.stack([bucket_counts(jnp.asarray(r, jnp.int32) + 1,
                                   learn_now)])
    return new, vec, lat


def paxos_round_telem(cfg: Config, st: PaxosState, r):
    return paxos_round(cfg, st, r, telem=True)


def paxos_round_flight(cfg: Config, st: PaxosState, r):
    return paxos_round(cfg, st, r, telem=True, flight=True)


def _paxos_extract(st: PaxosState) -> dict:
    return {"learned_mask": st.learned_mask, "learned_val": st.learned_val,
            "promised": st.promised, "acc_bal": st.acc_bal,
            "acc_val": st.acc_val}


def _paxos_pspec(cfg: Config) -> PaxosState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    m = P(ND, None)
    return PaxosState(seed=P(), promised=m, acc_bal=m, acc_val=m,
                      learned_val=m, learned_mask=m, down=P(ND))


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("paxos", paxos_init, paxos_round, _paxos_extract,
                            _paxos_pspec, telemetry_names=PAXOS_TELEMETRY,
                            round_telem=paxos_round_telem,
                            latency_names=PAXOS_LATENCY,
                            round_flight=paxos_round_flight)
    return _ENGINE


def paxos_run(cfg: Config, **kw):
    from ..network import runner
    return runner.run(cfg, get_engine(), **kw)
