"""Chained HotStuff as a JAX array kernel (docs/SPEC.md §7b) — the
linear-communication BFT engine.

Classic PBFT's scalability wall is the O(N²) all-to-all vote exchange
(PAPERS.md 2007.12637): even after the PR 8 sort diet, the §6b bcast
round's bytes are sort passes over [S, N] temporaries — 9.67M steps/s
at 100k nodes, 0.6% of the bandwidth floor (docs/PERF.md). The
HotStuff lineage replaces the quadratic exchange with O(N)
vote→leader→broadcast phases: every phase is a threshold *count* at
the round leader. This engine is the array form of that move:

  * **Star-shaped delivery.** One leader per view: the proposal is a
    leader→node broadcast row (the dpos producer-row idiom — O(N)
    per-receiver draws on absolute SPEC §2 edge keys) and the votes are
    node→leader rows (O(N) per-sender draws). No [N, N] matrix, no
    per-receiver multiset, ever.
  * **Threshold counts, not tallies.** A quorum certificate (QC) forms
    iff the delivered-vote count reaches Q = 2f+1 — ONE masked sum
    reduction. Zero `lax.sort`, zero cumsum: the engine lands behind a
    dpos-class ``PROGRAM_CONTRACT`` of sort_budget 0 / cumsum_budget 0.
  * **Chained three-phase pipeline.** The QC chain registers (b1, b2,
    b3) riding the carry ARE the prepare / pre-commit / commit phases
    of three consecutive blocks: a new QC shifts the chain, and a
    block commits when the three newest QCs sit in consecutive views
    (the chained-HotStuff 3-chain rule). Fault-free steady state:
    every round forms a QC, so every round commits one block while the
    two newer blocks advance a phase — one block per round through a
    three-deep pipeline.
  * **Per-node view synchronizer (SPEC §B).** Since the view-desync
    PR there is NO global pacemaker: every node keeps its own
    (view, timer) pair, advanced by locally-observed QCs and LOCAL
    timeouts, and views only ever re-align through delivered messages
    — a highest-view gossip flight (P1) and the proposal/QC-notify
    broadcast (P2/P6), all riding the same §2 delivery layer. Leaders
    rotate round-robin per RECEIVER: node i expects leader
    view[i] mod N, the round's effective proposer is the
    highest-view node whose own view elects it, and a receiver
    ignores proposals from views below its own. So drop, delay
    (§A.2), partition, crash (§6c), switch faults (§9) and byzantine
    senders naturally DESYNCHRONIZE views — the PAPERS.md 2601.00273
    attack class — and the STREAM_DESYNC timer-skew axis
    (ops/viewsync.desync_skew) injects it directly. A failed view
    breaks the consecutive-view chain, so its cost is visible as
    chain-commit lag, exactly the liveness shape the literature's
    leader-rotation attacks target.

State split: the QC-chain registers and the certified-view map are
GLOBAL per sweep (the certified chain is the network's shared state;
forks are unreachable in this model because a QC certifies one block
per height and the next proposal extends the newest QC). The per-NODE
state is what each replica has locally observed: its own pacemaker
(view, timer) and its durable committed prefix — O(N) carry leaves,
no [N, S] tensor anywhere. At zero fault rates every node's view
advances in lockstep, and the trajectory is bit-identical to the
retired global pacemaker (kept as the reference twin,
tests/reference_hotstuff.py — the PR 8 playbook).

Scalar twin: ``cpp/oracle.cpp`` ``HotstuffSim`` (the PR 5
aggregate-round pattern), byte-differential on decided logs across the
full adversary surface (drop / partition / churn / §6c crash-recover /
§A.2 delay / §B desync) — tests/test_hotstuff.py.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..core import rng
from ..core.config import Config
from ..ops.adversary import (CRASH_TELEMETRY, SAFETY_TELEMETRY, bitcast_i32,
                             crash_counts, crash_transition, delayed_open,
                             freeze_down, safety_counts)
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import draw as _draw
from ..ops.aggregate import (AGG_TELEMETRY, agg_counts, agg_ids, agg_poison,
                             agg_round, downlink, poison_count, seg_sum,
                             seg_widths, take_seg, uplink_edge, uplink_lies)
from ..ops.flight import bucket_counts
from ..ops.viewsync import SYNC_TELEMETRY, desync_skew, sync_counts

# SPEC §7c fork-certificate table depth: at most this many FORKED QCs
# (two conflicting quorums in one view) are value-tracked per run; later
# forks still count in telemetry but their deceived sets are not
# materialized in decided logs. Static so the carry stays O(N + S + F);
# mirrored as a compile-time constant in cpp/oracle.cpp.
FORK_TABLE = 8


class HotstuffState(NamedTuple):
    seed: jnp.ndarray       # [] uint32
    b1_v: jnp.ndarray       # [] i32 — newest QC: view (-1 = none)
    b1_h: jnp.ndarray       # [] i32 — newest QC: height (-1 = none)
    b2_v: jnp.ndarray       # [] i32 — parent QC (the locked block)
    b2_h: jnp.ndarray       # [] i32
    b3_v: jnp.ndarray       # [] i32 — grandparent QC
    b3_h: jnp.ndarray       # [] i32
    gcommit: jnp.ndarray    # [] i32 — globally committed chain length
    chain_v: jnp.ndarray    # [S] i32 — view that certified height s (-1)
    chain_vid: jnp.ndarray  # [S] i32 — §7c value-id certified at height s
    fvec: jnp.ndarray       # [N] i32 — bit k: node deceived at fork entry k
    ftab_v: jnp.ndarray     # [FORK_TABLE] i32 — fork entry: certifying view
    ftab_h: jnp.ndarray     # [FORK_TABLE] i32 — fork entry: height
    fnum: jnp.ndarray       # [] i32 — fork entries recorded (<= FORK_TABLE)
    view: jnp.ndarray       # [N] i32 — node i's OWN pacemaker view (§B)
    timer: jnp.ndarray      # [N] i32 — rounds since node i saw progress
    clen: jnp.ndarray       # [N] i32 — committed length node i learned
    down: jnp.ndarray       # [N] bool — SPEC §6c crashed mask


# Compiled-program contract (tools/hlocheck): the linear-BFT claim,
# machine-pinned — every phase is a count, so the ROUND program carries
# ZERO sort-class and ZERO cumsum-class ops (dpos-class budgets; the
# §6c max_crashed cap's admission cumsum is outside every registered
# config, exactly as for dpos). node_sharded="bounded": the per-node
# leaves are [N] vectors, the vote count is one psum, and the leader-
# row gathers move O(N) metadata — never an [N, S] carry leaf (none
# exists).
PROGRAM_CONTRACT = dict(sort_budget=0, cumsum_budget=0,
                        node_sharded="bounded")

# SPEC §6c persistent/volatile carry split (tools/lint check
# `registry`): a replica's committed prefix (`clen`) is the durable
# state HotStuff's safety argument rests on; its own pacemaker
# (`view`, `timer`) is volatile — a recovering node rejoins at view 0
# and resyncs from the next delivered gossip/proposal (§B). The QC
# chain / certified-view map are the NETWORK's abstract state (like
# the dpos producer schedule), not any node's — "meta", untouched by
# crashes.
CRASH_SPLIT = {
    "seed": "meta",
    "b1_v": "meta",
    "b1_h": "meta",
    "b2_v": "meta",
    "b2_h": "meta",
    "b3_v": "meta",
    "b3_h": "meta",
    "gcommit": "meta",
    "chain_v": "meta",
    # §7c certificate twin: the fork table is network-abstract history
    # (like chain_v), and fvec — though per-node — only records facts
    # about DELIVERED proposals (deceived requires pdel, which already
    # excludes down nodes), so none of it moves while a node is crashed.
    "chain_vid": "meta",
    "fvec": "meta",
    "ftab_v": "meta",
    "ftab_h": "meta",
    "fnum": "meta",
    "view": "volatile",
    "timer": "volatile",
    "clen": "persistent",
    "down": "meta",
}

# On-device protocol telemetry (docs/OBSERVABILITY.md). view_changes
# counts PER-NODE timeout-driven view advances since the §B per-node
# pacemaker (a synchronized population times out N-at-a-time).
HOTSTUFF_TELEMETRY = ("qc_formed",            # rounds forming a QC (0/1)
                      "blocks_committed",     # global commit advance
                      "commits_learned",      # Σ per-node clen advance
                      "view_changes",         # Σ per-node timeout advances
                      "proposals_delivered",  # Σ receivers of the round
                      "votes_counted",        # votes the leader counted
                      ) + CRASH_TELEMETRY \
                      + AGG_TELEMETRY \
                      + SAFETY_TELEMETRY \
                      + SYNC_TELEMETRY        # SPEC §7c/§9/§B (zeros
                      #                         unless the axes are on /
                      #                         views actually drift)

# Flight-recorder latency histograms (docs/OBSERVABILITY.md §"Flight
# recorder"):
#   view_change_wait_rounds — at each node's view advance (QC learned
#     or local timeout), the rounds ITS view took (timer + 1): 1 in
#     the fault-free steady state, view_timeout under a dead leader.
#   chain_commit_lag_rounds — per round, the pipeline depth
#     head_height - gcommit: the chained prepare/pre-commit stages not
#     yet committed (2-3 steady state; grows when failed views break
#     the consecutive-view chain — the chained-commit-stall signal).
HOTSTUFF_LATENCY = ("view_change_wait_rounds", "chain_commit_lag_rounds")


def _block_val(seed, chain_v, slots, sub=5):
    """Block value at (certifying view, height) — SPEC §7b:
    bitcast_i32(draw(STREAM_VALUE, view, 5, height)); pure counter
    function, so decided values need no [N, S] state anywhere (the
    oracle recomputes the identical u32). Broadcasts over inputs.
    SPEC §7c: an equivocating leader's SECOND block variant for the
    same (view, height) is the sibling subdraw 6 — `sub` selects."""
    return bitcast_i32(_draw(seed, rng.STREAM_VALUE,
                             jnp.asarray(chain_v).astype(jnp.uint32), sub,
                             jnp.asarray(slots).astype(jnp.uint32)))


def hotstuff_round(cfg: Config, st: HotstuffState, r, *,
                   telem: bool = False, flight: bool = False):
    N, S = cfg.n_nodes, cfg.log_capacity
    Q = 2 * cfg.f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)

    # ---- SPEC §6c crash-recover prologue: advance the down mask,
    # volatile reset on recovery (view/timer rejoin at 0; the committed
    # prefix persists — the §7b durable state).
    crash_on = cfg.crash_on
    down = st.down
    view, timer, clen = st.view, st.timer, st.clen
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, clen)
    # SPEC §B timer-skew injection: an affected node's local timer
    # jumps ahead, and when the skewed timer crosses view_timeout the
    # node times out RIGHT HERE — abandoning its view before this
    # round's proposal even arrives (the 2601.00273 premature-timeout
    # attack; P7's end-of-round check can't express that, since any
    # delivered proposal would reset the timer first). Applied AFTER
    # the frozen capture so the end-of-round freeze discards a down
    # node's skew — the oracle's `!is_down(i)` guard.
    if cfg.desync_on:
        timer = timer + desync_skew(seed, ur, uidx, cfg.desync_cutoff,
                                    cfg.max_skew_rounds)
        pre_to = timer >= cfg.view_timeout
        view = view + pre_to.astype(jnp.int32)
        timer = jnp.where(pre_to, 0, timer)

    # ---- P0 churn: the round's leader is offline (SPEC §2 "all
    # leaders step down" — in a one-leader-per-view protocol, every
    # would-be proposer skips its slot, forcing the timeout path).
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)

    honest = idx < (N - cfg.n_byzantine)   # SPEC §3c-style silent byz
    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                   < _lt(cfg.partition_cutoff))
    side = _draw(seed, rng.STREAM_PARTITION, ur, 1, uidx) & jnp.uint32(1)

    def _bcast_open(src_u32):
        """§2 openness of the src→j broadcast row on absolute edge
        keys (+ §A.2 retransmission). Delivery is per (round, edge):
        two flights sharing an edge in one round share its fate, so
        the gossip and proposal rows from one sender draw the SAME
        words — the model's link-state semantics, not a collision."""
        o = ~(rng.delivery_u32_jnp(seed, ur, src_u32, uidx)
              < _lt(cfg.drop_cutoff))
        if cfg.max_delay_rounds > 0:
            o |= delayed_open(seed, ur, src_u32, uidx, cfg.drop_cutoff,
                              cfg.max_delay_rounds)
        side_s = _draw(seed, rng.STREAM_PARTITION, ur, 1, src_u32) \
            & jnp.uint32(1)
        return o & ((side == side_s) | ~part_active)

    # ---- P1 highest-view gossip (SPEC §B view-sync message): the
    # highest-view honest live node broadcasts its view (lowest id on
    # ties — deterministic, mirrored); receivers behind it catch up.
    # This is the synchronizer's re-alignment channel — ONE O(N)
    # broadcast row through the §2 delivery layer, so drops/partitions/
    # crashes bound how fast desynced views can heal. Fault-free it is
    # a compiled-identical no-op on the trajectory (no view is ever
    # behind), preserving the global-pacemaker bit-identity.
    alive_h = honest & ~down if crash_on else honest
    vM = jnp.max(jnp.where(alive_h, view, -1))
    M = jnp.min(jnp.where(alive_h & (view == vM), idx, N))
    uM = jnp.clip(M, 0, N - 1).astype(jnp.uint32)
    gdel = ((vM >= 0) & (idx != M) & _bcast_open(uM))
    if crash_on:
        gdel &= ~down
    adv_g = gdel & (view < vM)
    view = jnp.where(adv_g, vM, view)

    # ---- P2 proposal: node i proposes iff ITS view elects it
    # (view[i] mod N == i — the §B per-receiver leader identity) and
    # extends the newest QC with the block at height b1_h + 1. With
    # desynced views several nodes may propose at once; the round's
    # EFFECTIVE proposal is the highest-view one (Vstar — stale
    # proposals lose, and a receiver ignores views below its own).
    # The broadcast is ONE leader→node delivery row on absolute §2
    # edge keys (the dpos producer-row idiom — O(N), never [N, N]).
    h_next = st.b1_h + 1
    # SPEC §7c: under byz_mode="equivocate" a byzantine leader DOES
    # propose — two block variants for the same (view, height), each
    # receiver shown one (per-receiver value-id e_j below). Under the
    # default silent mode a byzantine leader skips its view, exactly
    # as before (`equiv` is a Python bool: the flat/silent program is
    # unchanged bit for bit).
    prop_i = (view % jnp.int32(N) == idx) & ~churn & (h_next < S)
    if not equiv:
        prop_i &= honest
    if crash_on:
        prop_i &= ~down
    Vstar = jnp.max(jnp.where(prop_i, view, -1))
    exists = Vstar >= 0
    L = jnp.where(exists, Vstar % jnp.int32(N), jnp.int32(0))
    uL = L.astype(jnp.uint32)
    byzL = L >= jnp.int32(N - cfg.n_byzantine)

    switch = cfg.switch_on
    open_p = _bcast_open(uL)
    if not switch:
        open_v = ~(rng.delivery_u32_jnp(seed, ur, uidx, uL)
                   < _lt(cfg.drop_cutoff))
        if cfg.max_delay_rounds > 0:
            open_v |= delayed_open(seed, ur, uidx, uL, cfg.drop_cutoff,
                                   cfg.max_delay_rounds)

    pdel = exists & ((idx == L) | open_p) & (view <= Vstar)
    if crash_on:
        pdel &= ~down   # down receivers hear nothing (SPEC §6c)

    # ---- P3 votes: receivers of the proposal vote; the vote is a
    # node→leader flight on edge (j, L). Byzantine replicas (silent)
    # withhold. The leader's threshold check is ONE count — the whole
    # linear-communication point. (Given pdel, the partition side check
    # on the return edge is the identical predicate — a same-side pair
    # stays same-side within the round.) Under net_model="switch"
    # (SPEC §9) the votes route through the K aggregators instead: the
    # leader sees K pre-aggregated segment counts, and the STREAM_AGG
    # fault axes (a down aggregator drops its whole vote segment; a
    # stale one re-serves a shifted round's delivery pattern) become
    # view-liveness attacks.
    vote = pdel & honest
    if equiv:
        # §7c per-receiver value-id: which variant the (byzantine)
        # leader showed node j — draw(STREAM_EQUIV, round, leader, j)&1,
        # the same sup keying the pbft family uses for per-receiver
        # claims. Honest leaders pin every receiver to variant 0.
        evid = jnp.where(byzL,
                         (_draw(seed, rng.STREAM_EQUIV, ur, uL, uidx)
                          & jnp.uint32(1)).astype(jnp.int32),
                         0)
        # Byzantine REPLICAS under equivocate vote for BOTH variants
        # (the maximal double-vote adversary) — silent-mode byz never
        # vote at all.
        voteb = pdel & ~honest
    if switch:
        aggst = agg_round(cfg, seed, ur)
        K_agg = cfg.n_aggregators
        sids = agg_ids(N, K_agg)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            # vote/voteb already fold ~down via pdel; the fold here
            # kills a CRASHED liar's §9b uplink claim too (§6c: down
            # nodes send nothing, forged or not).
            up0 &= ~down
        down0 = downlink(cfg, seed, ur, aggst, 0, jnp.reshape(L, (1,)))[:, 0]
        # §9b poisoned combines: a byzantine aggregator serves a forged
        # full-segment-population count — for BOTH variant queries under
        # equivocate, which is exactly how a poisoned switch vertex
        # forges a forked QC without real double votes.
        pz0 = agg_poison(cfg, seed, ur, 0)
        wid = seg_widths(jnp.ones(N, bool), sids, K_agg) \
            if pz0 is not None else None
        # §9b uplink lies: a byzantine node claims a vote to its switch
        # vertex regardless of delivery (and, under equivocate, for both
        # variants — it's a claim, not a pinned value). The forged-value
        # payload is count-path-irrelevant for hotstuff.
        lie, _fv = uplink_lies(cfg, seed, ur, ~honest)

        def _served(segx):
            srv = jnp.where(down0, segx, 0)
            if pz0 is not None:
                srv = jnp.where(down0 & pz0, wid, srv)
            return jnp.sum(srv)

        if pz0 is not None:
            # Leader's own aggregator poisoned+delivered: the forged
            # width already counts L's slot — don't add the local vote.
            own = take_seg((pz0 & down0).astype(jnp.int32), sids,
                           K_agg)[L].astype(bool)

        def _count(sup, self_sup):
            contrib = sup & (idx != L) & up0
            seg = seg_sum(contrib.astype(jnp.int32), sids, K_agg)
            s = self_sup.astype(jnp.int32)
            if pz0 is not None:
                s = jnp.where(own, 0, s)
            return s + _served(seg)

        if equiv:
            claim = (voteb | lie) if lie is not None else voteb
            sup0 = (vote & (evid == 0)) | claim
            sup1 = (vote & (evid == 1)) | claim
            cnt0 = _count(sup0, sup0[L])
            cnt1 = _count(sup1, sup1[L])
        else:
            sup = (vote | lie) if lie is not None else vote
            cnt = _count(sup, vote[L])
    else:
        pz0 = None
        if equiv:
            vd0 = ((vote & (evid == 0)) | voteb) & ((idx == L) | open_v)
            vd1 = ((vote & (evid == 1)) | voteb) & ((idx == L) | open_v)
            cnt0 = jnp.sum(vd0.astype(jnp.int32))
            cnt1 = jnp.sum(vd1.astype(jnp.int32))
        else:
            vdel = vote & ((idx == L) | open_v)
            cnt = jnp.sum(vdel.astype(jnp.int32))
    if equiv:
        # §7c per-value QC tally: each variant needs its own quorum.
        # BOTH reaching Q in one view is a FORKED QC — the safety
        # violation classic HotStuff's signature checks exclude and
        # this byzantine model deliberately re-admits. The canonical
        # chain prefers variant 0 (deterministic tie-break, mirrored
        # in the oracle).
        qc0 = exists & (cnt0 >= Q)
        qc1 = exists & (cnt1 >= Q)
        qc = qc0 | qc1
        forked = qc0 & qc1
        vid = jnp.where(qc0, jnp.int32(0), jnp.int32(1))
        cnt = cnt0 + cnt1   # telemetry: total votes the leader counted
    else:
        qc = exists & (cnt >= Q)

    # ---- P4 QC-chain shift + chained 3-chain commit: the new QC is
    # the prepare phase of its block, promotes its parent to
    # pre-commit (the lock) and — when the three newest QCs sit in
    # consecutive views — commits the grandparent.
    b1_v = jnp.where(qc, Vstar, st.b1_v)
    b1_h = jnp.where(qc, h_next, st.b1_h)
    b2_v = jnp.where(qc, st.b1_v, st.b2_v)
    b2_h = jnp.where(qc, st.b1_h, st.b2_h)
    b3_v = jnp.where(qc, st.b2_v, st.b3_v)
    b3_h = jnp.where(qc, st.b2_h, st.b3_h)
    sarange = jnp.arange(S, dtype=jnp.int32)
    chain_v = jnp.where((sarange == h_next) & qc, Vstar, st.chain_v)
    consec = (b3_v >= 0) & (b1_v == b2_v + 1) & (b2_v == b3_v + 1)
    gcommit = jnp.where(qc & consec,
                        jnp.maximum(st.gcommit, b3_h + 1), st.gcommit)

    # ---- §7c fork-certificate table: on a forked QC, record (view,
    # height) in the next free slot and set that slot's bit for every
    # honest receiver the leader showed the NON-canonical variant —
    # those nodes durably believe the sibling block sits at this
    # height, which _extract materializes as conflicting decided
    # values. O(N + F) carry, no [N, S] tensor.
    if equiv:
        chain_vid = jnp.where((sarange == h_next) & qc, vid, st.chain_vid)
        deceived = pdel & honest & (evid == 1)
        can = forked & (st.fnum < FORK_TABLE)
        hot = (jnp.arange(FORK_TABLE, dtype=jnp.int32) == st.fnum) & can
        ftab_v = jnp.where(hot, Vstar, st.ftab_v)
        ftab_h = jnp.where(hot, h_next, st.ftab_h)
        fbit = jnp.left_shift(jnp.int32(1),
                              jnp.minimum(st.fnum, FORK_TABLE - 1))
        fvec = jnp.where(can & deceived, st.fvec | fbit, st.fvec)
        fnum = st.fnum + can.astype(jnp.int32)
    else:
        chain_vid, fvec = st.chain_vid, st.fvec
        ftab_v, ftab_h, fnum = st.ftab_v, st.ftab_h, st.fnum

    # ---- P6 learning + QC-notify: the proposal carries the proposer's
    # view and the commit state as of proposal time, so every receiver
    # syncs to Vstar and extends its durable committed prefix; when the
    # QC forms, the same open channels carry the certificate back out,
    # so receivers enter view Vstar + 1 — the within-round notify the
    # chained pipeline needs (without it the 3-chain's consecutive-view
    # rule could never fire).
    view = jnp.where(pdel, jnp.where(qc, Vstar + 1, Vstar), view)
    clen = jnp.where(pdel, jnp.maximum(clen, st.gcommit), clen)

    # ---- P7 per-node pacemaker: progress (a delivered proposal or a
    # view-sync catch-up) resets the local timer; otherwise the node's
    # OWN view changes after view_timeout local rounds without it.
    progress = pdel | adv_g
    to = ~progress & (timer + 1 >= cfg.view_timeout)
    advn = (pdel & qc) | adv_g | to       # node's view advanced this round
    view = view + to.astype(jnp.int32)
    timer_pre = timer                     # flight: rounds this view took
    timer = jnp.where(progress | to, 0, timer + 1)

    if crash_on:
        # SPEC §6c freeze: a down node's local state holds its
        # post-volatile-reset value (its timer must not tick, its
        # prefix must not grow, while crashed).
        view, timer, clen = freeze_down(down, frozen, (view, timer, clen))

    new = HotstuffState(seed, b1_v, b1_h, b2_v, b2_h,
                        b3_v, b3_h, gcommit, chain_v, chain_vid, fvec,
                        ftab_v, ftab_h, fnum, view, timer, clen, down)
    if not telem:
        return new
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    az = agg_counts(aggst, poison_count(aggst, pz0)) if switch \
        else agg_counts()
    if equiv:
        # §7c conflicting commit indices: a deceived node's durable
        # prefix crossed a recorded fork height this round — from here
        # on its decided log disagrees with the canonical chain at that
        # height. Static FORK_TABLE-deep loop, all counts on device.
        conf = jnp.zeros((), jnp.int32)
        for k in range(FORK_TABLE):
            inw = ((jnp.int32(k) < fnum) & (ftab_h[k] >= st.clen)
                   & (ftab_h[k] < new.clen))
            conf += jnp.sum((((fvec >> k) & 1).astype(bool)
                             & inw).astype(jnp.int32))
        sz = safety_counts(forked, conf)
    else:
        sz = safety_counts()
    syncz = sync_counts(new.view, honest & ~new.down, adv_g)
    tosum = jnp.sum(to.astype(jnp.int32))
    if cfg.desync_on:
        tosum = tosum + jnp.sum(pre_to.astype(jnp.int32))
    vec = jnp.stack([qc.astype(jnp.int32),
                     gcommit - st.gcommit,
                     jnp.sum(new.clen - st.clen),
                     tosum,
                     jnp.sum(pdel.astype(jnp.int32)),
                     cnt, *cz, *az, *sz, *syncz])
    if not flight:
        return new, vec
    lat = jnp.stack([
        bucket_counts(timer_pre + 1, advn),
        bucket_counts(b1_h + 1 - gcommit, True)])
    return new, vec, lat


def hotstuff_init(cfg: Config, seed) -> HotstuffState:
    N, S = cfg.n_nodes, cfg.log_capacity
    z = jnp.int32(0)
    none = jnp.int32(-1)
    return HotstuffState(
        jnp.asarray(seed, jnp.uint32), none, none, none, none,
        none, none, z, jnp.full((S,), -1, jnp.int32),
        jnp.zeros(S, jnp.int32), jnp.zeros(N, jnp.int32),
        jnp.full((FORK_TABLE,), -1, jnp.int32),
        jnp.full((FORK_TABLE,), -1, jnp.int32), z,
        jnp.zeros(N, jnp.int32), jnp.zeros(N, jnp.int32),
        jnp.zeros(N, jnp.int32), jnp.zeros(N, bool))


def hotstuff_round_telem(cfg: Config, st: HotstuffState, r):
    return hotstuff_round(cfg, st, r, telem=True)


def hotstuff_round_flight(cfg: Config, st: HotstuffState, r):
    return hotstuff_round(cfg, st, r, telem=True, flight=True)


def _extract(st: HotstuffState) -> dict:
    """Decided logs materialized from the O(N + S) carry: node i has
    committed exactly heights [0, clen[i]); the value at height s is
    the pure counter function of (certifying view, s) — so the [N, S]
    tensors exist only here, in the one-time extraction epilogue,
    never in the round program."""
    S = st.chain_v.shape[-1]
    sarange = jnp.arange(S, dtype=jnp.int32)
    committed = sarange[None, None, :] < st.clen[..., None]
    v0 = _block_val(st.seed[..., None], st.chain_v, sarange[None, :])
    v1 = _block_val(st.seed[..., None], st.chain_v, sarange[None, :], sub=6)
    base = jnp.where(st.chain_vid == 1, v1, v0)
    dval = jnp.where(committed, base[..., None, :], 0)
    # §7c deceived overlays: at each recorded fork, a node holding that
    # entry's fvec bit committed the SIBLING variant (subdraw 6 — the
    # canonical side of a fork is always variant 0). Static
    # FORK_TABLE-deep loop; the per-node divergence is exactly what the
    # oracle differential + safety assertions observe.
    for k in range(FORK_TABLE):
        ok = jnp.int32(k) < st.fnum
        hh = st.ftab_h[..., k]
        alt = _block_val(st.seed, st.ftab_v[..., k], hh, sub=6)
        hit = (((st.fvec >> k) & 1).astype(bool)[..., None]
               & (sarange == hh[..., None, None])
               & ok[..., None, None] & committed)
        dval = jnp.where(hit, alt[..., None, None], dval)
    return {"committed": committed, "dval": dval,
            "clen": st.clen, "gcommit": st.gcommit,
            "chain_v": st.chain_v, "view": st.view,
            "fvec": st.fvec, "fnum": st.fnum}


def _pspec(cfg: Config) -> HotstuffState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    g, v = P(), P(ND)
    return HotstuffState(seed=g, b1_v=g, b1_h=g,
                         b2_v=g, b2_h=g, b3_v=g, b3_h=g, gcommit=g,
                         chain_v=P(None), chain_vid=P(None), fvec=v,
                         ftab_v=P(None), ftab_h=P(None), fnum=g,
                         view=v, timer=v, clen=v, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("hotstuff", hotstuff_init, hotstuff_round,
                            _extract, _pspec,
                            telemetry_names=HOTSTUFF_TELEMETRY,
                            round_telem=hotstuff_round_telem,
                            latency_names=HOTSTUFF_LATENCY,
                            round_flight=hotstuff_round_flight)
    return _ENGINE
