"""Batched PBFT f-sweep: a whole f ladder as ONE XLA program.

The reference runs its `pbft::quorum` f-sweep [B:9] as one process per f
(each with N = 3f+1 nodes). A naive TPU port would compile 128 separate
programs (shapes differ per f) — ~an hour of XLA compiles for seconds of
execution. Instead, the TPU-native design pads every sweep element to
N_pad = 3·f_max+1 nodes and makes (n_real, f) *traced per-lane scalars*:

  * padded nodes are never honest senders, never delivered to/from, and
    are sliced off before serialization — and because every RNG draw is
    keyed by absolute ids (round, edge i→j, node), not by N (docs/SPEC.md
    §1-2), the draws real nodes see are IDENTICAL to the unpadded
    engine's. Byte-equivalence with the per-f C++ oracle runs is tested
    in tests/test_pbft_sweep.py.
  * quorum threshold Q = 2f+1 and primary = view mod n_real use the
    traced scalars, so one compiled kernel serves every f.

BOTH fault models compile this way (the former `--f-sweep` carve-outs,
VERDICT weak #5, are lifted): ``fault_model="edge"`` runs the dense
SPEC §6 round (:func:`pbft_round_padded`) and ``fault_model="bcast"``
runs the §6b aggregate sort-diet round
(:func:`pbft_bcast_round_padded` — the engines/pbft_bcast.py kernel
with traced (n_real, f): one payload sort, binary-search order
statistics, top-M run-table delivery). A bcast f ladder that used to
need one process per rung is now one compiled program, contract-pinned
at trace time by the ``pbft-100k-bcast-fsweep`` hlocheck target.

The ladder also carries an independent-sweeps axis: ``cfg.n_sweeps``
instances per rung run as extra vmap lanes — lane (rung k, sweep j)
seeds at lo32(seed + k + j), exactly the seed vector an individual
``f=fs[k], seed=seed+k, n_sweeps=K`` run would use, so per-rung decided
payloads stay byte-equal to standalone runs (the CLI equivalence
contract, tests/test_cli.py).

Cost: ~3.4x the FLOPs of the exact per-f sum (padding waste), repaid
>100x over in avoided compiles; the whole sweep runs as one `vmap` under
one `lax.scan`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import churn as churn_draw
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import delivery as _delivery
from .pbft import _adopt_val, _vth_select
from ..ops.adversary import draw as _draw
from ..ops.adversary import bitcast_i32 as _i32
from ..ops.viewsync import desync_skew
from .pbft import PbftState
from .pbft_bcast import (_aggregate_tallies, _kth_largest, _table_width,
                         view_bound)


def _padded_switch_phases(cfg: Config, seed, ur, n_real, honest,
                          pp_seen, pp_val, prepared, committed, dval, Q,
                          *, byz, bcast_uplink: bool):
    """The SPEC §9 switch P4/P5/P6 on a padded population with TRACED
    per-lane (n_real, Q): segmentation B = ceil(n_real/K) and the
    aggregator vertex base are the lane's true n_real, so every draw
    key matches the standalone switch run at that rung byte-for-byte
    (per-rung equivalence, tests/test_aggregate.py). ``byz`` is None
    without equivocators. Shared by both padded rounds — ``bcast_uplink``
    selects the §6b one-broadcast-per-round uplink vs the edge model's
    per-phase uplinks. Crash (§6c) is rejected upstream by the ladder."""
    from ..ops.aggregate import (agg_poison, agg_round, downlink,
                                 downlink_self, min_id_votes, seg_widths,
                                 uplink_bcast, uplink_edge, uplink_lies,
                                 value_votes)
    N = cfg.n_nodes                      # N_pad (static)
    K = cfg.n_aggregators
    idx = jnp.arange(N, dtype=jnp.int32)
    real = idx < n_real
    sids = jnp.minimum(idx // ((n_real + K - 1) // K), K - 1)
    aggst = agg_round(cfg, seed, ur)
    equiv = byz is not None
    if equiv:
        stance = (_draw(seed, rng.STREAM_EQUIV, ur, idx.astype(jnp.uint32),
                        jnp.uint32(0x80000000)) & jnp.uint32(1)).astype(bool)
    # SPEC §9b poisoned aggregation on the padded lanes: forged widths
    # count REAL segment populations only (seg_widths over the live
    # prefix) and lies are drawn for the lane's true byzantine tail —
    # both on absolute ids, so each rung stays byte-equal to its
    # standalone switch run.
    pz0 = agg_poison(cfg, seed, ur, 0)
    pz1 = agg_poison(cfg, seed, ur, 1)
    wid = seg_widths(real, sids, K, traced=True) if pz0 is not None \
        else None
    lie, fval = uplink_lies(cfg, seed, ur, real & ~honest)

    def up_ph(ph: int):
        if bcast_uplink:
            return uplink_bcast(cfg, seed, aggst, seg_ids=sids,
                                n_vert=n_real, traced=True)
        return uplink_edge(cfg, seed, aggst, ph, seg_ids=sids,
                           n_vert=n_real, traced=True)

    upb = up_ph(0)
    up0, up1, up2 = (upb, upb, upb) if bcast_uplink \
        else (upb, up_ph(1), up_ph(2))
    down0 = downlink(cfg, seed, ur, aggst, 0, idx, n_vert=n_real)
    dn0 = downlink_self(cfg, seed, ur, aggst, 0, seg_ids=sids,
                        n_vert=n_real)
    c4 = value_votes(pp_val, honest[:, None] & pp_seen, up0, down0, dn0,
                     sids, K, eq_up=(byz & stance & up0) if equiv else None,
                     lie=lie, lie_val=fval, poison=pz0, widths=wid,
                     traced=True)
    pcount = c4 + (honest[:, None] & pp_seen).astype(jnp.int32)
    prepared = prepared | (pp_seen & (pcount >= Q))
    down1 = downlink(cfg, seed, ur, aggst, 1, idx, n_vert=n_real)
    dn1 = downlink_self(cfg, seed, ur, aggst, 1, seg_ids=sids,
                        n_vert=n_real)
    c5 = (value_votes(pp_val, honest[:, None] & prepared, up1, down1, dn1,
                      sids, K,
                      eq_up=(byz & stance & up1) if equiv else None,
                      lie=lie, lie_val=fval, poison=pz1, widths=wid,
                      traced=True)
          + (honest[:, None] & prepared).astype(jnp.int32))
    commit_now = prepared & (c5 >= Q) & ~committed
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now
    down2 = downlink(cfg, seed, ur, aggst, 2, idx, n_vert=n_real)
    dec = honest[:, None] & committed
    imin, vad = min_id_votes(dec, dval, up2, down2, sids, K, N,
                             traced=True)
    adopt = (imin < N) & ~committed
    dval = jnp.where(adopt, vad, dval)
    committed = committed | adopt
    return prepared, committed, dval


def pbft_round_padded(cfg: Config, st: PbftState, r, n_real, f):
    """One SPEC §6 round on a padded population.

    ``cfg.n_nodes`` is the padded size N_pad (static); ``n_real`` = 3f+1
    and ``f`` are traced i32 scalars. Mirrors engines/pbft.py phase by
    phase; the only deltas are the padding mask and the traced Q/primary.
    """
    N, S = cfg.n_nodes, cfg.log_capacity
    Q = 2 * f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    sarange = jnp.arange(S, dtype=jnp.int32)
    real = idx < n_real

    deliver = _delivery(seed, N, ur, cfg.drop_cutoff, cfg.partition_cutoff,
                        cfg.max_delay_rounds)
    deliver = deliver & real[:, None] & real[None, :]
    churn = churn_draw(seed, ur, cfg.churn_cutoff)
    honest = idx < (n_real - cfg.n_byzantine)
    d_h = deliver & honest[:, None]
    d_self_h = (deliver | jnp.eye(N, dtype=bool)) & honest[:, None]

    # Equivocators (SPEC §6 byz_mode="equivocate") — same absolute-id
    # keyed draws as the unpadded engine, so padding stays byte-invisible.
    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        byz = real & ~honest
        sup = (_draw(seed, rng.STREAM_EQUIV, ur,
                     idx[:, None].astype(jnp.uint32),
                     idx[None, :].astype(jnp.uint32))
               & jnp.uint32(1)).astype(bool)

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    committed_at_start = committed
    # SPEC §B timer-skew injection on ABSOLUTE node-id keys: real ids
    # 0..n_real-1 draw exactly what a standalone 3f+1 run draws, so the
    # padding stays byte-invisible; padded ids burn draws no real node
    # ever observes. (No `real` mask needed — a padded node's timer is
    # already dead state.)
    if cfg.desync_on:
        timer = timer + desync_skew(seed, ur, idx.astype(jnp.uint32),
                                    cfg.desync_cutoff, cfg.max_skew_rounds)

    # ---- P0 churn: synchronized view bump.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up: (f+1)-th largest delivered honest view ∪ own.
    w = jnp.where(d_h, view[:, None], -1)
    w = jnp.where(jnp.eye(N, dtype=bool), view[None, :], w)
    # (f+1)-th largest with traced f, by value binary search (padded
    # senders contribute -1, which never wins; f < n_real <= N keeps
    # the statistic inside the real entries).
    vth = _vth_select(w, f, 2 * cfg.n_rounds + 2)
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare.
    is_primary = honest & (view % n_real == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = sarange[None, :] == fresh[:, None]
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % n_real
    del_self = deliver | jnp.eye(N, dtype=bool)
    prim_ok = del_self[prim, idx] & (view[prim] == view) & real
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(sup[prim, idx], 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, del_self[prim, idx] & real, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4/P5/P6 — flat per-receiver tallies, or the SPEC §9 switch
    # combine with TRACED segmentation (B = ceil(n_real/K) is per-lane,
    # so segment reduces go through jax.ops.segment_* instead of the
    # static reshape; draws are keyed on the lane's true n_real, making
    # each rung byte-equal to its standalone switch run).
    switch = cfg.switch_on
    if switch:
        prepared, committed, dval = _padded_switch_phases(
            cfg, seed, ur, n_real, honest,
            pp_seen, pp_val, prepared, committed, dval, Q,
            byz=byz if equiv else None, bcast_uplink=False)
    else:
        # ---- P4 prepare tally (value-matched, incl. self).
        val_eq = pp_val[:, None, :] == pp_val[None, :, :]
        pcount = jnp.sum(d_self_h[:, :, None] & pp_seen[:, None, :] & val_eq,
                         axis=0, dtype=jnp.int32)
        if equiv:
            extra = jnp.sum(deliver & byz[:, None] & sup, axis=0,
                            dtype=jnp.int32)
            pcount = pcount + extra[:, None]
        prepared = prepared | (pp_seen & (pcount >= Q))

        # ---- P5 commit tally.
        ccount = jnp.sum(d_self_h[:, :, None] & prepared[:, None, :] & val_eq,
                         axis=0, dtype=jnp.int32)
        if equiv:
            ccount = ccount + extra[:, None]
        commit_now = prepared & (ccount >= Q) & ~committed
        dval = jnp.where(commit_now, pp_val, dval)
        committed = committed | commit_now

        # ---- P6 decide gossip: adopt from lowest-id delivered decider.
        dec_b = committed & honest[:, None]
        imin = jnp.min(jnp.where(d_h[:, :, None] & dec_b[:, None, :],
                                 idx[:, None, None], N), axis=0)
        adopt = (imin < N) & ~committed
        dval = jnp.where(adopt, _adopt_val(d_h, dec_b, imin, dval), dval)
        committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    # The f-sweep does not model SPEC §6c crashes (the CLI rejects
    # --crash-prob with --f-sweep); the state's down mask rides unchanged.
    return PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                     prepared, committed, dval, st.down)


def pbft_bcast_round_padded(cfg: Config, st: PbftState, r, n_real, f,
                            m_cap: int):
    """One SPEC §6b round on a padded population — the aggregate
    sort-diet kernel of engines/pbft_bcast.py with traced per-lane
    (n_real, f): ONE payload sort, binary-search P1 order statistics
    (traced ranks K = f+1, f), and top-``m_cap`` run-table delivery.

    ``m_cap`` is the static table width covering every lane:
    max over rungs of ``_table_width(3f+1, f, byz)`` — a lane's live
    senders are <= 3f+1 (padded nodes never send), so the per-lane
    exactness bound holds inside the shared padded shape. Crash (§6c)
    is rejected upstream; padded receivers accumulate garbage that the
    extraction slices off, and never influence real nodes (they are
    never senders, primaries, nor deciders).
    """
    N, S = cfg.n_nodes, cfg.log_capacity
    Q = 2 * f + 1
    K = f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    sarange = jnp.arange(S, dtype=jnp.int32)
    real = idx < n_real

    no_part = cfg.no_partition
    bcast = rng.delivery_u32_jnp(seed, ur, uidx, uidx) >= _lt(cfg.drop_cutoff)
    if cfg.max_delay_rounds > 0:
        # SPEC §A.2 on the §6b broadcast key — same absolute (i, i)
        # keys as the unpadded engine, so padding stays byte-invisible.
        from ..ops.adversary import delayed_open
        bcast = bcast | delayed_open(seed, ur, uidx, uidx, cfg.drop_cutoff,
                                     cfg.max_delay_rounds)
    bcast = bcast & real
    if not no_part:
        part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                       < _lt(cfg.partition_cutoff))
        side = (_draw(seed, rng.STREAM_PARTITION, ur, 1, uidx)
                & jnp.uint32(1)).astype(jnp.int32)               # [N]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (n_real - cfg.n_byzantine)
    byz = real & ~honest

    def side_ok(b):
        return ~part_active | (side == b)

    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        # Per-receiver stances (SPEC §7c) with TRACED byz ids (the
        # lane's byz rows are n_real - nb .. n_real): the full [N, N]
        # sup draw masked to byz senders — the same absolute (r, i, j)
        # keys as the dedicated engine's [nb, N] grid, so each rung is
        # byte-equal to its standalone run. Materialized only when
        # equivocators exist; the byz-free contract-pinned ladder
        # program never pays it.
        supg = (_draw(seed, rng.STREAM_EQUIV, ur, uidx[:, None],
                      uidx[None, :]) & jnp.uint32(1)).astype(bool)
        sendg = (supg & (byz & bcast)[:, None]
                 & (idx[:, None] != idx[None, :]))
        if not no_part:
            sendg &= ~part_active | (side[:, None] == side[None, :])
        eq_extra = jnp.sum(sendg.astype(jnp.int32), axis=0)      # [N]

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    committed_at_start = committed
    # SPEC §B timer-skew injection — same absolute-id keying as the
    # dense padded round above.
    if cfg.desync_on:
        timer = timer + desync_skew(seed, ur, idx.astype(jnp.uint32),
                                    cfg.desync_cutoff, cfg.max_skew_rounds)

    # ---- P0 churn.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 per-side order statistics (ranks traced: K, K-1; the
    # ladder validates f >= 1, so K-1 >= 1 always has a defined rank).
    sender_v = honest & bcast
    vmax = view_bound(cfg)
    vplus = view + 1
    if no_part:
        w1 = jnp.where(sender_v, vplus, 0)[None, :]              # [1, N]
        stat = _kth_largest(jnp.concatenate([w1, w1]),
                            jnp.stack([K, K - 1]).astype(jnp.int32), vmax)
        a1 = jnp.broadcast_to(stat[0], (N,))
        a2 = jnp.broadcast_to(stat[1], (N,))
    else:
        cols = jnp.stack([jnp.where(sender_v & side_ok(0), vplus, 0),
                          jnp.where(sender_v & side_ok(1), vplus, 0)])
        stat = _kth_largest(jnp.concatenate([cols, cols]),
                            jnp.stack([K, K, K - 1, K - 1])
                            .astype(jnp.int32), vmax)
        a1 = stat[0:2][side]
        a2 = stat[2:4][side]
    vth = jnp.where(sender_v, a1, jnp.clip(view, a1, a2))
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare.
    is_primary = honest & (view % n_real == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = (sarange[None, :] == fresh[:, None])
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % n_real
    if no_part:
        prim_del = (prim == idx) | bcast[prim]
    else:
        prim_del = (prim == idx) | (bcast[prim]
                                    & (~part_active | (side[prim] == side)))
    prim_ok = prim_del & (view[prim] == view) & real
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        # Per-receiver fork — sup(r, prim(j), j), the same key as the
        # dedicated engine and the dense kernel's sup[prim, idx].
        sup_prim = (_draw(seed, rng.STREAM_EQUIV, ur,
                          prim.astype(jnp.uint32), uidx)
                    & jnp.uint32(1)).astype(bool)
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(sup_prim, 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, prim_del & real, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4 + P5 (+P6). Flat: the SHARED aggregate machinery (one
    # payload sort + top-M run tables, pbft_bcast._aggregate_tallies)
    # with traced Q and the rung-maxed static table width — one
    # quorum-count path for the dedicated engine and the ladder, so
    # they cannot drift. Switch (SPEC §9): the shared traced-
    # segmentation combine (`_padded_switch_phases`, §6b one-broadcast
    # uplink) — no sort at all.
    if cfg.switch_on:
        prepared, committed, dval = _padded_switch_phases(
            cfg, seed, ur, n_real, honest,
            pp_seen, pp_val, prepared, committed, dval, Q,
            byz=byz if equiv else None, bcast_uplink=True)
    else:
        _, prepared, commit_now, _ = _aggregate_tallies(
            pp_val, pp_seen, prepared, committed, honest, bcast, Q, m_cap,
            side=None if no_part else side,
            part_active=None if no_part else part_active,
            extra=eq_extra if equiv else None)
        dval = jnp.where(commit_now, pp_val, dval)
        committed = committed | commit_now

        # ---- P6 decide gossip: lowest-id broadcasting decider per side.
        dec = honest[:, None] & bcast[:, None] & committed
        if no_part:
            src = jnp.where(dec, idx[:, None], N)
            imin_rows = jnp.min(src, axis=0)[None, :]
            imin = jnp.broadcast_to(imin_rows, (N, S))
        else:
            rows = []
            for b in (0, 1):
                src = jnp.where(dec & side_ok(b)[:, None], idx[:, None], N)
                rows.append(jnp.min(src, axis=0))
            imin_rows = jnp.stack(rows)
            imin = imin_rows[side]
        adopt = (imin < N) & ~committed
        val_rows = dval[jnp.clip(imin_rows, 0, N - 1), sarange[None, :]]
        vfull = (jnp.broadcast_to(val_rows, (N, S)) if no_part
                 else val_rows[side])
        dval = jnp.where(adopt, vfull, dval)
        committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    return PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                     prepared, committed, dval, st.down)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _fsweep_jit(cfg: Config, m_cap: int, seeds, n_reals, fs):
    from .pbft import pbft_init

    st0 = jax.vmap(lambda s: pbft_init(cfg, s))(seeds)
    rounds = jnp.arange(cfg.n_rounds, dtype=jnp.int32)
    bcast = cfg.fault_model == "bcast"

    def body(sts, r):
        if bcast:
            fn = lambda s, n, f: pbft_bcast_round_padded(  # noqa: E731
                cfg, s, r, n, f, m_cap)
        else:
            fn = lambda s, n, f: pbft_round_padded(  # noqa: E731
                cfg, s, r, n, f)
        return jax.vmap(fn)(sts, n_reals, fs), None

    stF, _ = jax.lax.scan(body, st0, rounds)
    return stF


def pbft_fsweep_timed(cfg: Config, fs, repeats: int = 1):
    """Shared measurement harness for the one-program f-sweep (used by the
    CLI's --f-sweep and benchmarks/run_benchmarks.py, so their timing
    policy and step accounting cannot drift apart).

    Returns ``(out, compile_s, best_wall_s, real_steps)`` where the first
    call's wall time is the compile+warmup cost, ``best_wall_s`` is the
    best of ``repeats`` warm executions, and ``real_steps`` counts only
    real 3f+1 nodes (times ``cfg.n_sweeps`` instances per rung) — padded
    lanes are FLOP waste, not simulated work.

    Each timed repeat dispatches a DIFFERENT element-seed vector (base
    seed offset by (r+1)*len(fs)): the tunnel backend caches identical
    dispatches (docs/PERF.md round 5), so re-timing byte-identical
    inputs could replay a cached result. The kernel is branchless with
    seed-independent shapes — throughput is seed-invariant — and the
    reported ``out`` (and hence the digest) comes from the kept warmup
    state at the base seeds, the trajectories the digest contract names.
    """
    import time

    from ..network.runner import _sync_elem

    def sync(st):
        # Timing policy matches time_tpu (benchmarks/run_benchmarks.py):
        # the timed window covers device work via the shared jitted
        # O(1)-byte completion witness (runner._sync_elem — dispatch is
        # async and block_until_ready lies on the tunnel backend); the
        # ~8 MB result extraction happens once, after timing.
        np.asarray(_sync_elem(st.view))

    t0 = time.perf_counter()
    st0 = _fsweep_device(cfg, fs)
    sync(st0)  # un-synced warmup would drain inside the first window
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for rep in range(max(1, repeats)):
        t0 = time.perf_counter()
        stF = _fsweep_device(cfg, fs, seed_offset=(rep + 1) * len(fs))
        sync(stF)
        best = min(best, time.perf_counter() - t0)
    real_steps = (sum(3 * int(f) + 1 for f in fs) * cfg.n_rounds
                  * cfg.n_sweeps)
    return _fsweep_slice(st0, fs, cfg.n_sweeps), compile_s, best, real_steps


def rung_payloads(out) -> list[bytes]:
    """Per-rung canonical decided payloads: rung k's bytes are EXACTLY
    what a standalone ``f=fs[k], seed=seed+k, n_sweeps=K`` run
    serializes (network/simulator.decided_payload over the same
    pack_sparse), so per-rung digests compare 1:1 with individual runs
    — the lifted-carve-out acceptance contract (tests/test_cli.py)."""
    from ..core import serialize

    payloads = []
    for o in out:
        c, s, v = serialize.pack_sparse(o["committed"].astype(bool),
                                        o["dval"])
        payloads.append(serialize.serialize_decided("pbft", c, s, v))
    return payloads


def fsweep_payload(out) -> bytes:
    """Concatenated per-rung canonical decided payloads — THE equivalence
    handle for a ladder run (byte-equal to running each f alone). One
    definition shared by the CLI's --f-sweep report and the benchmark
    suite so their digests cannot drift."""
    return b"".join(rung_payloads(out))


def pbft_fsweep_run(cfg: Config, fs) -> list[dict]:
    """Run the f ladder, ``cfg.n_sweeps`` instances per rung, in one
    compiled program: rung k sweep j uses f = fs[k], seed = lo32(seed +
    k + j). ``cfg.f`` is ignored; ``cfg.n_nodes`` may be 0 (it is
    replaced by the padded size). Returns one dict per rung with arrays
    sliced back to that rung's real 3f+1 nodes, batched over the rung's
    sweeps — identical layout to engines.pbft.pbft_run's output for the
    equivalent standalone config.
    """
    return _fsweep_slice(_fsweep_device(cfg, fs), fs, cfg.n_sweeps)


def _fsweep_static(cfg: Config, fs):
    """Validate a ladder request and derive its static compile
    parameters: the padded config (one vmap lane per (rung, sweep)) and
    the bcast table width covering every rung. Shared by the dispatch
    path (:func:`_fsweep_device`) and the hlocheck trace-time lowering
    (:func:`fsweep_lower`), so the contract-pinned program IS the
    dispatched one."""
    import dataclasses

    fs = [int(f) for f in fs]
    if not fs or min(fs) < 1:
        raise ValueError(f"f-sweep rungs must be >= 1, got {fs!r}")
    if cfg.crash_on:
        # The padded round kernels carry the down mask unchanged — a
        # crashing config would silently simulate zero crashes (the
        # same divergence Config rejects for the cpu engine).
        raise ValueError("the pbft f-sweep does not implement the SPEC "
                         "§6c crash-recover adversary; run per-f configs "
                         "instead of --f-sweep with crash_prob > 0")
    if cfg.n_byzantine > min(fs):
        # Per-rung equivalence is against a standalone f=fs[k] run,
        # whose Config requires n_byzantine <= f — a rung below the byz
        # count has no valid standalone twin to be byte-equal to.
        raise ValueError(f"n_byzantine={cfg.n_byzantine} exceeds the "
                         f"smallest rung f={min(fs)}; every rung must "
                         f"satisfy the pbft n_byzantine <= f invariant")
    if cfg.switch_on and cfg.n_aggregators > 3 * min(fs) + 1:
        # Per-rung equivalence is against standalone f=fs[k] runs whose
        # Config requires n_aggregators <= n_nodes = 3f+1.
        raise ValueError(
            f"n_aggregators={cfg.n_aggregators} exceeds the smallest "
            f"rung's population 3*{min(fs)}+1 (SPEC §9: K <= n_nodes "
            "must hold for every rung's standalone twin)")
    n_pad = 3 * max(fs) + 1
    cfg_pad = dataclasses.replace(cfg, protocol="pbft", f=max(fs),
                                  n_nodes=n_pad,
                                  n_sweeps=len(fs) * cfg.n_sweeps)
    eb = (cfg.n_byzantine
          if cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0 else 0)
    m_cap = max(_table_width(3 * f + 1, f, eb) for f in fs)
    return fs, cfg_pad, m_cap


def _fsweep_device(cfg: Config, fs, seed_offset: int = 0):
    """Run the one-program ladder; return the padded final state ON
    DEVICE (callers extract or sync as appropriate). ``seed_offset``
    shifts every lane's seed WITHOUT touching the (static, compiled)
    config — the cache-defeating repeat knob of pbft_fsweep_timed; a
    seed change via dataclasses.replace(cfg, ...) would recompile."""
    fs, cfg_pad, m_cap = _fsweep_static(cfg, fs)
    ks = np.repeat(np.arange(len(fs), dtype=np.uint64), cfg.n_sweeps)
    js = np.tile(np.arange(cfg.n_sweeps, dtype=np.uint64), len(fs))
    seeds = ((np.uint64(cfg.seed) + np.uint64(seed_offset) + ks + js)
             & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    n_reals = jnp.asarray(np.repeat([3 * f + 1 for f in fs],
                                    cfg.n_sweeps), jnp.int32)
    fs_lanes = jnp.asarray(np.repeat(fs, cfg.n_sweeps), jnp.int32)
    return _fsweep_jit(cfg_pad, m_cap, jnp.asarray(seeds), n_reals,
                       fs_lanes)


def fsweep_lower(cfg: Config, fs):
    """Trace-time lowering of the exact one-program ladder
    :func:`_fsweep_device` dispatches, over ShapeDtypeStructs — the
    hlocheck `pbft-100k-bcast-fsweep` target (tools/hlocheck/hlo.py).
    A ladder is ONE dispatch (no chunked cross-dispatch carry), so the
    donation contract sees zero carry leaves by construction."""
    fs, cfg_pad, m_cap = _fsweep_static(cfg, fs)
    lanes = cfg_pad.n_sweeps
    return _fsweep_jit.lower(
        cfg_pad, m_cap,
        jax.ShapeDtypeStruct((lanes,), jnp.uint32),
        jax.ShapeDtypeStruct((lanes,), jnp.int32),
        jax.ShapeDtypeStruct((lanes,), jnp.int32))


def _fsweep_slice(stF, fs, n_sweeps: int) -> list[dict]:
    # Pull each padded array ONCE and slice on the host: per-rung device
    # slicing issued 3 tiny transfers per rung — ~2·|fs| tunnel
    # round-trips that dominated the measured wall at |fs|=128 (~26 s
    # for ~1 s of device time, caught 2026-07-30).
    committed = np.asarray(stF.committed)
    dval = np.asarray(stF.dval)
    view = np.asarray(stF.view)
    out = []
    for k, f in enumerate(fs):
        n = 3 * int(f) + 1
        lanes = slice(k * n_sweeps, (k + 1) * n_sweeps)
        out.append({
            "committed": committed[lanes, :n],
            "dval": dval[lanes, :n],
            "view": view[lanes, :n],
        })
    return out
