"""PBFT as a JAX array kernel (docs/SPEC.md §6).

The reference's `pbft::quorum` prepare+commit vote tallies [B:5] become
value-matched masked reductions: `count[j,s] = Σ_i delivered(i,j) ∧
pp_val[i,s] == pp_val[j,s]` compared against Q = 2f+1 (SURVEY.md §2
component 5). The f = 1..128 sweep [B:9] runs as a batch axis over
separately-compiled (N = 3f+1)-shaped programs (shapes differ per f).

View changes use the f+1 catch-up rule and are made certificate-free-safe
by the prepared-refusal rule (see SPEC §6 safety argument).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import (CRASH_TELEMETRY, SAFETY_TELEMETRY, crash_counts,
                             crash_transition, freeze_down, safety_counts)
from ..ops.aggregate import AGG_TELEMETRY, agg_counts, poison_count
from ..ops.viewsync import SYNC_TELEMETRY, desync_skew, sync_counts
from .raft import _delivery, _draw, _i32, _lt


class PbftState(NamedTuple):
    seed: jnp.ndarray       # [] uint32
    view: jnp.ndarray       # [N] i32
    timer: jnp.ndarray      # [N] i32
    pp_seen: jnp.ndarray    # [N, S] bool
    pp_view: jnp.ndarray    # [N, S] i32
    pp_val: jnp.ndarray     # [N, S] i32
    prepared: jnp.ndarray   # [N, S] bool
    committed: jnp.ndarray  # [N, S] bool
    dval: jnp.ndarray       # [N, S] i32
    down: jnp.ndarray       # [N] bool — SPEC §6c crashed mask


# SPEC §6c persistent/volatile carry split (tools/lint check `registry`):
# view/timer rejoin at 0 (P1's f+1 catch-up restores the view from live
# peers); the per-slot message log — pp_*, prepared, committed, dval —
# is the persisted state PBFT's safety argument rests on. Shared by the
# §6b bcast engine (same PbftState, same split — engines/pbft_bcast.py
# declares it independently so the lint checks each round's code).
# Compiled-program contract (tools/hlocheck): the dense §6 kernel
# tallies pairwise — sort-free AND scan-free by design (its tallies and
# `_vth_select` searches are plain reductions; the former cumsum count
# of 11 was reduction cascades the classifier now files under the
# reduce class — tools/hlocheck/hlo.py `_scan_window`). No node-sharded
# claim: the dense [i, j, s] tensors are the engine the §6b bcast
# kernel exists to replace at scale.
PROGRAM_CONTRACT = dict(sort_budget=0, cumsum_budget=0, node_sharded=None)

CRASH_SPLIT = {
    "seed": "meta",
    "view": "volatile",
    "timer": "volatile",
    "pp_seen": "persistent",
    "pp_view": "persistent",
    "pp_val": "persistent",
    "prepared": "persistent",
    "committed": "persistent",
    "dval": "persistent",
    "down": "meta",
}


def _vth_select(w, f, vmax: int):
    """(f+1)-th largest per column of ``w`` (ints in [-1, vmax]): the
    largest v with |{i : w[i, j] >= v}| >= f+1, by fixed-depth binary
    search on the value range — the full [N, N] column sort it replaces
    was ~20% of the one-program f-sweep (same move as the raft commit
    advance, docs/PERF.md). Works with a traced per-lane ``f``.

    Searches t = v+1 in [0, vmax+2) so the midpoint floor-division
    never stalls at lo = -1. Invariant: cnt_ge(lo) >= f+1 (lo = -1
    counts all N > f), cnt_ge(hi) < f+1 (hi = vmax+1 counts none).
    """
    n_cols = w.shape[1]
    w1 = w + 1
    lo = jnp.zeros(n_cols, jnp.int32)
    hi = jnp.full(n_cols, vmax + 2, jnp.int32)
    for _ in range(int(vmax + 1).bit_length()):
        mid = (lo + hi) // 2
        cnt = jnp.sum((w1 >= mid[None, :]).astype(jnp.int32), axis=0)
        ok = cnt >= f + 1
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid)
    return lo - 1


def _adopt_val(d_h, dec_b, imin, dval):
    """Value at ``dval[imin[j, s], s]`` without the arbitrary-index 2D
    gather (serial gather unit, 62% of the f-sweep program): the min-id
    decider is unique per (receiver, slot), so an equality mask + max
    reduction over the existing [N, N, S] broadcast shape is exact.
    Positions with no decider (imin == N) return I32_MIN; callers mask
    them via ``adopt``."""
    N = d_h.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    win = (d_h[:, :, None] & dec_b[:, None, :]
           & (idx[:, None, None] == imin[None, :, :]))
    return jnp.max(jnp.where(win, dval[:, None, :],
                             jnp.iinfo(jnp.int32).min), axis=0)


def pbft_init(cfg: Config, seed) -> PbftState:
    N, S = cfg.n_nodes, cfg.log_capacity
    z = jnp.zeros(N, jnp.int32)
    zs = jnp.zeros((N, S), jnp.int32)
    bs = jnp.zeros((N, S), bool)
    return PbftState(jnp.asarray(seed, jnp.uint32), z, z, bs, zs, zs, bs, bs,
                     zs, jnp.zeros(N, bool))


# On-device protocol telemetry (docs/OBSERVABILITY.md): the per-phase
# counters "Towards Improving the Performance of BFT Consensus"
# (PAPERS.md) builds its evaluation on. Reduced from the round's own
# tallies; never fed back into state (digest-neutral).
PBFT_TELEMETRY = ("prepare_quorums",   # (node, slot) newly prepared
                  "prepare_missed",    # seen, unprepared, tally < Q
                  "commit_quorums",    # committed via own 2f+1 tally
                  "commit_missed",     # prepared, uncommitted, tally < Q
                  "commits_adopted",   # committed via decide gossip
                  "view_changes",      # Σ per-node view advance
                  ) + CRASH_TELEMETRY \
                  + AGG_TELEMETRY \
                  + SAFETY_TELEMETRY \
                  + SYNC_TELEMETRY     # SPEC §B view-desync gauges

# Flight-recorder latency histograms (docs/OBSERVABILITY.md §"Flight
# recorder"; shared with the §6b bcast kernel):
#   view_change_wait_rounds — at each per-node view advance (timeout,
#     churn, or f+1 catch-up), the node's pre-round timer + 1: rounds
#     without progress before the view moved.
#   slot_commit_rounds — at each newly committed (node, slot), the
#     proposal-to-commit latency proxy r - s: primaries fill fresh
#     slots in ascending order at most one per round (P3), so slot s
#     cannot be pre-prepared before round s and r - s bounds its
#     time-to-commit from below exactly under a stable primary.
PBFT_LATENCY = ("view_change_wait_rounds", "slot_commit_rounds")


def pbft_round(cfg: Config, st: PbftState, r, *, telem: bool = False,
               flight: bool = False):
    N, S = cfg.n_nodes, cfg.log_capacity
    f = cfg.f
    Q = 2 * f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    sarange = jnp.arange(S, dtype=jnp.int32)

    deliver = _delivery(seed, N, ur, cfg.drop_cutoff, cfg.partition_cutoff,
                        cfg.max_delay_rounds)
    # SPEC §6c crash-recover adversary: down nodes neither send nor
    # receive; static no-op when crash_cutoff == 0 (digest-neutral).
    crash_on = cfg.crash_on
    down = st.down
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        deliver = deliver & up[:, None] & up[None, :]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (N - cfg.n_byzantine)
    d_h = deliver & honest[:, None]               # honest-sender delivery
    d_self_h = (deliver | jnp.eye(N, dtype=bool)) & honest[:, None]

    # Equivocating byzantine senders (SPEC §6 byz_mode="equivocate"):
    # sup[i, j] is byz i's per-receiver stance this round — it may back
    # conflicting values at different receivers simultaneously.
    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        byz = ~honest
        sup = (_draw(seed, rng.STREAM_EQUIV, ur,
                     idx[:, None].astype(jnp.uint32),
                     idx[None, :].astype(jnp.uint32))
               & jnp.uint32(1)).astype(bool)      # [i, j]

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    if crash_on:
        # Volatile reset on recovery (SPEC §6c): view/timer rejoin at 0
        # (P1's f+1 catch-up restores the view from live peers); the
        # per-slot message log — pp_*, prepared, committed, dval — is
        # the persisted state PBFT's safety argument rests on.
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, pp_seen, pp_view, pp_val, prepared,
                  committed, dval)
    committed_at_start = committed
    # SPEC §B timer-skew injection: an affected node's local timer jumps
    # ahead, so P2's start-of-round timeout fires before this round's
    # pre-prepare can reset it — the premature local view change of the
    # 2601.00273 attack class. Applied AFTER the frozen capture so the
    # §6c freeze discards a down node's skew (the oracle's `!is_down`
    # guard); a compiled no-op at the desync_rate=0 default.
    if cfg.desync_on:
        timer = timer + desync_skew(seed, ur, idx.astype(jnp.uint32),
                                    cfg.desync_cutoff, cfg.max_skew_rounds)

    # ---- P0 churn: synchronized view bump.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up: (f+1)-th largest delivered honest view ∪ own.
    w = jnp.where(d_h, view[:, None], -1)                       # [i, j]
    w = jnp.where(jnp.eye(N, dtype=bool), view[None, :], w)     # include self
    vth = _vth_select(w, f, 2 * cfg.n_rounds + 2)               # (f+1)-th largest
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare.
    is_primary = honest & (view % N == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)  # [N]
    fresh_hot = (sarange[None, :] == fresh[:, None])                   # [N, S]
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))       # [N, S]
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % N                                # [N] receiver's primary
    del_self = deliver | jnp.eye(N, dtype=bool)
    prim_ok = del_self[prim, idx] & (view[prim] == view)               # [N]
    pm_b = ppb[prim]                               # [N, S] primary's broadcast
    pm_val = msg_val[prim]
    if equiv:
        # A byzantine primary offers every slot, per-receiver conflicting
        # values, claiming the receiver's own view (no view-match guard).
        prim_byz = byz[prim]                                           # [N]
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(sup[prim, idx], 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))        # [N, S]
        prim_ok = jnp.where(prim_byz, del_self[prim, idx], prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4 prepare tally (value-matched, incl. self). Under
    # net_model="switch" (SPEC §9) the votes route through the K
    # aggregators: each combines its segment into (count, vmax, vmin)
    # and serves (count, value) only for value-UNIFORM segments;
    # receivers total the delivered serving segments matching their own
    # value, plus their local self vote. Equivocating support collapses
    # to the per-ROUND stance (the §6b draw — the switch dedups
    # per-receiver claims).
    switch = cfg.switch_on
    if switch:
        from ..ops.aggregate import (agg_ids, agg_poison, agg_round,
                                     downlink, downlink_self, min_id_votes,
                                     seg_widths, uplink_edge, uplink_lies,
                                     value_votes)
        K_agg = cfg.n_aggregators
        aggst = agg_round(cfg, seed, ur)
        sids = agg_ids(N, K_agg)
        # SPEC §9b poisoned aggregation (None / static no-op when off):
        # forged-combine draws are per vote PHASE (the byzantine vertex
        # equivocates between P4 and P5); the uplink lie is one claim
        # per (round, node) shared by both phases. P6's min-id decide
        # gossip is NOT poisonable — the decide message carries the
        # decider's identity, a claim the switch cannot forge without
        # it being attributable (SPEC §9b).
        pz4 = agg_poison(cfg, seed, ur, 0)
        pz5 = agg_poison(cfg, seed, ur, 1)
        wid = seg_widths(jnp.ones(N, bool), sids, K_agg) \
            if pz4 is not None else None
        lie, fval = uplink_lies(cfg, seed, ur, ~honest)
        if equiv:
            stance = (_draw(seed, rng.STREAM_EQUIV, ur,
                            idx.astype(jnp.uint32),
                            jnp.uint32(0x80000000))
                      & jnp.uint32(1)).astype(bool)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            up0 &= up
        down0 = downlink(cfg, seed, ur, aggst, 0, idx)
        dn0 = downlink_self(cfg, seed, ur, aggst, 0)
        c4 = value_votes(pp_val, honest[:, None] & pp_seen, up0, down0,
                         dn0, sids, K_agg,
                         eq_up=(byz & stance & up0) if equiv else None,
                         lie=lie, lie_val=fval, poison=pz4, widths=wid)
        pcount = c4 + (honest[:, None] & pp_seen).astype(jnp.int32)
    else:
        val_eq = pp_val[:, None, :] == pp_val[None, :, :]              # [i, j, s]
        pcount = jnp.sum(d_self_h[:, :, None] & pp_seen[:, None, :] & val_eq,
                         axis=0, dtype=jnp.int32)                      # [j, s]
        if equiv:
            # Byz i claims support for exactly j's value iff sup[i, j] —
            # value-independent, so one [j] count serves every slot.
            extra = jnp.sum(deliver & byz[:, None] & sup, axis=0,
                            dtype=jnp.int32)                           # [j]
            pcount = pcount + extra[:, None]
    prep_hit = pp_seen & (pcount >= Q)
    prep_new = prep_hit & ~prepared        # telemetry (DCE'd when off)
    prep_miss = pp_seen & ~prepared & ~prep_hit
    prepared = prepared | prep_hit

    # ---- P5 commit tally (switch: phase-1 two-hop, same combine).
    if switch:
        up1 = uplink_edge(cfg, seed, aggst, 1)
        if crash_on:
            up1 &= up
        down1 = downlink(cfg, seed, ur, aggst, 1, idx)
        dn1 = downlink_self(cfg, seed, ur, aggst, 1)
        c5 = value_votes(pp_val, honest[:, None] & prepared, up1, down1,
                         dn1, sids, K_agg,
                         eq_up=(byz & stance & up1) if equiv else None,
                         lie=lie, lie_val=fval, poison=pz5, widths=wid)
        ccount = c5 + (honest[:, None] & prepared).astype(jnp.int32)
    else:
        ccount = jnp.sum(d_self_h[:, :, None] & prepared[:, None, :] & val_eq,
                         axis=0, dtype=jnp.int32)
        if equiv:
            ccount = ccount + extra[:, None]
    commit_now = prepared & (ccount >= Q) & ~committed
    commit_miss = prepared & ~committed & (ccount < Q)  # telemetry
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now

    # ---- P6 decide gossip: adopt from lowest-id delivered decider
    # (switch: each aggregator serves its segment's min deciding id +
    # that decider's value — the order-statistic combine, phase 2).
    dec_b = committed & honest[:, None]
    if switch:
        up2 = uplink_edge(cfg, seed, aggst, 2)
        if crash_on:
            up2 &= up
        down2 = downlink(cfg, seed, ur, aggst, 2, idx)
        imin, vad = min_id_votes(dec_b, dval, up2, down2, sids, K_agg, N)
        adopt = (imin < N) & ~committed
        dval = jnp.where(adopt, vad, dval)
    else:
        imin = jnp.min(jnp.where(d_h[:, :, None] & dec_b[:, None, :],
                                 idx[:, None, None], N), axis=0)       # [j, s]
        adopt = (imin < N) & ~committed
        dval = jnp.where(adopt, _adopt_val(d_h, dec_b, imin, dval), dval)
    committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    if crash_on:
        # SPEC §6c freeze: down nodes hold their post-reset state.
        (view, timer, pp_seen, pp_view, pp_val, prepared, committed,
         dval) = freeze_down(
            down, frozen, (view, timer, pp_seen, pp_view, pp_val,
                           prepared, committed, dval))

    new = PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                    prepared, committed, dval, down)
    if not telem:
        return new
    cnt = lambda m: jnp.sum(m.astype(jnp.int32))  # noqa: E731
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    # view_changes clips per-node deltas at 0: a §6c recovery resets the
    # node's view to 0, and a raw sum would let that cancel real
    # advances (identical to the plain delta when crashes are off —
    # views never decrease otherwise).
    az = agg_counts(aggst, poison_count(aggst, pz4, pz5)) if switch \
        else agg_counts()
    # SPEC §7c safety invariants, reduced from the round's own tallies:
    # forked_qc — slots where this round's commit quorums certified
    # CONFLICTING values at honest nodes; conflict_commits — per-round
    # gauge of slots where two honest nodes hold committed with
    # different decided values. Static zeros unless a byzantine axis
    # that can actually violate agreement is on.
    unsafe = equiv or cfg.agg_poison_on or cfg.uplink_lies_on
    if unsafe:
        imin32, imax32 = jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max
        nw = commit_now & honest[:, None]
        forked = (jnp.any(nw, axis=0)
                  & (jnp.max(jnp.where(nw, pp_val, imin32), axis=0)
                     != jnp.min(jnp.where(nw, pp_val, imax32), axis=0)))
        cm = committed & honest[:, None]
        conflicts = (jnp.any(cm, axis=0)
                     & (jnp.max(jnp.where(cm, dval, imin32), axis=0)
                        != jnp.min(jnp.where(cm, dval, imax32), axis=0)))
        sz = safety_counts(forked, conflicts)
    else:
        sz = safety_counts()
    # SPEC §B desync gauges: end-of-round view disagreement among the
    # honest live population, plus the P1 catch-ups that healed some of
    # it — pbft's view-sync message is the f+1 catch-up rule.
    syncz = sync_counts(view, honest & ~down, catch)
    vec = jnp.stack([cnt(prep_new), cnt(prep_miss), cnt(commit_now),
                     cnt(commit_miss), cnt(adopt),
                     jnp.sum(jnp.maximum(view - st.view, 0)), *cz, *az,
                     *sz, *syncz])
    if not flight:
        return new, vec
    from ..ops.flight import bucket_counts
    lat = jnp.stack([
        bucket_counts(st.timer + 1, view > st.view),
        bucket_counts(jnp.asarray(r, jnp.int32) - sarange[None, :],
                      commit_now | adopt)])
    return new, vec, lat


def pbft_round_telem(cfg: Config, st: PbftState, r):
    return pbft_round(cfg, st, r, telem=True)


def pbft_round_flight(cfg: Config, st: PbftState, r):
    return pbft_round(cfg, st, r, telem=True, flight=True)


def _pbft_extract(st: PbftState) -> dict:
    return {"committed": st.committed, "dval": st.dval, "view": st.view,
            "prepared": st.prepared, "pp_val": st.pp_val, "pp_seen": st.pp_seen}


def _pbft_pspec(cfg: Config) -> PbftState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    return PbftState(seed=P(), view=v, timer=v, pp_seen=m, pp_view=m,
                     pp_val=m, prepared=m, committed=m, dval=m, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("pbft", pbft_init, pbft_round, _pbft_extract,
                            _pbft_pspec, telemetry_names=PBFT_TELEMETRY,
                            round_telem=pbft_round_telem,
                            latency_names=PBFT_LATENCY,
                            round_flight=pbft_round_flight)
    return _ENGINE


def pbft_run(cfg: Config, **kw):
    """``cfg.fault_model == "bcast"`` selects the SPEC §6b large-N engine
    (engines/pbft_bcast.py); the dispatch rule lives in
    :func:`consensus_tpu.network.simulator.engine_def`."""
    from ..network import runner
    from ..network.simulator import engine_def
    return runner.run(cfg, engine_def(cfg), **kw)
