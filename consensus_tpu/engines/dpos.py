"""DPoS as a JAX array kernel (docs/SPEC.md §7).

The reference's `dpos::vote` stake-weighted sum over up to 100k validators
with an epoch schedule [B:5, B:11] maps to `jax.ops.segment_sum` of stakes
by candidate, a stable top-K for the producer set, and a scan over rounds
that touches only one producer row per round — O(V) per round, O(E·V) for
all epoch tallies, never O(V²).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import CRASH_TELEMETRY, crash_counts, crash_transition
from .raft import _draw, _lt, _store_dtype


class DposState(NamedTuple):
    seed: jnp.ndarray       # [] uint32
    chain_r: jnp.ndarray    # [V, L] _store_dtype(n_rounds-1) — block round
    chain_p: jnp.ndarray    # [V, L] _store_dtype(n_candidates-1) — producer
    chain_len: jnp.ndarray  # [V] i32
    down: jnp.ndarray       # [V] bool — SPEC §6c crashed mask


# SPEC §6c persistent/volatile carry split (tools/lint check `registry`):
# the chain is durable and dpos carries NO volatile per-node state — a
# down validator simply stops appending (the round masks `append` with
# the down flags), so there is no recovery reset and no freeze call.
# Compiled-program contract (tools/hlocheck): the 181M-steps/s engine —
# one fusion per round at the HBM floor, zero sort-class passes in the
# ROUND program (the epoch top-21 argsort runs once in make_carry, i.e.
# in _init_jit, outside the scanned chunk hlocheck budgets).
# node_sharded="zero": no carry leaf is node-indexed, so a node-sharded
# round program must emit NO collectives at all.
PROGRAM_CONTRACT = dict(sort_budget=0, cumsum_budget=0, node_sharded="zero")

CRASH_SPLIT = {
    "seed": "meta",
    "chain_r": "persistent",
    "chain_p": "persistent",
    "chain_len": "persistent",
    "down": "meta",
}


def dpos_schedule(cfg: Config, seed):
    """Per-epoch stakes → votes → tally → top-K producers (SPEC §7)."""
    V, C, K = cfg.n_nodes, cfg.n_candidates, cfg.n_producers
    E = -(-cfg.n_rounds // cfg.epoch_len)
    v_idx = jnp.arange(V, dtype=jnp.uint32)
    stake = (_draw(seed, rng.STREAM_STAKE, 0, 0, v_idx)
             % jnp.uint32(1000) + 1).astype(jnp.int32)

    def epoch_producers(e):
        vote = (_draw(seed, rng.STREAM_VOTE, e, 0, v_idx)
                % jnp.uint32(C)).astype(jnp.int32)
        tally = jax.ops.segment_sum(stake, vote, num_segments=C)
        order = jnp.argsort(-tally, stable=True)  # ties → lower id first
        return order[:K].astype(jnp.int32), tally

    producers, tallies = jax.vmap(epoch_producers)(
        jnp.arange(E, dtype=jnp.uint32))
    return stake, producers, tallies  # [V], [E, K], [E, C]


def _producer_delivery(cfg: Config, seed, r, p):
    """Delivery row deliver(p, v) for the single producer p (SPEC §2;
    §A.2 delayed retransmission on the same absolute edge keys when
    ``max_delay_rounds > 0``)."""
    V = cfg.n_nodes
    v_idx = jnp.arange(V, dtype=jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    up = jnp.asarray(p, jnp.uint32)
    open_drop = ~(rng.delivery_u32_jnp(seed, ur, up, v_idx)
                  < _lt(cfg.drop_cutoff))
    if cfg.max_delay_rounds > 0:
        from ..ops.adversary import delayed_open
        open_drop |= delayed_open(seed, ur, up, v_idx, cfg.drop_cutoff,
                                  cfg.max_delay_rounds)
    part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                   < _lt(cfg.partition_cutoff))
    side = _draw(seed, rng.STREAM_PARTITION, ur, 1, v_idx) & jnp.uint32(1)
    side_p = _draw(seed, rng.STREAM_PARTITION, ur, 1, up) & jnp.uint32(1)
    ok = open_drop & ((side == side_p) | ~part_active)
    return ok & (v_idx != up)  # self handled separately


# On-device protocol telemetry (docs/OBSERVABILITY.md). "missed_appends"
# counts validators that failed to extend their chain this round for ANY
# reason (drop, partition, churn, full chain); "churn_slots" counts the
# rounds whose production slot was skipped entirely.
DPOS_TELEMETRY = ("blocks_appended",     # validator-chain extensions
                  "missed_appends",      # validators not extended
                  "producer_rotations",  # slot handoffs p_{r-1} != p_r
                  "churn_slots",         # rounds churned (no block)
                  "missed_slots",        # SPEC §A.1 per-producer slot miss
                  "suppressed_slots",    # SPEC §A.4 correlated suppression
                  ) + CRASH_TELEMETRY    # SPEC §6c (zeros when disabled)

# Flight-recorder latency histogram (docs/OBSERVABILITY.md §"Flight
# recorder"): chain_lag_rounds — one observation per round, the spread
# max(chain_len) - min(chain_len) across validators. Blocks arrive at
# most one per round, so the spread is how many ROUNDS the most-behind
# validator trails the head — the catch-up/irreversibility lag the
# SPEC §7 LIB rule is about, measurable on device without the host-side
# per-producer run analysis lib_index does.
DPOS_LATENCY = ("chain_lag_rounds",)


def dpos_round(cfg: Config, producers, st: DposState, r, *,
               telem: bool = False, flight: bool = False):
    V, L = cfg.n_nodes, cfg.log_capacity
    seed = st.seed
    e = r // cfg.epoch_len
    t = r % cfg.epoch_len
    p = producers[e, t % cfg.n_producers]
    churn = _draw(seed, rng.STREAM_CHURN, jnp.asarray(r, jnp.uint32), 0, 0) \
        < _lt(cfg.churn_cutoff)

    # SPEC §6c crash-recover adversary: a down producer is offline (no
    # block this round, like churn) and down validators miss the
    # broadcast — their chains simply stop growing while crashed. The
    # chain is durable; dpos carries no volatile per-node state, so
    # recovery is plain reachability again.
    crash_on = cfg.crash_on
    down = st.down
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, jnp.asarray(r, jnp.uint32), down, cfg.crash_cutoff,
            cfg.recover_cutoff, cfg.max_crashed)

    # SPEC §A.1 per-producer slot miss: round r's slot is skipped
    # chain-wide (like churn), but the draw is keyed (round, producer)
    # so failures correlate with the schedule. miss_cutoff == 0 is a
    # static no-op — the round program is byte-identical.
    miss_on = cfg.miss_on
    if miss_on:
        from ..ops.adversary import slot_missed
        miss = slot_missed(seed, r, p, cfg.miss_cutoff)

    # SPEC §A.4 correlated producer suppression: ONE draw per
    # (round // suppress_window, producer) — the window keying is the
    # point: a suppressed producer misses EVERY slot it is scheduled
    # for inside the window, so it vanishes from the distinct-producer
    # suffix for suppress_window rounds at a stretch and LIB stalls —
    # the targeted stream RESILIENCE.md §8's negative iid result asked
    # for. suppress_cutoff == 0 is a static no-op.
    suppress_on = cfg.suppress_on
    if suppress_on:
        suppressed = _draw(
            seed, rng.STREAM_SUPPRESS,
            (jnp.asarray(r, jnp.uint32)
             // jnp.uint32(cfg.suppress_window)), 0,
            jnp.asarray(p, jnp.int32).astype(jnp.uint32)) \
            < _lt(cfg.suppress_cutoff)

    recv = _producer_delivery(cfg, seed, r, p)
    recv = recv | (jnp.arange(V, dtype=jnp.int32) == p)   # self-append
    append = recv & ~churn & (st.chain_len < L)
    if miss_on:
        append = append & ~miss
    if suppress_on:
        append = append & ~suppressed
    if crash_on:
        append = append & ~down & ~down[p]

    slot_hot = (jnp.arange(L, dtype=jnp.int32)[None, :] == st.chain_len[:, None]) \
        & append[:, None]
    chain_r = jnp.where(slot_hot, jnp.asarray(r, st.chain_r.dtype),
                        st.chain_r)
    chain_p = jnp.where(slot_hot, p.astype(st.chain_p.dtype), st.chain_p)
    chain_len = st.chain_len + append.astype(jnp.int32)
    new = DposState(seed, chain_r, chain_p, chain_len, down)
    if not telem:
        return new
    rp = jnp.maximum(r - 1, 0)  # previous slot's producer (r=0: no handoff)
    p_prev = producers[rp // cfg.epoch_len,
                       (rp % cfg.epoch_len) % cfg.n_producers]
    n_app = jnp.sum(append.astype(jnp.int32))
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    missed = miss.astype(jnp.int32) if miss_on else jnp.int32(0)
    suppr = suppressed.astype(jnp.int32) if suppress_on else jnp.int32(0)
    vec = jnp.stack([n_app, jnp.int32(V) - n_app,
                     ((r > 0) & (p != p_prev)).astype(jnp.int32),
                     churn.astype(jnp.int32), missed, suppr, *cz])
    if not flight:
        return new, vec
    from ..ops.flight import bucket_counts
    lat = jnp.stack([bucket_counts(jnp.max(chain_len) - jnp.min(chain_len),
                                   True)])
    return new, vec, lat


def dpos_make_carry(cfg: Config, seed):
    """Carry = (per-epoch producer schedule, chain state). The schedule is
    computed once from the seed and rides the scan carry unchanged."""
    _, producers, _ = dpos_schedule(cfg, seed)
    V, L = cfg.n_nodes, cfg.log_capacity
    # chain_p holds PRODUCER ids — drawn from the top-K of the
    # n_candidates tally (dpos_schedule), so the tight bound is
    # n_candidates-1 (<= n_nodes-1, Config enforces C <= V): the 100k
    # benchmark has C=1024 → u16 where a V-based bound would force i32.
    st0 = DposState(jnp.asarray(seed, jnp.uint32),
                    jnp.zeros((V, L), _store_dtype(cfg.n_rounds - 1)),
                    jnp.zeros((V, L), _store_dtype(cfg.n_candidates - 1)),
                    jnp.zeros(V, jnp.int32), jnp.zeros(V, bool))
    return producers, st0


def dpos_round_carry(cfg: Config, carry, r):
    producers, st = carry
    return producers, dpos_round(cfg, producers, st, r)


def dpos_round_carry_telem(cfg: Config, carry, r):
    producers, st = carry
    new, vec = dpos_round(cfg, producers, st, r, telem=True)
    return (producers, new), vec


def dpos_round_carry_flight(cfg: Config, carry, r):
    producers, st = carry
    new, vec, lat = dpos_round(cfg, producers, st, r, telem=True,
                               flight=True)
    return (producers, new), vec, lat


def _dpos_extract(carry) -> dict:
    _, st = carry
    return {"chain_r": st.chain_r.astype(jnp.int32),
            "chain_p": st.chain_p.astype(jnp.int32),
            "chain_len": st.chain_len}


def _dpos_pspec(cfg: Config):
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    # The [E, K] schedule is replicated; chain state shards over validators.
    return (P(None, None),
            DposState(seed=P(), chain_r=P(ND, None), chain_p=P(ND, None),
                      chain_len=P(ND), down=P(ND)))


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("dpos", dpos_make_carry, dpos_round_carry,
                            _dpos_extract, _dpos_pspec,
                            telemetry_names=DPOS_TELEMETRY,
                            round_telem=dpos_round_carry_telem,
                            latency_names=DPOS_LATENCY,
                            round_flight=dpos_round_carry_flight)
    return _ENGINE


def lib_index(chain_p, chain_len, n_candidates: int, n_producers: int):
    """SPEC §7 last-irreversible block: largest local index k such that
    the blocks after k were produced by >= T = floor(2K/3)+1 distinct
    candidates (-1 if none). Computed once from final chains (forks are
    unreachable in this model — SPEC §7 fork-choice note — so LIB is the
    only meaningful piece of the BitShares/EOS chain rule here).

    Vectorized over leading batch axes: chain_p [..., L], chain_len
    [...] -> lib [...]. Equivalent closed form: (T-th largest of each
    candidate's last occurrence index) - 1, clamped to -1.
    """
    chain_p = np.asarray(chain_p)
    chain_len = np.asarray(chain_len)
    T = (2 * n_producers) // 3 + 1
    lead = chain_p.shape[:-1]
    L = chain_p.shape[-1]
    if T > n_candidates:
        return np.full(lead, -1, np.int64)
    # Per-candidate last occurrence, loop-free (the naive per-k loop was
    # the one remaining host-side Python loop next to a hot path; at
    # L in the thousands it dominated the extraction epilogue). Stable
    # argsort groups each candidate's occurrences into a run with k
    # ascending inside it, so the end of each run IS that candidate's
    # last occurrence; invalid tail slots (k >= chain_len) sort into a
    # sentinel run past every real candidate. Run ends are unique per
    # (row, candidate), so one fancy-index scatter lands them all.
    B = int(np.prod(lead, dtype=np.int64)) if lead else 1
    k_idx = np.arange(L, dtype=np.int64)
    valid = k_idx < chain_len.reshape(B, 1)
    p = np.where(valid, chain_p.reshape(B, L), n_candidates)
    order = np.argsort(p, axis=-1, kind="stable")   # == k, sorted by p
    p_sorted = np.take_along_axis(p, order, axis=-1)
    run_end = np.ones((B, L), dtype=bool)
    run_end[:, :-1] = p_sorted[:, 1:] != p_sorted[:, :-1]
    rows, ends = np.nonzero(run_end)
    lo = np.full((B, n_candidates + 1), -1, np.int64)  # +1: sentinel run
    lo[rows, p_sorted[rows, ends]] = order[rows, ends]
    last_occ = lo[:, :n_candidates].reshape(lead + (n_candidates,))
    lt = np.partition(last_occ, n_candidates - T, axis=-1)[..., n_candidates - T]
    return np.maximum(lt - 1, -1)


def dpos_run(cfg: Config, **kw):
    """Returns {chain_r, chain_p, chain_len, lib} (host numpy, leading
    sweep axis); ``lib`` is the SPEC §7 last-irreversible index."""
    from ..network import runner
    out = runner.run(cfg, get_engine(), **kw)
    out["lib"] = lib_index(out["chain_p"], out["chain_len"],
                           cfg.n_candidates, cfg.n_producers)
    return out
