"""Raft as a branchless JAX array kernel — the TPU engine's flagship.

Implements docs/SPEC.md §3 over the whole node population at once: state is
a struct-of-arrays pytree (one row per node), one round is a pure function
built from masked `where`-selects and matrix-shaped message exchanges, and
a run is `lax.scan` over rounds with sweeps vmapped as a leading batch axis
(SURVEY.md §7 core design decision; the reference's `raft::log` scalar hot
loops `match_index`/`append_entries` [B:5] become the gather/scatter and
running-max updates below).

State is int32 on device (TPU x64 is disabled), except match/next
replication bookkeeping, stored at the narrowest width that holds L+1
(:func:`_match_dtype`); u32 semantics from the spec are preserved because
terms/indices stay < 2^31 and RNG words are bitcast — byte-equivalence
with the uint32 C++ oracle is checked in tests/test_raft_differential.py.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config

ROLE_F, ROLE_C, ROLE_L = 0, 1, 2
NONE = -1


class RaftState(NamedTuple):
    seed: jnp.ndarray       # [] uint32 — per-sweep seed (SPEC §1)
    term: jnp.ndarray       # [N] i32
    role: jnp.ndarray       # [N] i32
    voted_for: jnp.ndarray  # [N] i32
    log_term: jnp.ndarray   # [N, L] i32
    log_val: jnp.ndarray    # [N, L] i32
    log_len: jnp.ndarray    # [N] i32
    commit: jnp.ndarray     # [N] i32
    timer: jnp.ndarray      # [N] i32
    timeout: jnp.ndarray    # [N] i32
    match_idx: jnp.ndarray  # [N, N] _match_dtype(L) — match_idx[l, j]
    next_idx: jnp.ndarray   # [N, N] _match_dtype(L)
    down: jnp.ndarray       # [N] bool — SPEC §6c crashed mask


# SPEC §6c persistent/volatile carry split — machine-checked against the
# recovery-reset and freeze code in raft_round by tools/lint (check
# `registry`): volatile fields are exactly those reset on the recovery
# mask; persistent+volatile is exactly the frozen tuple; "meta" fields
# (the per-sweep seed and the down mask itself) sit outside the split.
# timeout is persistent because it is a pure function of (seed, term,
# id) and the term persists — recomputing it on rejoin is a no-op.
# Compiled-program contract (tools/hlocheck, docs/STATIC_ANALYSIS.md
# "compiled-program layer"): regression CEILINGS on the lowered round
# program — the sort-diet work may lower them, never raise them. The
# dense [N, N] kernel is sort-free; its cumsum passes are the log-match
# brackets lower as plain-reduction cascades, filed under the reduce
# class (tools/hlocheck/hlo.py `_scan_window`) — the round is scan-free. No node-sharded claim: the dense
# engine's multi-chip story is digest-tested (test_runner), not
# structure-claimed — the capped §3b engine owns that claim.
PROGRAM_CONTRACT = dict(sort_budget=0, cumsum_budget=0, node_sharded=None)

CRASH_SPLIT = {
    "seed": "meta",
    "term": "persistent",
    "role": "volatile",
    "voted_for": "persistent",
    "log_term": "persistent",
    "log_val": "persistent",
    "log_len": "persistent",
    "commit": "persistent",
    "timer": "volatile",
    "timeout": "persistent",
    "match_idx": "volatile",     # leader bookkeeping, re-init at election
    "next_idx": "volatile",
    "down": "meta",
}

# Shared kernels live in ops/ (SURVEY.md §7 package layout); the aliases
# keep this module's call sites terse and preserve the original seams.
from ..ops.adversary import CRASH_TELEMETRY, crash_counts, crash_transition
from ..ops.adversary import bitcast_i32 as _i32
from ..ops.aggregate import AGG_TELEMETRY, agg_counts
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import draw as _draw
from ..ops.adversary import freeze_down as _freeze


def _draw_timeout(seed, t_min, t_max, term, idx):
    d = _draw(seed, rng.STREAM_TIMEOUT, term.astype(jnp.uint32), 0, idx)
    return jnp.int32(t_min) + (d % jnp.uint32(t_max - t_min)).astype(jnp.int32)


def _store_dtype(vmax: int):
    """Narrowest unsigned storage holding values in [0, vmax]. The round
    kernels are HBM-bound (docs/PERF.md), so for state re-read every
    round a narrower dtype is a direct bandwidth win. Same integer
    values at any width: decided logs are bit-identical (differential
    suites) and the oracle keeps u32; extract boundaries cast back."""
    if vmax <= 0xFF:
        return jnp.uint8
    return jnp.uint16 if vmax <= 0xFFFF else jnp.int32


def _match_dtype(L: int):
    """Storage dtype for match/next replication state: values are
    bounded by L+1 (next_idx reaches exactly L+1 at a full log)."""
    return _store_dtype(L + 1)


def raft_init(cfg: Config, seed) -> RaftState:
    N, L = cfg.n_nodes, cfg.log_capacity
    seed = jnp.asarray(seed, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    z = jnp.zeros(N, jnp.int32)
    return RaftState(
        seed=seed,
        term=z, role=z, voted_for=jnp.full(N, NONE, jnp.int32),
        log_term=jnp.zeros((N, L), jnp.int32),
        log_val=jnp.zeros((N, L), jnp.int32),
        log_len=z, commit=z, timer=z,
        timeout=_draw_timeout(seed, cfg.t_min, cfg.t_max, z, idx.astype(jnp.uint32)),
        match_idx=jnp.zeros((N, N), _match_dtype(L)),
        next_idx=jnp.ones((N, N), _match_dtype(L)),
        down=jnp.zeros(N, bool),
    )


from ..ops.adversary import delivery as _delivery  # SPEC §2 delivery mask


# Mask elements below which the helpers keep the plain gather: the
# one-hot reduce pays O(rows*cols) vector work to avoid the serial
# gather unit — a win at benchmark shapes, pure overhead at tiny ones
# (where the gather is a handful of elements; measured raft-5node
# readings 5.5-7.6M steps/s are dispatch-bound variance either way,
# docs/PERF.md). Both paths are value-identical; the reduce path is
# oracle-differential-tested by the large-N configs in
# tests/test_raft_differential.py / test_raft_sparse.py.
_SMALL_PICK = 4096


def _pick1(mat, k):
    """mat[i, k[i]] as a one-hot masked reduction. The obvious
    ``take_along_axis(mat, k[:, None], 1)[:, 0]`` lowers to the serial
    per-element gather unit (~10 ms per call at [800k, 128] on v5 lite
    — it was half the capped-engine round); the masked reduce is one
    vectorized fused pass (~2-4x faster, exact: one hot lane per row)."""
    k = k.astype(jnp.int32)
    if mat.shape[0] * mat.shape[-1] <= _SMALL_PICK:
        return jnp.take_along_axis(mat, k[:, None],
                                   axis=1)[:, 0].astype(jnp.int32)
    L = mat.shape[-1]
    hot = jnp.arange(L, dtype=jnp.int32)[None, :] == k[:, None]
    return jnp.sum(jnp.where(hot, mat.astype(jnp.int32), 0), axis=1)


def _pick_row(mat, rsel):
    """mat[rsel[j], j] for [R, N] ``mat`` (or an [R] vector broadcast to
    columns) — same serial-gather avoidance as :func:`_pick1`, reducing
    over the row axis. Out-of-range ``rsel`` yields 0 on the reduce
    path; every caller clips/bounds ``rsel``, so both paths agree."""
    rsel = rsel.astype(jnp.int32)
    R = mat.shape[0]
    n = rsel.shape[0]
    if mat.ndim == 1:
        if R * n <= _SMALL_PICK:
            return mat[rsel].astype(jnp.int32)
        mat = jnp.broadcast_to(mat[:, None], (R, n))
    elif R * mat.shape[1] <= _SMALL_PICK:
        return mat[rsel, jnp.arange(n, dtype=jnp.int32)].astype(jnp.int32)
    hot = jnp.arange(R, dtype=jnp.int32)[:, None] == rsel[None, :]
    return jnp.sum(jnp.where(hot, mat.astype(jnp.int32), 0), axis=0)


def _last_term(log_term, log_len):
    """log_term[i, log_len[i]-1] or 0 for empty logs."""
    L = log_term.shape[-1]
    k = jnp.clip(log_len - 1, 0, L - 1)
    return jnp.where(log_len > 0, _pick1(log_term, k), 0)


# On-device protocol telemetry (docs/OBSERVABILITY.md): per-round i32
# counters reduced from the round's own intermediates, in this order.
# Never fed back into state — enabling them is digest-neutral.
RAFT_TELEMETRY = ("leader_elections",    # candidates winning this round
                  "append_accepted",     # AppendEntries applied (log match)
                  "append_rejected",     # AppendEntries refused (mismatch)
                  "entries_committed",   # Σ per-node commit-index advance
                  "attack_rounds",       # SPEC §A.3 attack-active rounds
                  ) + CRASH_TELEMETRY \
                  + AGG_TELEMETRY        # SPEC §9 (zeros when flat)

# Flight-recorder latency histograms (docs/OBSERVABILITY.md §"Flight
# recorder"): per-round duration observations bucketed on device by
# ops/flight.bucket_counts, declared next to the counter names so the
# validate_trace registry can be lint-synced the same way. Shared with
# the §3b sparse kernel (same protocol, same semantics):
#   election_wait_rounds — at each leader win, the winner's pre-round
#     liveness timer + 1: rounds since it last heard from a leader (or
#     reset) before gaining leadership — the leadership-gap latency.
#   commit_lag_rounds — per round, each live leader's log_len - commit:
#     proposed-but-uncommitted depth. Leaders propose at most one entry
#     per round (P3a), so the depth IS the commit latency in rounds
#     under a stable leader.
RAFT_LATENCY = ("election_wait_rounds", "commit_lag_rounds")


def raft_round(cfg: Config, st: RaftState, r, *, telem: bool = False,
               flight: bool = False):
    """One SPEC §3 round. `cfg` static; `r` traced i32 scalar.

    ``telem=True`` additionally returns the :data:`RAFT_TELEMETRY`
    vector; the state computation is the identical trace either way
    (the counters read intermediates, XLA dead-code-eliminates them
    when unused). ``flight=True`` (implies telem) further returns the
    :data:`RAFT_LATENCY` bucket matrix ``i32[H, N_BUCKETS]`` — same
    digest-neutrality argument."""
    N, L = cfg.n_nodes, cfg.log_capacity
    E = min(cfg.max_entries, L)
    majority = N // 2 + 1
    mdt = _match_dtype(L)
    seed = st.seed
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    eye = jnp.eye(N, dtype=bool)

    deliver = _delivery(seed, N, ur, cfg.drop_cutoff, cfg.partition_cutoff,
                        cfg.max_delay_rounds)
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)

    # SPEC §A.3 targeted attacks. attack == "none" is a static config
    # fact — no draw, no masks, byte-identical round program.
    elect_on = cfg.attack == "elect"
    sticky_on = cfg.attack == "sticky"
    if elect_on or sticky_on:
        from ..ops.adversary import attack_fires
        atk = attack_fires(seed, ur, cfg.attack_cutoff)
    if sticky_on:
        # Leader-stickiness abuse: while the target holds the
        # leadership at the START of an attacked round, ALL inbound
        # delivery to it is jammed (it never observes higher terms, so
        # the §3 term-change rule cannot fire) and the P0 churn
        # step-down skips it. Its own broadcasts still travel.
        tgt = cfg.attack_target
        sticky_act = atk & (st.role[tgt] == ROLE_L)
        deliver = deliver & ~(sticky_act
                              & (jnp.arange(N, dtype=jnp.int32)[None, :]
                                 == tgt))
    # SPEC §3c Raft byzantine minority (ids >= N - n_byzantine):
    # "silent" withholds every send (votes, acks, heartbeats); state
    # updates stay normal. "equivocate" double-grants: a byz node's vote
    # response goes to EVERY delivered candidate, ignoring term and
    # log-up-to-date checks — the election-safety attack.
    honest = idx < (N - cfg.n_byzantine)
    withhold = cfg.n_byzantine > 0 and cfg.byz_mode == "silent"
    double_grant = cfg.n_byzantine > 0 and cfg.byz_mode == "equivocate"

    term, role, voted_for = st.term, st.role, st.voted_for
    log_term, log_val, log_len = st.log_term, st.log_val, st.log_len
    commit, timer, timeout = st.commit, st.timer, st.timeout
    match_idx, next_idx = st.match_idx, st.next_idx
    down = st.down

    # SPEC §6c crash-recover adversary. crash_cutoff == 0 is a static
    # config fact: the whole block traces away and the round program is
    # the pre-§6c one (digest-neutral by construction, tests/test_crash.py).
    crash_on = cfg.crash_on
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        # Volatile reset on recovery (rejoin from the persisted log):
        # role/timer and leader bookkeeping are volatile; term, voted_for,
        # log, commit survive. timeout is a pure function of (seed, term,
        # id) and the term persisted, so it is definitionally unchanged.
        role = jnp.where(rec, ROLE_F, role)
        timer = jnp.where(rec, 0, timer)
        match_idx = jnp.where(rec[:, None], jnp.asarray(0, mdt), match_idx)
        next_idx = jnp.where(rec[:, None], jnp.asarray(1, mdt), next_idx)
        # A down node neither sends nor receives...
        deliver = deliver & up[:, None] & up[None, :]
        # ...and its own state freezes at the post-reset value.
        frozen = (term, role, voted_for, log_term, log_val, log_len,
                  commit, timer, timeout, match_idx, next_idx)

    def bump(cond, new_term, term, role, voted_for, timeout):
        """SPEC §3 term-change rule where cond."""
        term2 = jnp.where(cond, new_term, term)
        role2 = jnp.where(cond, ROLE_F, role)
        vf2 = jnp.where(cond, NONE, voted_for)
        to2 = jnp.where(cond, _draw_timeout(seed, cfg.t_min, cfg.t_max, term2, uidx),
                        timeout)
        return term2, role2, vf2, to2

    # ---- P0 churn.
    stepdown = churn & (role == ROLE_L)
    if sticky_on:
        stepdown = stepdown & ~(sticky_act & (idx == tgt))
    role = jnp.where(stepdown, ROLE_F, role)
    timer = jnp.where(stepdown, 0, timer)
    reset = stepdown

    # ---- P1 candidacy.
    cand_new = (role != ROLE_L) & (timer >= timeout)
    term = term + cand_new.astype(jnp.int32)
    role = jnp.where(cand_new, ROLE_C, role)
    voted_for = jnp.where(cand_new, idx, voted_for)
    timer = jnp.where(cand_new, 0, timer)
    reset |= cand_new
    timeout = jnp.where(cand_new, _draw_timeout(seed, cfg.t_min, cfg.t_max, term, uidx),
                        timeout)

    # SPEC §A.3 "elect": repeated election disruption — in any attacked
    # round where a candidacy fired in P1 (a timeout expired, so a
    # quorum is about to assemble), ALL round-r election traffic is
    # jammed: P2a/P2b/P2c see no delivered requests or responses. P3
    # replication traffic is untouched. Only LIVE candidacies count
    # under §6c: a down node's cand_new is a phantom (its frozen timer
    # stays expired for the whole outage, and the freeze reverts the
    # candidacy itself), so it must not keep the jammer firing.
    if elect_on:
        live_cand = cand_new & up if crash_on else cand_new
        jam = atk & jnp.any(live_cand)
        deliver_e = deliver & ~jam
    else:
        deliver_e = deliver

    # ---- P2 election. Requests snapshot (post-P1).
    was_cand = role == ROLE_C
    if withhold:
        was_cand &= honest  # byz candidates never broadcast requests
    req_term, req_lidx = term, log_len
    req_lterm = _last_term(log_term, log_len)

    # P2a term catch-up: max delivered candidate term per receiver j.
    sent_term = jnp.where((was_cand[:, None]) & deliver_e,
                          req_term[:, None], 0)
    t_in = jnp.max(sent_term, axis=0)
    bumped = t_in > term
    term, role, voted_for, timeout = bump(bumped, t_in, term, role, voted_for, timeout)

    # P2b grants. elig[c, j]: candidate c's request is grantable at j.
    own_lterm = req_lterm  # P2a mutates no log state; last terms are unchanged
    up_to_date = (req_lterm[:, None] > own_lterm[None, :]) | (
        (req_lterm[:, None] == own_lterm[None, :])
        & (req_lidx[:, None] >= log_len[None, :]))
    elig = was_cand[:, None] & deliver_e \
        & (req_term[:, None] == term[None, :]) & up_to_date
    vf_safe = jnp.clip(voted_for, 0, N - 1)
    vf_elig = (voted_for >= 0) & (_pick_row(elig, vf_safe) > 0)
    first_elig = jnp.min(jnp.where(elig, idx[:, None], N), axis=0)
    grant = jnp.where(
        vf_elig, voted_for,
        jnp.where((voted_for == NONE) & (first_elig < N), first_elig, NONE))
    granted = grant >= 0
    voted_for = jnp.where(granted, grant, voted_for)
    timer = jnp.where(granted, 0, timer)
    reset |= granted

    # P2c tally: votes[c] = 1 + Σ_j [grant_j == c ∧ delivered(j, c)].
    # Under net_model="switch" (SPEC §9) the vote responses route
    # through the K aggregators: each segment-sums its members' votes
    # per candidate (the response edge never travels point-to-point)
    # and candidates see K pre-aggregated counts — the factorized
    # two-hop uplink(j) ∧ downlink(a(j), c) replaces deliver_e[j, c].
    switch = cfg.switch_on
    if switch:
        from ..ops.aggregate import (agg_ids, agg_round, downlink,
                                     seg_sum, uplink_edge)
        aggst = agg_round(cfg, seed, ur)
        sids = agg_ids(N, cfg.n_aggregators)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            up0 &= up
        contrib = (grant[:, None] == idx[None, :]) & ~eye
        if withhold:
            contrib &= honest[:, None]
        if double_grant:
            # Byz j's vote bundle claims EVERY candidate whose request
            # it got (request leg stays flat; response rides the switch).
            byz_votes = (~honest)[:, None] & was_cand[None, :] \
                & deliver_e.T & ~eye
            contrib = jnp.where((~honest)[:, None], byz_votes, contrib)
        seg = seg_sum((contrib & up0[:, None]).astype(jnp.int32), sids,
                      cfg.n_aggregators)                       # [K, N]
        down0 = downlink(cfg, seed, ur, aggst, 0, idx)         # [K, N]
        if crash_on:
            down0 &= up[None, :]
        votes_in = jnp.sum(jnp.where(down0, seg, 0), axis=0)
        if elect_on:
            votes_in = jnp.where(jam, 0, votes_in)
        if sticky_on:
            votes_in = jnp.where(sticky_act & (idx == tgt), 0, votes_in)
        votes = 1 + votes_in
    else:
        resp = (grant[:, None] == idx[None, :]) & deliver_e
        if withhold:
            resp &= honest[:, None]  # byz vote responses never travel
        if double_grant:
            # Byz j's response reaches EVERY candidate whose request it
            # got.
            byz_votes = (~honest)[:, None] & was_cand[None, :] \
                & deliver_e.T & deliver_e
            resp = jnp.where((~honest)[:, None], byz_votes, resp)
        votes = 1 + jnp.sum(resp, axis=0, dtype=jnp.int32)
    win = (role == ROLE_C) & (votes >= majority)
    role = jnp.where(win, ROLE_L, role)
    timer = jnp.where(win, 0, timer)
    reset |= win
    match_idx = jnp.where(win[:, None],
                          jnp.where(eye, log_len[:, None], 0),
                          match_idx).astype(mdt)
    next_idx = jnp.where(win[:, None], log_len[:, None] + 1,
                         next_idx).astype(mdt)

    # ---- P3a propose.
    lead = role == ROLE_L
    can_prop = lead & (log_len < E)
    slot_hot = (jnp.arange(L, dtype=jnp.int32)[None, :] == log_len[:, None]) \
        & can_prop[:, None]
    prop_val = _i32(_draw(seed, rng.STREAM_VALUE, ur, 0, uidx))
    log_term = jnp.where(slot_hot, term[:, None], log_term)
    log_val = jnp.where(slot_hot, prop_val[:, None], log_val)
    log_len = log_len + can_prop.astype(jnp.int32)
    match_idx = jnp.where(eye & can_prop[:, None], log_len[:, None],
                          match_idx).astype(mdt)

    # ---- P3b snapshot sender state (post-(a), commit pre-(e)).
    was_leader = lead & honest if withhold else lead
    s_term, s_len, s_commit = term, log_len, commit
    s_next, s_logt, s_logv = next_idx, log_term, log_val

    # ---- P3c receivers.
    sent_lterm = jnp.where(was_leader[:, None] & deliver, s_term[:, None], 0)
    t_in2 = jnp.max(sent_lterm, axis=0)
    bumped2 = t_in2 > term
    term, role, voted_for, timeout = bump(bumped2, t_in2, term, role, voted_for, timeout)

    valid = was_leader[:, None] & deliver & (s_term[:, None] == term[None, :])
    lstar = jnp.min(jnp.where(valid, idx[:, None], N), axis=0)
    has_l = lstar < N
    ls = jnp.clip(lstar, 0, N - 1)

    timer = jnp.where(has_l, 0, timer)
    reset |= has_l
    role = jnp.where(has_l & (role == ROLE_C), ROLE_F, role)

    prev = _pick_row(s_next, ls) - 1                 # [N] (i32: u8 can't go -1)
    lrow_t = jnp.take(s_logt, ls, axis=0)            # [N, L] leader log rows
    lrow_v = jnp.take(s_logv, ls, axis=0)
    kprev = jnp.clip(prev - 1, 0, L - 1)
    prev_term_l = jnp.where(prev > 0, _pick1(lrow_t, kprev), 0)
    own_at_prev = jnp.where((prev > 0) & (prev <= log_len),
                            _pick1(log_term, kprev), 0)
    ok = (prev == 0) | ((prev <= log_len) & (own_at_prev == prev_term_l))
    apply_ = has_l & ok
    append_rej = has_l & ~ok  # telemetry; DCE'd when telem is off

    l_len = _pick_row(s_len, ls)
    karange = jnp.arange(L, dtype=jnp.int32)[None, :]
    copy_mask = apply_[:, None] & (karange >= prev[:, None]) & (karange < l_len[:, None])
    log_term = jnp.where(copy_mask, lrow_t, log_term)
    log_val = jnp.where(copy_mask, lrow_v, log_val)
    log_len = jnp.where(apply_, l_len, log_len)
    commit = jnp.where(
        apply_,
        jnp.maximum(commit, jnp.minimum(_pick_row(s_commit, ls), log_len)),
        commit)
    ack_to = jnp.where(has_l, ls, NONE)
    ack_ok = apply_
    ack_match = jnp.where(apply_, l_len, 0)
    ack_term = term

    # ---- P3d leaders process acks. ackm[j, l] = ack_to[j]==l ∧ delivered(j, l).
    still_lead = was_leader & (role == ROLE_L)
    ackm = (ack_to[:, None] == idx[None, :]) & deliver
    if withhold:
        ackm &= honest[:, None]  # byz acks never travel
    t_in3 = jnp.max(jnp.where(ackm, ack_term[:, None], 0), axis=0)
    bump3 = still_lead & (t_in3 > term)
    term, role, voted_for, timeout = bump(bump3, t_in3, term, role, voted_for, timeout)
    proc = still_lead & ~bump3

    succ_lj = (ackm & ack_ok[:, None]).T             # [l, j]
    fail_lj = (ackm & ~ack_ok[:, None]).T
    match_idx = jnp.where(proc[:, None] & succ_lj,
                          jnp.maximum(match_idx, ack_match[None, :].astype(mdt)),
                          match_idx)
    next_idx = jnp.where(
        proc[:, None] & succ_lj, match_idx + jnp.asarray(1, mdt),
        jnp.where(proc[:, None] & fail_lj,
                  jnp.maximum(jnp.asarray(1, mdt), next_idx - jnp.asarray(1, mdt)),
                  next_idx))

    # ---- P3e commit advance: majority-th largest of match_idx row,
    # i.e. the largest m with |{j : match_idx[l,j] >= m}| >= majority.
    # Computed by a fixed-depth binary search over the value range [0, E]
    # — match_idx <= log_len <= E = min(max_entries, L), since P3a stops
    # proposing at E and followers only copy leader logs — so ~log2(E)
    # masked [N,N] count-reductions instead of a full [N,N] jnp.sort:
    # same value bit-for-bit, ~10x fewer VPU ops (the sort was 45% of the
    # round pre-optimization; docs/PERF.md "Round-4 attribution").
    lo = jnp.zeros(N, jnp.int32)            # count_ge(0) = N >= majority
    hi = jnp.full(N, E + 1, jnp.int32)      # count_ge(E+1) = 0 < majority
    for _ in range((E + 1).bit_length()):   # halves [lo, hi) to width 1
        mid = (lo + hi) // 2
        cnt = jnp.sum((match_idx >= mid[:, None].astype(mdt)).astype(jnp.int32),
                      axis=1)
        ok = cnt >= majority
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid)
    med = lo
    kmed = jnp.clip(med - 1, 0, L - 1)
    term_at_med = _pick1(log_term, kmed)
    adv = proc & (med > commit) & (med > 0) & (term_at_med == term)
    commit = jnp.where(adv, med, commit)

    # ---- P4 timers.
    timer = jnp.where(role == ROLE_L, 0, jnp.where(reset, timer, timer + 1))

    if crash_on:
        # SPEC §6c freeze: a down node's state is exactly its
        # post-volatile-reset value — delivery masking already kept its
        # (never-sent) messages out of everyone else's round.
        (term, role, voted_for, log_term, log_val, log_len, commit,
         timer, timeout, match_idx, next_idx) = _freeze(
            down, frozen, (term, role, voted_for, log_term, log_val,
                           log_len, commit, timer, timeout, match_idx,
                           next_idx))

    new = RaftState(seed, term, role, voted_for, log_term, log_val, log_len,
                    commit, timer, timeout, match_idx, next_idx, down)
    if not telem:
        return new
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    if elect_on:
        attacked = jam.astype(jnp.int32)
    elif sticky_on:
        attacked = sticky_act.astype(jnp.int32)
    else:
        attacked = jnp.int32(0)
    az = agg_counts(aggst) if switch else agg_counts()
    vec = jnp.stack([jnp.sum(win.astype(jnp.int32)),
                     jnp.sum(apply_.astype(jnp.int32)),
                     jnp.sum(append_rej.astype(jnp.int32)),
                     jnp.sum(commit - st.commit), attacked, *cz, *az])
    if not flight:
        return new, vec
    from ..ops.flight import bucket_counts
    lat = jnp.stack([bucket_counts(st.timer + 1, win),
                     bucket_counts(log_len - commit,
                                   (role == ROLE_L) & ~down)])
    return new, vec, lat


def raft_round_telem(cfg: Config, st: RaftState, r):
    """EngineDef.round_telem entry — a stable named function (a
    functools.partial would hash by identity and fragment jit caches)."""
    return raft_round(cfg, st, r, telem=True)


def raft_round_flight(cfg: Config, st: RaftState, r):
    """EngineDef.round_flight entry (counters + latency buckets)."""
    return raft_round(cfg, st, r, telem=True, flight=True)


def _raft_extract(st: RaftState) -> dict:
    return {"commit": st.commit, "log_term": st.log_term, "log_val": st.log_val,
            "term": st.term, "role": st.role}


def _raft_pspec(cfg: Config) -> RaftState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    return RaftState(seed=P(), term=v, role=v, voted_for=v, log_term=m,
                     log_val=m, log_len=v, commit=v, timer=v, timeout=v,
                     match_idx=m, next_idx=m, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("raft", raft_init, raft_round, _raft_extract,
                            _raft_pspec, telemetry_names=RAFT_TELEMETRY,
                            round_telem=raft_round_telem,
                            latency_names=RAFT_LATENCY,
                            round_flight=raft_round_flight)
    return _ENGINE


def raft_run(cfg: Config, **kw):
    """Run the full batched simulation. Returns host numpy arrays
    {commit, log_term, log_val, term, role} with leading sweep axis [B, ...].
    Keyword args (mesh=, checkpoint_path=, resume=) pass through to
    :func:`consensus_tpu.network.runner.run`.

    ``cfg.max_active > 0`` selects the O(A*N) large-population engine
    (engines/raft_sparse.py, SPEC §3b); 0 selects this dense kernel. The
    dispatch rule lives in :func:`consensus_tpu.network.simulator.engine_def`
    (single source for benchmarks and the digest path alike)."""
    from ..network import runner
    from ..network.simulator import engine_def
    return runner.run(cfg, engine_def(cfg), **kw)
