"""PBFT under the broadcast-atomic fault model (docs/SPEC.md §6b) —
the large-N engine.

The §6 dense kernel (engines/pbft.py) compares values pairwise:
`[i, j, s]` tensors, O(N²·S) — structurally impossible at the north
star's 100k-node scale (BASELINE.json:5 names PBFT in the 100k sweeps).
Under §6b, faults drop a sender's round broadcast atomically, so every
per-receiver multiset depends only on the receiver's partition side and
the round collapses to per-(slot, side) aggregates — the same math the
C++ oracle's ``round_bcast_fast`` proved byte-identical at benchmark
scale (cpp/oracle.cpp, docs/PERF.md "oracle asymptotics"), now ported
on-chip as the ROADMAP sort-diet:

  * **P1** needs only the K-th/(K-1)-th largest sender view per side
    (K = f+1): an order statistic, found by fixed-depth binary search on
    the value range (views are bounded by 2·n_rounds; the `_vth_select`
    move from the dense engine, docs/PERF.md round 5) — the former
    batched `jnp.sort` is gone. The receiver-side insertion is a clamp:
    adding own view x to a multiset whose K-th/(K-1)-th largest are
    a1/a2 puts the new K-th largest at clip(x, a1, a2); a receiver that
    IS a sender replaces its own copy, so its statistic is a1 directly.
  * **P4/P5** tallies ride ONE `lax.sort` per round (down from three
    sort passes): the slot's pp_val column is sorted once with the
    per-node flags bit-packed into a single i32 payload, equal-value
    runs are bracketed gather-free off the monotone cumsum
    (`_SortedRuns.run_counts`), and — new — the results LEAVE sorted
    space without the former unsort (a second payload sort; a
    `.at[perm].set` scatter measured far worse, docs/PERF.md round 5):
    at most ``_table_width(cfg)`` distinct values can reach any node's
    quorum threshold (every passing value needs ≥ Q-1-n_byzantine ≥ f
    valid same-value senders out of ≤ N, so ≤ N//(2f-byz) ≤ 4 values
    qualify — exact, from the Config invariants n_nodes = 3f+1 and
    n_byzantine <= f), so the top-M runs per (slot, side) — extracted
    by M masked max-reductions — form a tiny (value, count) table that
    answers every node's count by an elementwise value match in
    ORIGINAL order. No gather, no scatter, no second sort.
  * **P6** stays the per-side min-reduce + O(S) candidate-row select.

Protocol phases, state, and tie-breaks are §6's verbatim; only fault
granularity changes (SPEC §6b: per-sender drops, unchanged partitions,
per-round equivocation stances). Bit-identity is pinned three ways:
against the retired sorted-tally round (kept as a test-only reference,
tests/reference_pbft_bcast.py) across adversary grids, against the
dense engine when faultless, and byte-for-byte against the oracle's
independent per-receiver derivation (tests/test_pbft_bcast.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import rng
from ..core.config import Config
from ..ops.adversary import (crash_counts, crash_transition, freeze_down,
                             safety_counts)
from ..ops.aggregate import agg_counts, poison_count
from ..ops.adversary import draw as _draw
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import bitcast_i32 as _i32
from ..ops.flight import bucket_counts
from ..ops.viewsync import desync_skew, sync_counts
from .pbft import PBFT_LATENCY, PBFT_TELEMETRY, PbftState, pbft_init

I32_MAX = jnp.iinfo(jnp.int32).max
I32_MIN = jnp.iinfo(jnp.int32).min

# SPEC §6c persistent/volatile carry split — identical to the dense §6
# kernel's (engines/pbft.py: the fault granularity changes, the state
# split does not); declared per-module so tools/lint (check `registry`)
# verifies THIS round's reset/freeze code.
# Compiled-program contract (tools/hlocheck): the sort diet LANDED —
# ONE compiled sort pass per round (the P4/P5 payload sort; P1 is a
# binary-search order statistic, delivery is a top-M run table instead
# of the former unsort) and the cumsum brackets down from 33 to the
# run-count cumsum+cummax pairs. The budgets are LOWERED in the same
# commit as the diet so it cannot creep back (docs/PERF.md "per-engine
# sort budgets"); the retired 3-sort round is the negative fixture
# proving the tightened ceiling fires (tests/test_hlocheck.py).
# No node-sharded claim yet: GSPMD currently gathers full [N, S]-class
# operands when the node axis is sharded (measured, hlocheck registry
# notes) — flipping this to "bounded" is the acceptance bar for the
# mesh-scaling refactor.
PROGRAM_CONTRACT = dict(sort_budget=1, cumsum_budget=20, node_sharded=None)

CRASH_SPLIT = {
    "seed": "meta",
    "view": "volatile",
    "timer": "volatile",
    "pp_seen": "persistent",
    "pp_view": "persistent",
    "pp_val": "persistent",
    "prepared": "persistent",
    "committed": "persistent",
    "dval": "persistent",
    "down": "meta",
}


def view_bound(cfg: Config) -> int:
    """Static upper bound on any node's view when P1 runs: views start
    at 0 and grow at most +2 per round (P0 churn, P2 timeout; the P1
    catch-up never exceeds the current max, §6c recovery resets to 0),
    so at round r < n_rounds every view is <= 2·n_rounds - 1. The same
    bound the dense engine's `_vth_select` search uses."""
    return 2 * cfg.n_rounds + 2


def _kth_largest(w1, ks, vmax: int):
    """Row-wise k-th largest of N-padded multisets. ``w1``: [C, N] i32,
    entry+1 for multiset members and 0 for pads — entries are ints in
    [0, vmax], so pads sort below every entry exactly like the -1 pads
    of the full sort this replaces. Returns [C] i32 in [-1, vmax]: the
    largest v with |{j : w1[c, j] >= v + 1}| >= ks[c] (-1 when fewer
    than k entries, the padded-sort semantics). Fixed-depth binary
    search on the value range — the dense engine's `_vth_select` move
    (docs/PERF.md round 5); ``ks`` may be traced (per-lane f in the
    padded f-sweep round), [C] or broadcastable."""
    n_rows = w1.shape[0]
    lo = jnp.zeros((n_rows,), jnp.int32)
    hi = jnp.full((n_rows,), vmax + 2, jnp.int32)
    for _ in range(int(vmax + 1).bit_length()):
        mid = (lo + hi) // 2
        cnt = jnp.sum((w1 >= mid[:, None]).astype(jnp.int32), axis=1)
        ok = cnt >= ks
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid)
    return lo - 1


def _table_width(n_nodes: int, f: int, equiv_byz: int) -> int:
    """Static width M of the per-(slot, side) top-run tables — the
    exactness bound of the aggregate delivery. A node passes a quorum
    check iff cnt(value) >= Q - self_adj - extra, with self_adj <= 1
    and extra <= equiv_byz (the equivocating-support ceiling), so any
    value that can pass ANY node's threshold has
    cnt >= Tmin = Q - 1 - equiv_byz = 2f - equiv_byz. Counts over one
    (slot, side) sum to at most the valid-sender population <= n_nodes,
    so at most n_nodes // Tmin distinct values qualify — all of them in
    the top-M runs by count (a value below Tmin can never outrank one
    at/above it). Config guarantees n_byzantine <= f, so Tmin >= f >= 1
    whenever f >= 1; the f = 0 edge is n_nodes = 3f+1 = 1, where M = 1
    covers every run outright. Flagship (f = 33333, no byz): M = 1."""
    tmin = 2 * f - equiv_byz
    return max(1, min(n_nodes, n_nodes // max(1, tmin)))


class _SortedRuns:
    """Equal-value run machinery over ONE batched payload sort —
    the whole sort budget of the round.

    ``vals_sn`` [S, N] is sorted along nodes with ``bits_sn`` (a packed
    i32 of every per-node flag the tallies need — an extra sort payload
    is ~free while a [16, 100k] arbitrary-index gather costs ~15 ms on
    v5 lite) and optionally ``extra_sn`` (per-node equivocation
    support) riding as payloads. Unlike the retired `_SortedTally`
    there is NO index/permutation payload: nothing is ever unsorted —
    results return to original order via the top-M run tables
    (:func:`_top_runs` + a per-node value match). The sorted VALUES are
    never masked to sentinels, so arbitrary 32-bit payloads are safe.
    """

    def __init__(self, vals_sn, bits_sn, extra_sn=None):
        n_slots = vals_sn.shape[0]
        ops = (vals_sn, bits_sn) + \
            (() if extra_sn is None else (extra_sn,))
        srt = jax.lax.sort(ops, dimension=1, num_keys=1)
        self.sv, self.bits = srt[0], srt[1]
        self.extra = srt[2] if extra_sn is not None else None
        brk = self.sv[:, 1:] != self.sv[:, :-1]
        self.newrun = jnp.concatenate(
            [jnp.ones((n_slots, 1), bool), brk], axis=1)
        self.endrun = jnp.concatenate(
            [brk, jnp.ones((n_slots, 1), bool)], axis=1)

    def bit(self, k: int):
        """Unpack flag k of the packed payload, sorted order [S, N]."""
        return ((self.bits >> k) & 1).astype(bool)

    def run_counts(self, valid_sn_sorted):
        """Per-run count of valid entries, materialized at each run's
        END position (garbage elsewhere — consumers mask with
        ``endrun``): the plain inclusive cumsum at the end minus the
        exclusive value at the run start, the start value propagated
        forward by a boundary-masked cummax (builtin cumulative ops
        keep the optimized TPU lowering — a custom-combine
        ``lax.associative_scan`` lowers to ~17 levels of
        slice/pad/interleave passes that were ~35% of the 100k
        program). Two cumulative ops per call — the round's whole
        cumsum-class surface is two of these per partition side."""
        flags = valid_sn_sorted.astype(jnp.int32)
        s = jnp.cumsum(flags, axis=1)
        ex_start = jax.lax.cummax(
            jnp.where(self.newrun, s - flags, -1), axis=1)
        return s - ex_start


def _top_runs(runs: _SortedRuns, end_counts, m: int):
    """The ``m`` largest (value, count) runs per slot row, by count —
    the segment-max extraction that replaces the unsort. ``end_counts``
    is :meth:`_SortedRuns.run_counts` output (valid at run ends).
    Returns ``(tv, tc)``: [S, m] i32 tables; ``tc == -1`` marks an
    absent entry (fewer than m runs). Count ties break to the largest
    value; each value appears at most once (the winning run's value is
    masked out before the next extraction), and the choice cannot leak
    into results — every value that can pass a threshold is in the
    table (see :func:`_table_width`), the rest compare unequal."""
    active = runs.endrun
    tvs, tcs = [], []
    for _ in range(m):
        cur = jnp.where(active, end_counts, -1)
        tc = jnp.max(cur, axis=1)                               # [S]
        hit = (cur == tc[:, None]) & (tc[:, None] >= 0)
        tv = jnp.max(jnp.where(hit, runs.sv, I32_MIN), axis=1)  # [S]
        active = active & ~((runs.sv == tv[:, None])
                            & (tc[:, None] >= 0))
        tvs.append(tv)
        tcs.append(tc)
    return jnp.stack(tvs, axis=-1), jnp.stack(tcs, axis=-1)     # [S, m]


def _table_count(vals, tv, tc):
    """Count lookup against one (slot, side) table: for each entry of
    ``vals`` ([..., S] with the slot axis LAST broadcastable against
    the [S, m] tables), the count of its equal-value run — 0 when the
    value is absent (then its true count is below every threshold, the
    table-width argument). Pure elementwise match + sum over m; the
    ``tc >= 0`` guard voids absent entries whatever garbage value they
    hold."""
    match = (vals[..., None] == tv) & (tc >= 0)
    return jnp.sum(jnp.where(match, tc, 0), axis=-1)


def _aggregate_tallies(pp_val, pp_seen, prepared, committed, honest, bcast,
                       Q, m: int, *, side=None, part_active=None,
                       extra=None, up=None):
    """The shared §6b P4+P5 aggregate machinery — ONE payload sort,
    per-(slot, side) top-``m`` run tables, elementwise delivery, with
    the P4 → P5 chain running through the same tables in sorted space
    so the two views cannot disagree. Used by BOTH the dedicated round
    and the padded traced-f ladder round (engines/pbft_sweep.py), so a
    fix to the quorum-count path can never diverge them.

    ``Q`` may be traced (the ladder's per-lane 2f+1); ``m`` is the
    static table width (:func:`_table_width`, maxed over rungs in the
    ladder). ``side``/``part_active`` are None on the static
    no-partition path; ``extra`` is the PER-RECEIVER equivocating
    support count ([N] i32 — SPEC §7c: byz stances are per (sender,
    receiver), so the caller reduces its sup grid with the broadcast,
    self-exclusion and partition filters already folded; still
    value-independent, so one count per receiver serves every slot) —
    None without equivocators; ``up`` is the §6c receiver mask (None
    when crashes are off — down SENDERS are already outside every
    count via the bcast fold).

    Returns ``(prep_hit, prepared2, commit_now, c5)`` in original node
    order — callers derive telemetry (prep_new/miss, commit_miss) and
    state updates from these.
    """
    N, S = pp_val.shape
    no_part = side is None

    def side_ok(b):
        return ~part_active | (side == b)

    if extra is not None:
        # Rides the payload sort so the sorted-space P4 → P5 chain sees
        # each SENDER's own per-receiver count (SPEC §7c).
        extra_sn = jnp.broadcast_to(extra[:, None], (N, S)).T
    else:
        extra_sn = None

    def b32(x):
        return x.astype(jnp.int32)

    bits = (b32(pp_seen) | (b32(prepared) << 1)
            | ((b32(honest) | (b32(bcast) << 1))[:, None] << 2))
    if not no_part:
        bits |= ((b32(side) | (b32(side_ok(0)) << 1)
                  | (b32(side_ok(1)) << 2))[:, None] << 4)
    tal = _SortedRuns(pp_val.T, bits.T, extra_sn)
    pp_seen_s, prepared_s = tal.bit(0), tal.bit(1)
    honest_s, bcast_s = tal.bit(2), tal.bit(3)
    hb_s = honest_s & bcast_s
    extra_s = jnp.int32(0) if tal.extra is None else tal.extra

    def tables_for(relevant_s):
        """Per-side top-m (value, count) tables of the §6b multiset
        count — valid honest broadcasting senders per value run."""
        if no_part:
            masks = (hb_s & relevant_s,)
        else:
            masks = (hb_s & tal.bit(5) & relevant_s,
                     hb_s & tal.bit(6) & relevant_s)
        pairs = [_top_runs(tal, tal.run_counts(mk), m) for mk in masks]
        return ([tv for tv, _ in pairs], [tc for _, tc in pairs])

    def counts_sorted(tvs, tcs):
        """Table lookup for every SORTED position (the P4 → P5 chain):
        position p's count is its value sv[p]'s table count on its own
        side — exact for every count that can meet a threshold."""
        if no_part:
            return _table_count(tal.sv, tvs[0][:, None, :],
                                tcs[0][:, None, :])
        return jnp.where(tal.bit(4),
                         _table_count(tal.sv, tvs[1][:, None, :],
                                      tcs[1][:, None, :]),
                         _table_count(tal.sv, tvs[0][:, None, :],
                                      tcs[0][:, None, :]))

    def counts_nodes(tvs, tcs):
        """Table lookup in ORIGINAL node order — the delivery that
        replaces the unsort. The ≤2 per-side tables are O(S·m) data;
        selecting a node's side row is the same tiny-[2, ...]-by-side
        select P6 already uses, never an [N, S] arbitrary gather."""
        if no_part:
            return _table_count(pp_val, tvs[0][None, :, :],
                                tcs[0][None, :, :])
        tv = jnp.stack(tvs)[side]                        # [N, S, m]
        tc = jnp.stack(tcs)[side]
        return _table_count(pp_val, tv, tc)

    extra_n = jnp.int32(0) if extra is None else extra[:, None]

    # ---- P4 prepare tally (value-matched §6b count incl. self: the
    # self vote never travels, so it counts regardless of bcast fate).
    tv4, tc4 = tables_for(pp_seen_s)
    c4 = (counts_nodes(tv4, tc4)
          + (honest[:, None] & pp_seen & ~bcast[:, None]).astype(jnp.int32)
          + extra_n)
    prep_hit = pp_seen & (c4 >= Q)
    if up is not None:
        # A down receiver can neither prepare nor commit (SPEC §6c) —
        # masked here, not just frozen, so telemetry counters derived
        # from these never report a quorum the trajectory didn't take.
        prep_hit &= up[:, None]
    prepared2 = prepared | prep_hit

    # The sorted-space side of the same P4 decision, for P5's sender
    # mask: each SENDER's own prepare verdict from the same tables +
    # its own self/extra adjustments (the flags ride the sort payload).
    # Down senders need no mask — they never broadcast, so hb_s already
    # excludes them from every count.
    c4_s = (counts_sorted(tv4, tc4)
            + (honest_s & pp_seen_s & ~bcast_s).astype(jnp.int32)
            + extra_s)
    prepared2_s = prepared_s | (pp_seen_s & (c4_s >= Q))

    # ---- P5 commit tally, chained off the P4 result.
    tv5, tc5 = tables_for(prepared2_s)
    c5 = (counts_nodes(tv5, tc5)
          + (honest[:, None] & prepared2
             & ~bcast[:, None]).astype(jnp.int32)
          + extra_n)
    commit_now = prepared2 & (c5 >= Q) & ~committed
    if up is not None:
        commit_now &= up[:, None]
    return prep_hit, prepared2, commit_now, c5


def pbft_bcast_round(cfg: Config, st: PbftState, r, *, telem: bool = False,
                     flight: bool = False):
    N, S = cfg.n_nodes, cfg.log_capacity
    f = cfg.f
    Q = 2 * f + 1
    K = f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    sarange = jnp.arange(S, dtype=jnp.int32)

    # ---- SPEC §6b adversary: per-sender broadcast drops + §2 partition.
    # partition_cutoff == 0 is a static config fact: the partition can
    # never activate, every side_ok() is identically true, and the two
    # sides' aggregates are equal — so the no_part branches below
    # compute one of everything instead of two. Bit-identical: streams
    # are counter-based, so not drawing `side` changes nothing else.
    # The general path is untouched.
    no_part = cfg.no_partition
    bcast = rng.delivery_u32_jnp(seed, ur, uidx, uidx) >= _lt(cfg.drop_cutoff)
    if cfg.max_delay_rounds > 0:
        # SPEC §A.2 delayed retransmission on the per-sender broadcast
        # key (i, i) — the §6b analog of the edge-wise delay term.
        from ..ops.adversary import delayed_open
        bcast = bcast | delayed_open(seed, ur, uidx, uidx, cfg.drop_cutoff,
                                     cfg.max_delay_rounds)
    # SPEC §6c crash-recover adversary: a down node's round broadcasts
    # drop atomically (folded into the per-sender bcast flag — exactly
    # the §6b fault granularity); the receiving side is handled by
    # masking the quorum/adopt events with `up` in ORIGINAL order, so a
    # frozen node also never *counts* a quorum it cannot apply — and
    # then the state freeze below. (The sorted-space chain needs no up
    # flag: down nodes never broadcast, so they are already outside
    # every honest-broadcasting count mask.)
    crash_on = cfg.crash_on
    down = st.down
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        bcast = bcast & up
    if not no_part:
        part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                       < _lt(cfg.partition_cutoff))
        side = (_draw(seed, rng.STREAM_PARTITION, ur, 1, uidx)
                & jnp.uint32(1)).astype(jnp.int32)               # [N]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (N - cfg.n_byzantine)
    byz = ~honest

    def side_ok(b):
        return ~part_active | (side == b)

    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        # SPEC §7c: equivocation is PER RECEIVER — byz sender i's stance
        # toward receiver j is the dense kernel's sup(r, i, j) draw
        # (same STREAM_EQUIV keying, so the §6 and §6b engines model
        # the same adversary). Only the n_byzantine tail rows exist:
        # the grid is [nb, N], never [N, N]. ``extra`` folds the §6b
        # atomic-broadcast fate, self-exclusion and the partition
        # filter, leaving the per-receiver support count the aggregate
        # machinery adds to every slot.
        nb = cfg.n_byzantine
        bids = uidx[N - nb:]
        supg = (_draw(seed, rng.STREAM_EQUIV, ur, bids[:, None],
                      uidx[None, :]) & jnp.uint32(1)).astype(bool)  # [nb, N]
        sendg = (supg & bcast[N - nb:, None]
                 & (bids[:, None] != uidx[None, :]))
        if not no_part:
            sendg &= ~part_active | (side[N - nb:, None] == side[None, :])
        eq_extra = jnp.sum(sendg.astype(jnp.int32), axis=0)        # [N]

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    if crash_on:
        # Volatile reset on recovery (SPEC §6c): view/timer rejoin at 0;
        # the per-slot message log persists (same split as the dense §6
        # kernel — the fault granularity changes, the state split not).
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, pp_seen, pp_view, pp_val, prepared,
                  committed, dval)
    committed_at_start = committed
    # SPEC §B timer-skew injection (same placement as the dense §6
    # kernel): the skewed timer crosses P2's start-of-round timeout
    # before any pre-prepare can reset it. After the frozen capture, so
    # the §6c freeze discards a down node's skew; no-op at rate 0.
    if cfg.desync_on:
        timer = timer + desync_skew(seed, ur, uidx, cfg.desync_cutoff,
                                    cfg.max_skew_rounds)

    # ---- P0 churn.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up: (f+1)-th largest of delivered honest views
    # ∪ own. Senders are side-separable; per side b the K-th and
    # (K-1)-th largest sender views are ORDER STATISTICS of an
    # N-padded multiset (pads below every view, like the retired sort's
    # -1 pads) — a fixed-depth binary search on the bounded view range
    # replaces the former batched [2, N] sort outright (sort-class ops
    # 3 → 1 for the round). The receiver-side insertion is a clamp:
    # inserting own view x into a desc-sorted multiset T makes the K-th
    # largest clip(x, T[K-1], T[K-2]); a receiver that IS a sender
    # replaces its own copy, leaving the multiset unchanged.
    sender_v = honest & bcast
    vmax = view_bound(cfg)
    vplus = view + 1                                   # [1, vmax+1]; 0 = pad
    if no_part:
        w1 = jnp.where(sender_v, vplus, 0)[None, :]              # [1, N]
        if K >= 2:
            stat = _kth_largest(jnp.concatenate([w1, w1]),
                                jnp.asarray([K, K - 1], jnp.int32), vmax)
            a1 = jnp.broadcast_to(stat[0], (N,))                 # [N]
            a2 = jnp.broadcast_to(stat[1], (N,))
        else:
            stat = _kth_largest(w1, jnp.asarray([K], jnp.int32), vmax)
            a1 = jnp.broadcast_to(stat[0], (N,))
            a2 = jnp.full((N,), I32_MAX, jnp.int32)
    else:
        cols = jnp.stack([jnp.where(sender_v & side_ok(0), vplus, 0),
                          jnp.where(sender_v & side_ok(1), vplus, 0)])
        if K >= 2:
            stat = _kth_largest(jnp.concatenate([cols, cols]),
                                jnp.asarray([K, K, K - 1, K - 1],
                                            jnp.int32), vmax)
            a1 = stat[0:2][side]                                 # [N]
            a2 = stat[2:4][side]
        else:
            a1 = _kth_largest(cols, jnp.asarray([K, K], jnp.int32),
                              vmax)[side]
            a2 = jnp.full((N,), I32_MAX, jnp.int32)
    in_set = sender_v                                            # self side ok
    vth = jnp.where(in_set, a1, jnp.clip(view, a1, a2))
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare (one sender per receiver — O(N·S) gathers).
    is_primary = honest & (view % N == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = (sarange[None, :] == fresh[:, None])
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % N
    if no_part:
        prim_del = (prim == idx) | bcast[prim]
    else:
        prim_del = (prim == idx) | (bcast[prim]
                                    & (~part_active | (side[prim] == side)))
    prim_ok = prim_del & (view[prim] == view)
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        # Per-receiver fork (SPEC §7c): the byz primary's stance toward
        # THIS receiver — sup(r, prim(j), j), the dense kernel's
        # sup[prim, idx] — picks which of the two conflicting values it
        # pre-prepares here.
        sup_prim = (_draw(seed, rng.STREAM_EQUIV, ur,
                          prim.astype(jnp.uint32), uidx)
                    & jnp.uint32(1)).astype(bool)                  # [N]
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(sup_prim, 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, prim_del, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4 + P5 tallies. net_model="flat": one payload sort,
    # per-(slot, side) top-M run tables, elementwise delivery
    # (:func:`_aggregate_tallies` — shared with the padded traced-f
    # ladder round). net_model="switch" (SPEC §9): the round's ONE
    # atomic broadcast lands on the sender's aggregator (uplink at the
    # aggregator's effective — possibly STALE — round) and each
    # aggregator combines its segment into (count, vmax, vmin), serving
    # value-uniform segments only; receivers total K pre-aggregated
    # values instead of running the sorted-space machinery at all — the
    # switch round carries ZERO sort-class and ZERO cumsum-class ops
    # (the tightened `pbft-100k-bcast-switch` hlocheck ceiling).
    switch = cfg.switch_on
    if switch:
        from ..ops.aggregate import (agg_ids, agg_poison, agg_round,
                                     downlink, downlink_self, min_id_votes,
                                     seg_widths, uplink_bcast, uplink_lies,
                                     value_votes)
        K_agg = cfg.n_aggregators
        aggst = agg_round(cfg, seed, ur)
        sids = agg_ids(N, K_agg)
        upb = uplink_bcast(cfg, seed, aggst)
        if crash_on:
            upb &= up
        if equiv:
            # The switch DEDUPS per-receiver claims — a vertex holds one
            # uplink claim per sender per round — so equivocating
            # support through an aggregator collapses to the per-ROUND
            # stance (its own STREAM_EQUIV key, disjoint from the
            # sup(r, i, j) grid's receiver ids).
            stance = (_draw(seed, rng.STREAM_EQUIV, ur, uidx,
                            jnp.uint32(0x80000000))
                      & jnp.uint32(1)).astype(bool)
            eq_up = byz & stance & upb
        else:
            eq_up = None
        # SPEC §9b poisoned aggregation (None / static no-op when off);
        # P6's min-id decide gossip stays unpoisonable — the decide
        # message carries the decider's identity (see engines/pbft.py).
        pz4 = agg_poison(cfg, seed, ur, 0)
        pz5 = agg_poison(cfg, seed, ur, 1)
        wid = seg_widths(jnp.ones(N, bool), sids, K_agg) \
            if pz4 is not None else None
        lie, fval = uplink_lies(cfg, seed, ur, byz)
        down0 = downlink(cfg, seed, ur, aggst, 0, idx)
        dn0 = downlink_self(cfg, seed, ur, aggst, 0)
        c4 = value_votes(pp_val, honest[:, None] & pp_seen, upb, down0,
                         dn0, sids, K_agg, eq_up=eq_up,
                         lie=lie, lie_val=fval, poison=pz4, widths=wid)
        pcount = c4 + (honest[:, None] & pp_seen).astype(jnp.int32)
        prep_hit = pp_seen & (pcount >= Q)
        if crash_on:
            prep_hit &= up[:, None]
        prepared2 = prepared | prep_hit
        down1 = downlink(cfg, seed, ur, aggst, 1, idx)
        dn1 = downlink_self(cfg, seed, ur, aggst, 1)
        c5 = (value_votes(pp_val, honest[:, None] & prepared2, upb,
                          down1, dn1, sids, K_agg, eq_up=eq_up,
                          lie=lie, lie_val=fval, poison=pz5, widths=wid)
              + (honest[:, None] & prepared2).astype(jnp.int32))
        commit_now = prepared2 & (c5 >= Q) & ~committed
        if crash_on:
            commit_now &= up[:, None]
    else:
        prep_hit, prepared2, commit_now, c5 = _aggregate_tallies(
            pp_val, pp_seen, prepared, committed, honest, bcast, Q,
            _table_width(N, f, cfg.n_byzantine if equiv else 0),
            side=None if no_part else side,
            part_active=None if no_part else part_active,
            extra=eq_extra if equiv else None,
            up=up if crash_on else None)
    prep_new = prep_hit & ~prepared        # telemetry (DCE'd when off)
    prep_miss = pp_seen & ~prepared & ~prep_hit
    prepared = prepared2
    commit_miss = prepared & ~committed & (c5 < Q)  # telemetry
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now

    # ---- P6 decide gossip: lowest-id broadcasting decider per side
    # (flat) or per aggregator segment (switch — the min/value
    # order-statistic combine, phase 2 downlink).
    if switch:
        down2 = downlink(cfg, seed, ur, aggst, 2, idx)
        dec_sw = honest[:, None] & committed
        imin_sw, vad = min_id_votes(dec_sw, dval, upb, down2, sids,
                                    K_agg, N)
        adopt = (imin_sw < N) & ~committed
        if crash_on:
            adopt &= up[:, None]   # down receivers adopt nothing
        dval = jnp.where(adopt, vad, dval)
        committed = committed | adopt
    else:
        # The decider — hence the adopted value — varies only per
        # (partition side, slot): gather the ≤2 candidate rows (O(S)
        # elements) and select per receiver, NEVER a [N, S]
        # arbitrary-index gather of those same values (that gather ran
        # on the serial unit and was 66% of the 8-sweep 100k program;
        # docs/PERF.md).
        dec = honest[:, None] & bcast[:, None] & committed        # [N, S]
        if no_part:
            src = jnp.where(dec, idx[:, None], N)
            imin_rows = jnp.min(src, axis=0)[None, :]             # [1, S]
            imin = jnp.broadcast_to(imin_rows, (N, S))
        else:
            rows = []
            for b in (0, 1):
                src = jnp.where(dec & side_ok(b)[:, None], idx[:, None], N)
                rows.append(jnp.min(src, axis=0))                 # [S]
            imin_rows = jnp.stack(rows)                           # [2, S]
            imin = imin_rows[side]                                # [N, S]
        adopt = (imin < N) & ~committed
        if crash_on:
            adopt &= up[:, None]  # down receivers adopt nothing (§6c)
        val_rows = dval[jnp.clip(imin_rows, 0, N - 1),
                        sarange[None, :]]                         # [1|2, S]
        vfull = (jnp.broadcast_to(val_rows, (N, S)) if no_part
                 else val_rows[side])
        dval = jnp.where(adopt, vfull, dval)
        committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    if crash_on:
        # SPEC §6c freeze: covers the state the masks above don't reach
        # (a down node's pp_*/view/timer could still move from an up
        # sender's broadcast or local timers).
        (view, timer, pp_seen, pp_view, pp_val, prepared, committed,
         dval) = freeze_down(
            down, frozen, (view, timer, pp_seen, pp_view, pp_val,
                           prepared, committed, dval))

    new = PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                    prepared, committed, dval, down)
    if not telem:
        return new
    cnt = lambda mk: jnp.sum(mk.astype(jnp.int32))  # noqa: E731
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    az = agg_counts(aggst, poison_count(aggst, pz4, pz5)) if switch \
        else agg_counts()
    # SPEC §7c safety invariants — same reductions as the dense kernel
    # (engines/pbft.py): forked commit quorums this round, committed-
    # value conflicts across honest nodes. Static zeros unless a
    # byzantine axis that can violate agreement is on.
    unsafe = equiv or cfg.agg_poison_on or cfg.uplink_lies_on
    if unsafe:
        nw = commit_now & honest[:, None]
        forked = (jnp.any(nw, axis=0)
                  & (jnp.max(jnp.where(nw, pp_val, I32_MIN), axis=0)
                     != jnp.min(jnp.where(nw, pp_val, I32_MAX), axis=0)))
        cm = committed & honest[:, None]
        conflicts = (jnp.any(cm, axis=0)
                     & (jnp.max(jnp.where(cm, dval, I32_MIN), axis=0)
                        != jnp.min(jnp.where(cm, dval, I32_MAX), axis=0)))
        sz = safety_counts(forked, conflicts)
    else:
        sz = safety_counts()
    # view_changes clips at 0 like the dense kernel: a §6c recovery
    # resets the view, and the raw delta would cancel real advances.
    # SPEC §B desync gauges — same reductions as the dense kernel: P1
    # catch-up is pbft's view-sync message.
    syncz = sync_counts(view, honest & ~down, catch)
    vec = jnp.stack([cnt(prep_new), cnt(prep_miss), cnt(commit_now),
                     cnt(commit_miss), cnt(adopt),
                     jnp.sum(jnp.maximum(view - st.view, 0)), *cz, *az,
                     *sz, *syncz])
    if not flight:
        return new, vec
    # Same PBFT_LATENCY semantics as the dense §6 kernel (the fault
    # granularity changes, the measured quantities do not).
    lat = jnp.stack([
        bucket_counts(st.timer + 1, view > st.view),
        bucket_counts(jnp.asarray(r, jnp.int32) - sarange[None, :],
                      commit_now | adopt)])
    return new, vec, lat


def pbft_bcast_round_telem(cfg: Config, st: PbftState, r):
    return pbft_bcast_round(cfg, st, r, telem=True)


def pbft_bcast_round_flight(cfg: Config, st: PbftState, r):
    return pbft_bcast_round(cfg, st, r, telem=True, flight=True)


def _extract(st: PbftState) -> dict:
    return {"committed": st.committed, "dval": st.dval, "view": st.view,
            "prepared": st.prepared, "pp_val": st.pp_val,
            "pp_seen": st.pp_seen}


def _pspec(cfg: Config) -> PbftState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    return PbftState(seed=P(), view=v, timer=v, pp_seen=m, pp_view=m,
                     pp_val=m, prepared=m, committed=m, dval=m, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("pbft-bcast", pbft_init, pbft_bcast_round,
                            _extract, _pspec, telemetry_names=PBFT_TELEMETRY,
                            round_telem=pbft_bcast_round_telem,
                            latency_names=PBFT_LATENCY,
                            round_flight=pbft_bcast_round_flight)
    return _ENGINE
