"""PBFT under the broadcast-atomic fault model (docs/SPEC.md §6b) —
the large-N engine.

The §6 dense kernel (engines/pbft.py) compares values pairwise:
`[i, j, s]` tensors, O(N²·S) — structurally impossible at the north
star's 100k-node scale (BASELINE.json:5 names PBFT in the 100k sweeps).
Under §6b, faults drop a sender's round broadcast atomically, so a
receiver's prepare/commit tally is a pure multiset count over the slot's
sender values, computable in O(N·S·log N):

  * one `lax.sort` per slot over the sender values, carrying an index
    payload (the permutation) plus every per-node flag the tallies
    need, bit-packed into one i32 payload (partitions are
    side-separable, §2 — the side flags ride along too);
  * equal-value run boundaries in sorted order by elementwise compare;
    each value's count of valid same-value senders gather-free from the
    plain monotone cumsum of the validity flags, bracketed at the run
    boundaries by a forward cummax / reverse cummin (builtin cumulative
    ops — see _SortedTally.count). The sorted VALUES are never masked
    to sentinels, so arbitrary 32-bit payloads are safe;
  * both phases' tallies chain elementwise in sorted order and ONE
    unsort (a second payload sort) returns the results (arbitrary-index
    gathers run on the serial gather unit, ~15 ms per [16, 100k] pass
    on v5 lite, so the design uses none; see _SortedTally).

Protocol phases, state, and tie-breaks are §6's verbatim; only fault
granularity changes (SPEC §6b: per-sender drops, unchanged partitions,
per-round equivocation stances). With drop_rate = partition_rate = 0 and
no byzantine nodes this engine is round-for-round identical to the dense
one (tested in tests/test_pbft_bcast.py, along with differential
byte-equivalence vs the oracle's §6b path — cpp/oracle.cpp PbftSim with
fault_bcast = 1, the BcastNet/del/eq_sup dispatch in PbftSim::run).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import crash_counts, crash_transition, freeze_down
from ..ops.adversary import draw as _draw
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import bitcast_i32 as _i32
from .pbft import PBFT_TELEMETRY, PbftState, pbft_init

I32_MAX = jnp.iinfo(jnp.int32).max

# SPEC §6c persistent/volatile carry split — identical to the dense §6
# kernel's (engines/pbft.py: the fault granularity changes, the state
# split does not); declared per-module so tools/lint (check `registry`)
# verifies THIS round's reset/freeze code.
# Compiled-program contract (tools/hlocheck): THE sort-class-bound round
# (docs/PERF.md — carry-bandwidth floor 0.6% of HBM peak, the bytes are
# sort temporaries). 3 sort passes/round compiled today (the two
# _SortedTally payload sorts + the §2 partition-side order statistic);
# the ROADMAP bandwidth-floor item exists to LOWER this number — the
# budget is the ceiling that guarantees it can only go down. No
# node-sharded claim yet: GSPMD currently gathers full [N, S]-class
# operands when the node axis is sharded (measured, hlocheck registry
# notes) — flipping this to "bounded" is the acceptance bar for the
# mesh-scaling refactor.
PROGRAM_CONTRACT = dict(sort_budget=3, cumsum_budget=33, node_sharded=None)

CRASH_SPLIT = {
    "seed": "meta",
    "view": "volatile",
    "timer": "volatile",
    "pp_seen": "persistent",
    "pp_view": "persistent",
    "pp_val": "persistent",
    "prepared": "persistent",
    "committed": "persistent",
    "dval": "persistent",
    "down": "meta",
}


class _SortedTally:
    """Exact multiset counter, entirely in sorted space: count[s, j] =
    |{i : valid[s, i] ∧ vals[s, i] == vals[s, j]}| for arbitrary i32
    values (validity rides the permutation; nothing is masked to a
    sentinel).

    The round is sort-bound at N=100k, so the design minimizes
    sort-class passes AND arbitrary-index gathers: ONE payload sort up
    front carries the per-node flags (a searchsorted — even with the
    sort-based lowering — would be a full extra sort per side, and the
    default binary-search lowering is a 17-step sequential gather loop,
    ~345 ms/call on v5 lite at [16, 100k], whose batched form faults
    the TPU worker); counts are gather-free segmented scans over
    equal-value runs (see count()); and ONE unsort (a second payload
    sort keyed on the permutation) returns all phases' results
    together. Callers unpack their flags from the sorted payload,
    combine counts elementwise there (P4 → P5 chain included), and
    unsort once.
    """

    def __init__(self, vals_sn, bits_sn, extra_sn=None):
        """``bits_sn``: per-(slot, node) i32 bitmask of every flag the
        tally phases need, riding the sort as ONE payload (a [16, 100k]
        arbitrary-index gather costs ~15 ms on v5 lite — 9 of them were
        90% of the round — while an extra sort payload is ~free).
        ``extra_sn``: optional i32 payload (equivocating-byz support)."""
        S, N = vals_sn.shape
        iota = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (S, N))
        ops = (vals_sn, iota, bits_sn) + \
            (() if extra_sn is None else (extra_sn,))
        srt = jax.lax.sort(ops, dimension=1, num_keys=1)
        self.sv, self.perm, self.bits = srt[0], srt[1], srt[2]
        self.extra = srt[3] if extra_sn is not None else None
        brk = self.sv[:, 1:] != self.sv[:, :-1]
        self.newrun = jnp.concatenate([jnp.ones((S, 1), bool), brk], axis=1)
        self.endrun = jnp.concatenate([brk, jnp.ones((S, 1), bool)], axis=1)

    def bit(self, k):
        """Unpack flag k of the packed payload, sorted order [S, N]."""
        return ((self.bits >> k) & 1).astype(bool)

    def count(self, valid_sn_sorted):
        """Per-position count of valid entries in its equal-value run —
        gather-free AND custom-scan-free. The plain (unsegmented)
        inclusive cumsum ``s`` is nondecreasing, so the exclusive value
        at a position's run START is the max of boundary-masked
        ``s - f`` at-or-left of it (forward cummax), and the inclusive
        value at its run END is the min of boundary-masked ``s``
        at-or-right of it (reverse cummin); the difference is the run's
        valid count. Builtin cumsum/cummax/cummin keep the optimized
        TPU lowering — a custom-combine ``lax.associative_scan`` lowers
        to ~17 levels of slice/pad/interleave passes that were ~35% of
        the 100k program."""
        f = valid_sn_sorted.astype(jnp.int32)
        s = jnp.cumsum(f, axis=1)
        ex_start = jax.lax.cummax(jnp.where(self.newrun, s - f, -1), axis=1)
        s_end = jax.lax.cummin(jnp.where(self.endrun, s, jnp.int32(2**30)),
                               axis=1, reverse=True)
        return s_end - ex_start

    def unsort(self, packed_sn):
        """Sorted-order [S, N] i32 payload → original [N, S] order via
        one payload sort keyed on the permutation."""
        _, out = jax.lax.sort((self.perm, packed_sn), dimension=1,
                              num_keys=1)
        return out.T


def pbft_bcast_round(cfg: Config, st: PbftState, r, *, telem: bool = False):
    N, S = cfg.n_nodes, cfg.log_capacity
    f = cfg.f
    Q = 2 * f + 1
    K = f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    sarange = jnp.arange(S, dtype=jnp.int32)

    # ---- SPEC §6b adversary: per-sender broadcast drops + §2 partition.
    # partition_cutoff == 0 is a static config fact: the partition can
    # never activate, every side_ok() is identically true, and the two
    # sides' tallies/sorts/minima are equal — so the no_part branches
    # below compute one of everything instead of two (the 4 per-round
    # multiset counts are ~60% of the round at N=100k). Bit-identical:
    # streams are counter-based, so not drawing `side` changes nothing
    # else. The general path is untouched.
    no_part = cfg.partition_cutoff == 0
    bcast = rng.delivery_u32_jnp(seed, ur, uidx, uidx) >= _lt(cfg.drop_cutoff)
    # SPEC §6c crash-recover adversary: a down node's round broadcasts
    # drop atomically (folded into the per-sender bcast flag — exactly
    # the §6b fault granularity); the receiving side is handled by
    # masking the quorum/adopt events with `up` (the down flag rides
    # the P4/P5 sort payload), so a frozen node also never *counts* a
    # quorum it cannot apply — and then the state freeze below.
    crash_on = cfg.crash_cutoff > 0
    down = st.down
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        bcast = bcast & up
    if not no_part:
        part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                       < _lt(cfg.partition_cutoff))
        side = (_draw(seed, rng.STREAM_PARTITION, ur, 1, uidx)
                & jnp.uint32(1)).astype(jnp.int32)               # [N]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (N - cfg.n_byzantine)
    byz = ~honest

    def side_ok(b):
        return ~part_active | (side == b)

    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        stance = (_draw(seed, rng.STREAM_EQUIV, ur, uidx,
                        jnp.uint32(0x80000000)) & jnp.uint32(1)).astype(bool)

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    if crash_on:
        # Volatile reset on recovery (SPEC §6c): view/timer rejoin at 0;
        # the per-slot message log persists (same split as the dense §6
        # kernel — the fault granularity changes, the state split not).
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, pp_seen, pp_view, pp_val, prepared,
                  committed, dval)
    committed_at_start = committed

    # ---- P0 churn.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up: (f+1)-th largest of delivered honest views
    # ∪ own. Senders are side-separable; per side b take the K-th and
    # (K-1)-th largest sender views (ascending sort, -1 pads — views are
    # always >= 0), then the receiver-side insertion is a clamp:
    # inserting own view x into a desc-sorted multiset T makes the K-th
    # largest clip(x, T[K-1], T[K-2]); a receiver that IS a sender
    # replaces its own copy, leaving the multiset unchanged.
    sender_v = honest & bcast
    # One batched [2, N] sort for both partition sides: 1-D sorts hit a
    # serial TPU path (~64 ms each at N=100k) while batched sorts are
    # near-free; row-wise results are identical.
    if no_part:
        t = jnp.sort(jnp.where(sender_v, view, -1)[None, :], axis=1)
        a1 = jnp.broadcast_to(t[0, N - K], (N,))                 # [N]
        a2 = (jnp.broadcast_to(t[0, N - K + 1], (N,)) if K >= 2
              else jnp.full((N,), I32_MAX, jnp.int32))
    else:
        cols = jnp.stack([jnp.where(sender_v & side_ok(0), view, -1),
                          jnp.where(sender_v & side_ok(1), view, -1)])
        t = jnp.sort(cols, axis=1)                               # ascending
        a1 = t[:, N - K][side]                                   # [N]
        a2 = (t[:, N - K + 1] if K >= 2
              else jnp.full((2,), I32_MAX, jnp.int32))[side]
    in_set = sender_v                                            # self side ok
    vth = jnp.where(in_set, a1, jnp.clip(view, a1, a2))
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare (one sender per receiver — O(N·S) gathers).
    is_primary = honest & (view % N == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = (sarange[None, :] == fresh[:, None])
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % N
    if no_part:
        prim_del = (prim == idx) | bcast[prim]
    else:
        prim_del = (prim == idx) | (bcast[prim]
                                    & (~part_active | (side[prim] == side)))
    prim_ok = prim_del & (view[prim] == view)
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(stance[prim], 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, prim_del, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4 + P5 tallies, entirely in sorted space (one sort carrying
    # every needed flag as a packed payload, one unsort — see
    # _SortedTally). The P4 → P5 dependency (commit votes only count
    # prepared nodes) chains elementwise in sorted order.
    if equiv:
        # Byz support is value-independent (SPEC §6b): one count per
        # side, minus the receiver's own stance (self never travels).
        eq_send = byz & bcast & stance
        if no_part:
            extra = jnp.broadcast_to(jnp.sum(eq_send.astype(jnp.int32)),
                                     (N,))
        else:
            extra = jnp.stack(
                [jnp.sum((eq_send & side_ok(0)).astype(jnp.int32)),
                 jnp.sum((eq_send & side_ok(1)).astype(jnp.int32))
                 ])[side]                                        # [N]
        extra = extra - (eq_send).astype(jnp.int32)
        extra_sn = jnp.broadcast_to(extra[:, None], (N, S)).T
    else:
        extra_sn = None

    def b32(x):
        return x.astype(jnp.int32)

    bits = (b32(pp_seen) | (b32(prepared) << 1) | (b32(committed) << 2)
            | ((b32(honest) | (b32(bcast) << 1))[:, None] << 3))
    if not no_part:
        bits |= ((b32(side) | (b32(side_ok(0)) << 1)
                  | (b32(side_ok(1)) << 2))[:, None] << 5)
    if crash_on:
        bits |= b32(up)[:, None] << 8
    tal = _SortedTally(pp_val.T, bits.T, extra_sn)
    pp_seen_s, prepared_s, committed_s = tal.bit(0), tal.bit(1), tal.bit(2)
    honest_s, bcast_s = tal.bit(3), tal.bit(4)
    hb_s = honest_s & bcast_s
    extra_s = jnp.int32(0) if tal.extra is None else tal.extra

    def counts_for_s(relevant_s):
        """Value-matched §6b count incl. self (SPEC §6 P4/P5), sorted
        order: sorted-count of broadcasting senders + the self vote
        (which never travels, so it counts regardless of bcast fate)."""
        if no_part:
            cnt = tal.count(hb_s & relevant_s)
        else:
            c0 = tal.count(hb_s & tal.bit(6) & relevant_s)
            c1 = tal.count(hb_s & tal.bit(7) & relevant_s)
            cnt = jnp.where(tal.bit(5), c1, c0)
        self_adj = (honest_s & relevant_s & ~bcast_s).astype(jnp.int32)
        return cnt + self_adj + extra_s

    # ---- P4 prepare tally. (Telemetry masks are computed in SORTED
    # order — their jnp.sum totals are permutation-invariant, so no
    # extra unsort payload is ever needed for them.)
    c4 = counts_for_s(pp_seen_s)
    prep_hit_s = pp_seen_s & (c4 >= Q)
    if crash_on:
        # A down receiver can neither prepare nor commit (SPEC §6c) —
        # masked here, not just frozen, so the telemetry counters below
        # never report a quorum the trajectory didn't take.
        prep_hit_s &= tal.bit(8)
    prep_new_s = prep_hit_s & ~prepared_s       # telemetry (DCE'd when off)
    prep_miss_s = pp_seen_s & ~prepared_s & ~prep_hit_s
    prepared2_s = prepared_s | prep_hit_s

    # ---- P5 commit tally.
    c5 = counts_for_s(prepared2_s)
    commit_now_s = prepared2_s & (c5 >= Q) & ~committed_s
    if crash_on:
        commit_now_s &= tal.bit(8)
    commit_miss_s = prepared2_s & ~committed_s & (c5 < Q)  # telemetry

    packed = tal.unsort(b32(prepared2_s) | (b32(commit_now_s) << 1))
    prepared = (packed & 1).astype(bool)
    commit_now = (packed >> 1).astype(bool)
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now

    # ---- P6 decide gossip: lowest-id broadcasting decider per side.
    # The decider — hence the adopted value — varies only per
    # (partition side, slot): gather the ≤2 candidate rows (O(S)
    # elements) and select per receiver, NEVER a [N, S] arbitrary-index
    # gather of those same values (that gather ran on the serial unit
    # and was 66% of the 8-sweep 100k program; docs/PERF.md).
    dec = honest[:, None] & bcast[:, None] & committed            # [N, S]
    if no_part:
        src = jnp.where(dec, idx[:, None], N)
        imin_rows = jnp.min(src, axis=0)[None, :]                 # [1, S]
        imin = jnp.broadcast_to(imin_rows, (N, S))
    else:
        rows = []
        for b in (0, 1):
            src = jnp.where(dec & side_ok(b)[:, None], idx[:, None], N)
            rows.append(jnp.min(src, axis=0))                     # [S]
        imin_rows = jnp.stack(rows)                               # [2, S]
        imin = imin_rows[side]                                    # [N, S]
    adopt = (imin < N) & ~committed
    if crash_on:
        adopt &= up[:, None]   # down receivers adopt nothing (SPEC §6c)
    val_rows = dval[jnp.clip(imin_rows, 0, N - 1),
                    sarange[None, :]]                             # [1|2, S]
    vfull = (jnp.broadcast_to(val_rows, (N, S)) if no_part
             else val_rows[side])
    dval = jnp.where(adopt, vfull, dval)
    committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    if crash_on:
        # SPEC §6c freeze: covers the state the masks above don't reach
        # (a down node's pp_*/view/timer could still move from an up
        # sender's broadcast or local timers).
        (view, timer, pp_seen, pp_view, pp_val, prepared, committed,
         dval) = freeze_down(
            down, frozen, (view, timer, pp_seen, pp_view, pp_val,
                           prepared, committed, dval))

    new = PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                    prepared, committed, dval, down)
    if not telem:
        return new
    cnt = lambda m: jnp.sum(m.astype(jnp.int32))  # noqa: E731
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    # view_changes clips at 0 like the dense kernel: a §6c recovery
    # resets the view, and the raw delta would cancel real advances.
    vec = jnp.stack([cnt(prep_new_s), cnt(prep_miss_s), cnt(commit_now_s),
                     cnt(commit_miss_s), cnt(adopt),
                     jnp.sum(jnp.maximum(view - st.view, 0)), *cz])
    return new, vec


def pbft_bcast_round_telem(cfg: Config, st: PbftState, r):
    return pbft_bcast_round(cfg, st, r, telem=True)


def _extract(st: PbftState) -> dict:
    return {"committed": st.committed, "dval": st.dval, "view": st.view,
            "prepared": st.prepared, "pp_val": st.pp_val,
            "pp_seen": st.pp_seen}


def _pspec(cfg: Config) -> PbftState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    return PbftState(seed=P(), view=v, timer=v, pp_seen=m, pp_view=m,
                     pp_val=m, prepared=m, committed=m, dval=m, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("pbft-bcast", pbft_init, pbft_bcast_round,
                            _extract, _pspec, telemetry_names=PBFT_TELEMETRY,
                            round_telem=pbft_bcast_round_telem)
    return _ENGINE
