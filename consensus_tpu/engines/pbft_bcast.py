"""PBFT under the broadcast-atomic fault model (docs/SPEC.md §6b) —
the large-N engine.

The §6 dense kernel (engines/pbft.py) compares values pairwise:
`[i, j, s]` tensors, O(N²·S) — structurally impossible at the north
star's 100k-node scale (BASELINE.json:5 names PBFT in the 100k sweeps).
Under §6b, faults drop a sender's round broadcast atomically, so a
receiver's prepare/commit tally is a pure multiset count over the slot's
sender values, computable in O(N·S·log N):

  * one `lax.sort` per slot over the sender values, carrying the two
    per-partition-side validity flags as payload;
  * inclusive→exclusive cumulative sums of each flag over the sorted
    order (partitions are side-separable, §2);
  * per receiver, `searchsorted` left/right brackets its own value's
    run; the cumsum difference of its side's flag is the exact count —
    no sentinel values, so arbitrary 32-bit payloads are safe.

Protocol phases, state, and tie-breaks are §6's verbatim; only fault
granularity changes (SPEC §6b: per-sender drops, unchanged partitions,
per-round equivocation stances). With drop_rate = partition_rate = 0 and
no byzantine nodes this engine is round-for-round identical to the dense
one (tested in tests/test_pbft_bcast.py, along with differential
byte-equivalence vs the oracle's §6b path — cpp/oracle.cpp PbftSim with
fault_bcast = 1, the BcastNet/del/eq_sup dispatch in PbftSim::run).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.config import Config
from ..ops.adversary import draw as _draw
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import bitcast_i32 as _i32
from .pbft import PbftState, pbft_init

I32_MAX = jnp.iinfo(jnp.int32).max


class _SortedCounter:
    """Exact multiset counter: count_b[s, j] = |{i : valid_b[s, i] ∧
    vals[s, i] == query[s, j]}| for arbitrary i32 values (validity rides
    a permutation; nothing is masked to a sentinel).

    The O(N·S·log N) sort and both searchsorted brackets depend only on
    (vals, query), so they run ONCE per round and serve both the P4 and
    P5 tallies — only the per-phase validity gather/cumsum differs.
    """

    def __init__(self, vals_sn, query_sn):
        S, N = vals_sn.shape
        iota = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (S, N))
        self.sv, self.perm = jax.lax.sort((vals_sn, iota), dimension=1,
                                          num_keys=1)

        def one_slot(sorted_v, q):
            # method="sort" is the only TPU-viable lowering at N=100k:
            # the default binary-search method is a 17-step sequential
            # gather loop (~345 ms/call measured on v5 lite at [16,100k]);
            # the sort-based lowering rides the fast batched sort unit
            # (<1 ms). Same results, bit-for-bit.
            return (jnp.searchsorted(sorted_v, q, side="left", method="sort"),
                    jnp.searchsorted(sorted_v, q, side="right", method="sort"))

        self.lo, self.hi = jax.vmap(one_slot)(self.sv, query_sn)

    def count(self, valid_sn):
        f = jnp.take_along_axis(valid_sn.astype(jnp.int32), self.perm, axis=1)
        zero = jnp.zeros(f.shape[:-1] + (1,), jnp.int32)
        ex = jnp.concatenate([zero, jnp.cumsum(f, axis=1)], axis=1)  # [S,N+1]
        return (jnp.take_along_axis(ex, self.hi, axis=1)
                - jnp.take_along_axis(ex, self.lo, axis=1))


def pbft_bcast_round(cfg: Config, st: PbftState, r) -> PbftState:
    N, S = cfg.n_nodes, cfg.log_capacity
    f = cfg.f
    Q = 2 * f + 1
    K = f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    sarange = jnp.arange(S, dtype=jnp.int32)

    # ---- SPEC §6b adversary: per-sender broadcast drops + §2 partition.
    bcast = rng.delivery_u32_jnp(seed, ur, uidx, uidx) >= _lt(cfg.drop_cutoff)
    part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                   < _lt(cfg.partition_cutoff))
    side = (_draw(seed, rng.STREAM_PARTITION, ur, 1, uidx)
            & jnp.uint32(1)).astype(jnp.int32)                   # [N]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (N - cfg.n_byzantine)
    byz = ~honest

    def side_ok(b):
        return ~part_active | (side == b)

    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    if equiv:
        stance = (_draw(seed, rng.STREAM_EQUIV, ur, uidx,
                        jnp.uint32(0x80000000)) & jnp.uint32(1)).astype(bool)

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    committed_at_start = committed

    # ---- P0 churn.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up: (f+1)-th largest of delivered honest views
    # ∪ own. Senders are side-separable; per side b take the K-th and
    # (K-1)-th largest sender views (ascending sort, -1 pads — views are
    # always >= 0), then the receiver-side insertion is a clamp:
    # inserting own view x into a desc-sorted multiset T makes the K-th
    # largest clip(x, T[K-1], T[K-2]); a receiver that IS a sender
    # replaces its own copy, leaving the multiset unchanged.
    sender_v = honest & bcast
    # One batched [2, N] sort for both partition sides: 1-D sorts hit a
    # serial TPU path (~64 ms each at N=100k) while batched sorts are
    # near-free; row-wise results are identical.
    cols = jnp.stack([jnp.where(sender_v & side_ok(0), view, -1),
                      jnp.where(sender_v & side_ok(1), view, -1)])
    t = jnp.sort(cols, axis=1)                                   # ascending
    a1 = t[:, N - K][side]                                       # [N]
    a2 = (t[:, N - K + 1] if K >= 2
          else jnp.full((2,), I32_MAX, jnp.int32))[side]
    in_set = sender_v                                            # self side ok
    vth = jnp.where(in_set, a1, jnp.clip(view, a1, a2))
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare (one sender per receiver — O(N·S) gathers).
    is_primary = honest & (view % N == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = (sarange[None, :] == fresh[:, None])
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % N
    prim_del = (prim == idx) | (bcast[prim]
                                & (~part_active | (side[prim] == side)))
    prim_ok = prim_del & (view[prim] == view)
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(stance[prim], 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, prim_del, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # Shared [S, N] views of the tally inputs; one sort serves P4 + P5.
    vals_sn = pp_val.T
    counter = _SortedCounter(vals_sn, vals_sn)

    if equiv:
        # Byz support is value-independent (SPEC §6b): one count per
        # side, minus the receiver's own stance (self never travels).
        eq_send = byz & bcast & stance
        extra = jnp.stack([jnp.sum((eq_send & side_ok(0)).astype(jnp.int32)),
                           jnp.sum((eq_send & side_ok(1)).astype(jnp.int32))
                           ])[side]                              # [N]
        extra = extra - (eq_send).astype(jnp.int32)
        extra = extra[:, None]
    else:
        extra = jnp.zeros((N, 1), jnp.int32)

    def counts_for(relevant_ns):
        """Value-matched §6b count[j, s] incl. self (SPEC §6 P4/P5):
        sorted-count of broadcasting senders + the self vote (which
        never travels, so it counts regardless of bcast fate)."""
        c0 = counter.count((honest & bcast & side_ok(0))[None, :]
                           & relevant_ns.T)
        c1 = counter.count((honest & bcast & side_ok(1))[None, :]
                           & relevant_ns.T)
        cnt = jnp.where((side == 0)[None, :], c0, c1).T           # [N, S]
        self_adj = (honest[:, None] & relevant_ns
                    & ~bcast[:, None]).astype(jnp.int32)
        return cnt + self_adj + extra

    # ---- P4 prepare tally.
    pcount = counts_for(pp_seen)
    prepared = prepared | (pp_seen & (pcount >= Q))

    # ---- P5 commit tally.
    ccount = counts_for(prepared)
    commit_now = prepared & (ccount >= Q) & ~committed
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now

    # ---- P6 decide gossip: lowest-id broadcasting decider per side.
    dec = honest[:, None] & bcast[:, None] & committed            # [N, S]
    imin = []
    for b in (0, 1):
        src = jnp.where(dec & side_ok(b)[:, None], idx[:, None], N)
        imin.append(jnp.min(src, axis=0))                         # [S]
    imin = jnp.stack(imin)[side]                                  # [N, S]
    adopt = (imin < N) & ~committed
    dval = jnp.where(adopt, dval[jnp.clip(imin, 0, N - 1),
                                 sarange[None, :]], dval)
    committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    return PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                     prepared, committed, dval)


def _extract(st: PbftState) -> dict:
    return {"committed": st.committed, "dval": st.dval, "view": st.view,
            "prepared": st.prepared, "pp_val": st.pp_val,
            "pp_seen": st.pp_seen}


def _pspec(cfg: Config) -> PbftState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    return PbftState(seed=P(), view=v, timer=v, pp_seen=m, pp_view=m,
                     pp_val=m, prepared=m, committed=m, dval=m)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("pbft-bcast", pbft_init, pbft_bcast_round,
                            _extract, _pspec)
    return _ENGINE
