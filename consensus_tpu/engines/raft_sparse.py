"""Large-population Raft: O(A*N) per round instead of O(N^2) (SPEC §3b).

The dense kernel (engines/raft.py) carries `[N, N]` match/next state and a
full `[N, N]` delivery mask — 40 GB each at the north star's 100k-node
scale (BASELINE.json:5), which no chip holds. This engine is the TPU
answer to SURVEY.md §7's "hard parts" (never materialize full N^2):

  * **Active-sender cap** `A = cfg.max_active`: per round, only the top-A
    candidates and top-A leaders — ranked by (term desc, id asc) — send
    messages. Suppressing a sender is indistinguishable from the network
    dropping its messages, which Raft tolerates by design, so safety is
    untouched; with randomized timeouts the concurrent-sender count
    rarely approaches even a small A.
  * **Leader slots**: replication bookkeeping (`match/next`) lives in A
    rows of `[A, N]`, owned by the currently tracked leaders. A leader
    keeps its rows while continuously tracked; on (re-)entry its rows are
    re-initialized exactly as at election (match = 0 except self,
    next = log_len + 1).
  * **Edge-wise delivery** (ops/adversary.delivery_edges): draws evaluated
    only for the O(A*N) live edges, byte-identical to the dense mask's
    entries because every draw is keyed by absolute (round, src, dst) ids.

When the concurrent candidate/leader count never exceeds A, this engine's
decided logs are bit-identical to the dense engine's (tested in
tests/test_raft_sparse.py); the capped semantics are mirrored scalar-for-
scalar in the C++ oracle (cpp/oracle.cpp RaftSim with max_active > 0).

Memory at N=100k, L=128, A=8: ~113 MB per sweep instance (logs dominate)
vs ~90 GB dense — see docs/SCALE.md for the full budget.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import rng
from ..core.config import Config
from ..ops.adversary import bitcast_i32 as _i32
from ..ops.adversary import crash_counts, crash_transition
from ..ops.adversary import delivery_edges as _edges
from ..ops.adversary import draw as _draw
from ..ops.adversary import cutoff as _lt
from ..ops.adversary import freeze_down as _freeze
from ..ops.aggregate import agg_counts
from ..ops.flight import bucket_counts
from .raft import (NONE, RAFT_LATENCY, RAFT_TELEMETRY, ROLE_C, ROLE_F,
                   ROLE_L, _draw_timeout, _last_term, _match_dtype, _pick1,
                   _pick_row)


def _rows_from_small(small, rsel):
    """``small[rsel]`` for a [A, L] table with STATIC tiny A: an A-deep
    fused select chain instead of a row gather. The gather writes the
    [N, L] result at ~87 GB/s on v5 lite (it was 45% of the capped
    flagship round); the select chain re-reads only the [A, L] table
    per output tile and writes at full bandwidth. Falls back to the
    gather when A is large enough that an A-deep chain stops being a
    single fused pass."""
    A = small.shape[0]
    if A > 16:
        return small[rsel]
    out = jnp.broadcast_to(small[0][None, :],
                           (rsel.shape[0], small.shape[1]))
    for k in range(1, A):
        out = jnp.where((rsel == k)[:, None], small[k][None, :], out)
    return out

I32_MAX = jnp.iinfo(jnp.int32).max


class RaftSparseState(NamedTuple):
    seed: jnp.ndarray        # [] uint32
    term: jnp.ndarray        # [N] i32
    role: jnp.ndarray        # [N] i32
    voted_for: jnp.ndarray   # [N] i32
    log_term: jnp.ndarray    # [N, L] i32
    log_val: jnp.ndarray     # [N, L] i32
    log_len: jnp.ndarray     # [N] i32
    commit: jnp.ndarray      # [N] i32
    timer: jnp.ndarray       # [N] i32
    timeout: jnp.ndarray     # [N] i32
    lead_id: jnp.ndarray     # [A] i32 — tracked leader ids, NONE when empty
    lead_match: jnp.ndarray  # [A, N] _match_dtype(L)
    lead_next: jnp.ndarray   # [A, N] _match_dtype(L)
    down: jnp.ndarray        # [N] bool — SPEC §6c crashed mask


# SPEC §6c persistent/volatile carry split (tools/lint check `registry`;
# same semantics as the dense kernel's — see engines/raft.py). The
# tracked-leader slots are "meta": they are not per-node protocol state
# but a cache keyed by lead_id, whose lifecycle re-initializes rows at
# (re-)election and never tracks a down node, so recovery resets and
# the down-freeze both bypass them by construction.
# Compiled-program contract (tools/hlocheck): 2 sorts/round (the §3b
# tracked-set maintenance) is the ceiling; the round is scan-free (the
# former cumsum count of 30 was plain-reduction cascades, reclassified
# by tools/hlocheck/hlo.py `_scan_window`). node_sharded="strict" is
# the repo's multi-chip claim
# (ROADMAP, tests/test_mesh_collectives.py): under node sharding the
# round stays in the all-reduce family at the canonical shape and every
# collective is O(N) metadata at flagship N — never the [N, L] carry.
PROGRAM_CONTRACT = dict(sort_budget=2, cumsum_budget=0,
                        node_sharded="strict")

CRASH_SPLIT = {
    "seed": "meta",
    "term": "persistent",
    "role": "volatile",
    "voted_for": "persistent",
    "log_term": "persistent",
    "log_val": "persistent",
    "log_len": "persistent",
    "commit": "persistent",
    "timer": "volatile",
    "timeout": "persistent",
    "lead_id": "meta",
    "lead_match": "meta",
    "lead_next": "meta",
    "down": "meta",
}


def raft_sparse_init(cfg: Config, seed) -> RaftSparseState:
    N, L, A = cfg.n_nodes, cfg.log_capacity, cfg.max_active
    seed = jnp.asarray(seed, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    z = jnp.zeros(N, jnp.int32)
    return RaftSparseState(
        seed=seed, term=z, role=z, voted_for=jnp.full(N, NONE, jnp.int32),
        log_term=jnp.zeros((N, L), jnp.int32),
        log_val=jnp.zeros((N, L), jnp.int32),
        log_len=z, commit=z, timer=z,
        timeout=_draw_timeout(seed, cfg.t_min, cfg.t_max, z,
                              idx.astype(jnp.uint32)),
        lead_id=jnp.full(A, NONE, jnp.int32),
        lead_match=jnp.zeros((A, N), _match_dtype(L)),
        lead_next=jnp.ones((A, N), _match_dtype(L)),
        down=jnp.zeros(N, bool),
    )


def _top_active(mask, term, idx, A: int):
    """Ids of the top-A ``mask`` nodes by (term desc, id asc); NONE-padded.

    The tie-break is lexicographic `lax.sort` on (-term, id): suppressed
    (non-mask) lanes sort last via an INT32_MAX key.
    """
    neg = jnp.where(mask, -term, I32_MAX)
    key_sorted, ids_sorted = jax.lax.sort((neg, idx), num_keys=2)
    return jnp.where(key_sorted[:A] != I32_MAX, ids_sorted[:A], NONE)


def raft_sparse_round(cfg: Config, st: RaftSparseState, r, *,
                      telem: bool = False, flight: bool = False):
    """One SPEC §3 round under the §3b active-sender cap. Mirrors the dense
    kernel phase by phase; every dense [N, N] object becomes [A, N]/[N, A].
    ``telem=True`` additionally returns the shared :data:`RAFT_TELEMETRY`
    counter vector (same semantics as the dense kernel's — elections are
    counted over the tracked candidate set, which under the §3b cap is
    the only set that can win); ``flight=True`` adds the shared
    :data:`RAFT_LATENCY` bucket matrix (winner waits read off the
    tracked candidate slots)."""
    N, L, A = cfg.n_nodes, cfg.log_capacity, cfg.max_active
    E = min(cfg.max_entries, L)
    majority = N // 2 + 1
    mdt = _match_dtype(L)
    seed = st.seed
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    karange = jnp.arange(L, dtype=jnp.int32)[None, :]

    crash_on = cfg.crash_on

    # SPEC §A.3 targeted attacks — same semantics as the dense kernel
    # (attack == "none" is a static no-op). The sticky mask is defined
    # on the START-of-round role; the elect jam (defined after P1, when
    # cand_new exists) masks only the P2 election edges at their call
    # sites.
    elect_on = cfg.attack == "elect"
    sticky_on = cfg.attack == "sticky"
    if elect_on or sticky_on:
        from ..ops.adversary import attack_fires
        atk = attack_fires(seed, ur, cfg.attack_cutoff)
    if sticky_on:
        tgt = cfg.attack_target
        sticky_act = atk & (st.role[tgt] == ROLE_L)

    def dedge(src, dst):
        m = _edges(seed, ur, src, dst, cfg.drop_cutoff, cfg.partition_cutoff,
                   cfg.max_delay_rounds)
        if crash_on:  # SPEC §6c: down nodes neither send nor receive
            s = jnp.clip(jnp.asarray(src, jnp.int32), 0, N - 1)
            d = jnp.clip(jnp.asarray(dst, jnp.int32), 0, N - 1)
            m = m & up[s] & up[d]
        if sticky_on:  # SPEC §A.3: inbound to the sticky leader jammed
            m = m & ~(sticky_act & (jnp.asarray(dst, jnp.int32) == tgt))
        return m

    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    # SPEC §3c byzantine minority — same masks as the dense kernel.
    honest = idx < (N - cfg.n_byzantine)
    withhold = cfg.n_byzantine > 0 and cfg.byz_mode == "silent"
    double_grant = cfg.n_byzantine > 0 and cfg.byz_mode == "equivocate"

    term, role, voted_for = st.term, st.role, st.voted_for
    log_term, log_val, log_len = st.log_term, st.log_val, st.log_len
    commit, timer, timeout = st.commit, st.timer, st.timeout
    lead_id, lead_match, lead_next = st.lead_id, st.lead_match, st.lead_next
    down = st.down

    # SPEC §6c crash-recover adversary — same semantics as the dense
    # kernel: volatile reset on recovery (role/timer; the tracked-leader
    # slot lifecycle below re-inits replication rows at re-election),
    # delivery masked via dedge(), per-node state frozen while down.
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        role = jnp.where(rec, ROLE_F, role)
        timer = jnp.where(rec, 0, timer)
        frozen = (term, role, voted_for, log_term, log_val, log_len,
                  commit, timer, timeout)

    def bump(cond, new_term, term, role, voted_for, timeout):
        term2 = jnp.where(cond, new_term, term)
        role2 = jnp.where(cond, ROLE_F, role)
        vf2 = jnp.where(cond, NONE, voted_for)
        to2 = jnp.where(cond, _draw_timeout(seed, cfg.t_min, cfg.t_max,
                                            term2, uidx), timeout)
        return term2, role2, vf2, to2

    # ---- P0 churn.
    stepdown = churn & (role == ROLE_L)
    if sticky_on:
        stepdown = stepdown & ~(sticky_act & (idx == tgt))
    role = jnp.where(stepdown, ROLE_F, role)
    timer = jnp.where(stepdown, 0, timer)
    reset = stepdown

    # ---- P1 candidacy.
    cand_new = (role != ROLE_L) & (timer >= timeout)
    term = term + cand_new.astype(jnp.int32)
    role = jnp.where(cand_new, ROLE_C, role)
    voted_for = jnp.where(cand_new, idx, voted_for)
    timer = jnp.where(cand_new, 0, timer)
    reset |= cand_new
    timeout = jnp.where(cand_new,
                        _draw_timeout(seed, cfg.t_min, cfg.t_max, term, uidx),
                        timeout)

    # ---- P2 election over the active candidate set (SPEC §3b).
    cand_mask = role == ROLE_C
    if withhold:
        cand_mask &= honest  # byz candidates never broadcast (SPEC §3c)
    if crash_on:
        cand_mask &= up      # down candidates send nothing (SPEC §6c)
    cand_ids = _top_active(cand_mask, term, idx, A)            # [A]
    cvalid = cand_ids >= 0
    cid = jnp.clip(cand_ids, 0, N - 1)
    req_term = jnp.where(cvalid, term[cid], 0)
    req_lidx = log_len[cid]
    req_lterm = _last_term(log_term[cid], log_len[cid])
    del_cj = dedge(cand_ids[:, None], idx[None, :])            # [A, N]
    if elect_on:
        # SPEC §A.3 "elect": jam ALL round-r election traffic in any
        # attacked round where a candidacy fired in P1. Only LIVE
        # candidacies count under §6c — a down node's frozen expired
        # timer re-fires cand_new every round, but the freeze reverts
        # the candidacy, so it must not keep the jammer firing.
        live_cand = cand_new & up if crash_on else cand_new
        jam = atk & jnp.any(live_cand)
        del_cj = del_cj & ~jam

    # P2a term catch-up.
    t_in = jnp.max(jnp.where(del_cj, req_term[:, None], 0), axis=0)
    bumped = t_in > term
    term, role, voted_for, timeout = bump(bumped, t_in, term, role,
                                          voted_for, timeout)

    # P2b grants. elig[k, j]: active candidate k's request grantable at j.
    own_lterm = _last_term(log_term, log_len)
    up_to_date = (req_lterm[:, None] > own_lterm[None, :]) | (
        (req_lterm[:, None] == own_lterm[None, :])
        & (req_lidx[:, None] >= log_len[None, :]))
    elig = del_cj & (req_term[:, None] == term[None, :]) & up_to_date
    vmatch = cand_ids[:, None] == voted_for[None, :]           # [A, N]
    vf_elig = jnp.any(vmatch & elig, axis=0)
    first_elig = jnp.min(jnp.where(elig, cid[:, None], N), axis=0)
    grant = jnp.where(
        vf_elig, voted_for,
        jnp.where((voted_for == NONE) & (first_elig < N), first_elig, NONE))
    granted = grant >= 0
    voted_for = jnp.where(granted, grant, voted_for)
    timer = jnp.where(granted, 0, timer)
    reset |= granted

    # P2c tally per active candidate; winners become leaders. Under
    # net_model="switch" (SPEC §9) the responses route through the K
    # aggregators — segment-summed per candidate, then combined over
    # the delivered aggregator set (same factorized two-hop as the
    # dense kernel; the request legs stay flat).
    switch = cfg.switch_on
    if switch:
        from ..ops.aggregate import (agg_ids, agg_round, downlink,
                                     seg_sum, uplink_edge)
        aggst = agg_round(cfg, seed, ur)
        sids = agg_ids(N, cfg.n_aggregators)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            up0 &= up
        not_self = idx[:, None] != cand_ids[None, :]
        contrib = (grant[:, None] == cand_ids[None, :]) \
            & cvalid[None, :] & not_self
        if withhold:
            contrib &= honest[:, None]
        if double_grant:
            byz_votes = (~honest)[:, None] & cvalid[None, :] \
                & del_cj.T & not_self
            contrib = jnp.where((~honest)[:, None], byz_votes, contrib)
        seg = seg_sum((contrib & up0[:, None]).astype(jnp.int32), sids,
                      cfg.n_aggregators)                       # [K, A]
        down0 = downlink(cfg, seed, ur, aggst, 0, cand_ids)    # [K, A]
        if crash_on:
            down0 &= up[cid][None, :]
        votes_in = jnp.sum(jnp.where(down0, seg, 0), axis=0)
        if elect_on:
            votes_in = jnp.where(jam, 0, votes_in)
        if sticky_on:
            votes_in = jnp.where(sticky_act & (cand_ids == tgt), 0,
                                 votes_in)
        votes = 1 + votes_in                                   # [A]
    else:
        del_jc = dedge(idx[:, None], cand_ids[None, :])        # [N, A]
        if elect_on:
            del_jc = del_jc & ~jam
        resp = (grant[:, None] == cand_ids[None, :]) & del_jc
        if withhold:
            resp &= honest[:, None]
        if double_grant:
            byz_votes = (~honest)[:, None] & cvalid[None, :] \
                & del_cj.T & del_jc
            resp = jnp.where((~honest)[:, None], byz_votes, resp)
        votes = 1 + jnp.sum(resp, axis=0, dtype=jnp.int32)     # [A]
    win = cvalid & (role[cid] == ROLE_C) & (votes >= majority)
    win_id = jnp.where(win, cid, N)                            # N ⇒ dropped
    role = role.at[win_id].set(ROLE_L, mode="drop")
    timer = timer.at[win_id].set(0, mode="drop")
    reset = reset.at[win_id].set(True, mode="drop")

    # ---- Tracked-leader slot lifecycle (SPEC §3b): rows follow ids;
    # entries (new winners or re-entries) get fresh election-time rows.
    # Down leaders are untracked (they replicate nothing while crashed;
    # on recovery they rejoin as followers — SPEC §6c).
    lead_track = role == ROLE_L
    if crash_on:
        lead_track &= up
    new_ids = _top_active(lead_track, term, idx, A)            # [A]
    same = new_ids[:, None] == jnp.where(lead_id[None, :] >= 0,
                                         lead_id[None, :], N + 1)  # [A, A]
    carried = jnp.any(same, axis=1) & (new_ids >= 0)
    src_slot = jnp.argmax(same, axis=1)
    nid = jnp.clip(new_ids, 0, N - 1)
    init_match = jnp.where(idx[None, :] == nid[:, None],
                           log_len[nid][:, None], 0).astype(mdt)  # [A, N]
    init_next = ((log_len[nid][:, None] + 1)
                 * jnp.ones((A, N), jnp.int32)).astype(mdt)
    lead_match = jnp.where(carried[:, None], lead_match[src_slot], init_match)
    lead_next = jnp.where(carried[:, None], lead_next[src_slot], init_next)
    lead_id = new_ids
    lvalid = lead_id >= 0
    lid = jnp.clip(lead_id, 0, N - 1)

    # ---- P3a propose (every leader, tracked or not — local append only).
    lead = role == ROLE_L
    can_prop = lead & (log_len < E)
    slot_hot = (karange == log_len[:, None]) & can_prop[:, None]
    prop_val = _i32(_draw(seed, rng.STREAM_VALUE, ur, 0, uidx))
    log_term = jnp.where(slot_hot, term[:, None], log_term)
    log_val = jnp.where(slot_hot, prop_val[:, None], log_val)
    log_len = log_len + can_prop.astype(jnp.int32)
    # Tracked leaders' self-match follows their own append.
    self_pos = jnp.where(lvalid & can_prop[lid], lid, N)
    lead_match = lead_match.at[jnp.arange(A, dtype=jnp.int32), self_pos].set(
        log_len[lid].astype(mdt), mode="drop")

    # ---- P3b snapshot tracked-sender state.
    was_lead_k = lvalid & lead[lid]
    if withhold:
        was_lead_k &= honest[lid]  # byz heartbeats never travel
    s_term, s_len, s_commit = term[lid], log_len[lid], commit[lid]
    s_next = lead_next
    s_logt, s_logv = log_term[lid], log_val[lid]               # [A, L]

    # ---- P3c receivers.
    del_lj = dedge(jnp.where(was_lead_k, lead_id, NONE)[:, None],
                   idx[None, :])                               # [A, N]
    t_in2 = jnp.max(jnp.where(del_lj, s_term[:, None], 0), axis=0)
    bumped2 = t_in2 > term
    term, role, voted_for, timeout = bump(bumped2, t_in2, term, role,
                                          voted_for, timeout)

    valid = del_lj & (s_term[:, None] == term[None, :])        # [A, N]
    lstar = jnp.min(jnp.where(valid, lid[:, None], N), axis=0)  # [N] node id
    has_l = lstar < N
    kstar = jnp.argmin(jnp.where(valid, lid[:, None], N), axis=0)  # [N] slot

    timer = jnp.where(has_l, 0, timer)
    reset |= has_l
    role = jnp.where(has_l & (role == ROLE_C), ROLE_F, role)

    prev = _pick_row(s_next, kstar) - 1                        # [N] (i32: u8 can't go -1)
    lrow_t = _rows_from_small(s_logt, kstar)                   # [N, L]
    lrow_v = _rows_from_small(s_logv, kstar)
    kprev = jnp.clip(prev - 1, 0, L - 1)
    prev_term_l = jnp.where(prev > 0, _pick1(lrow_t, kprev), 0)
    own_at_prev = jnp.where((prev > 0) & (prev <= log_len),
                            _pick1(log_term, kprev), 0)
    ok = (prev == 0) | ((prev <= log_len) & (own_at_prev == prev_term_l))
    apply_ = has_l & ok
    append_rej = has_l & ~ok  # telemetry; DCE'd when telem is off

    l_len = _pick_row(s_len, kstar)
    copy_mask = apply_[:, None] & (karange >= prev[:, None]) \
        & (karange < l_len[:, None])
    log_term = jnp.where(copy_mask, lrow_t, log_term)
    log_val = jnp.where(copy_mask, lrow_v, log_val)
    log_len = jnp.where(apply_, l_len, log_len)
    commit = jnp.where(
        apply_,
        jnp.maximum(commit, jnp.minimum(_pick_row(s_commit, kstar), log_len)),
        commit)
    ack_slot = jnp.where(has_l, kstar, A)                      # A ⇒ no ack
    ack_ok = apply_
    ack_match = jnp.where(apply_, l_len, 0)
    ack_term = term

    # ---- P3d tracked leaders process acks.
    still_lead_k = was_lead_k & (role[lid] == ROLE_L)
    del_jl = dedge(idx[:, None], jnp.where(was_lead_k, lead_id, NONE)[None, :])
    ackm = (ack_slot[:, None] == jnp.arange(A, dtype=jnp.int32)[None, :]) \
        & del_jl  # [N, A]
    if withhold:
        ackm &= honest[:, None]  # byz acks never travel
    t_in3 = jnp.max(jnp.where(ackm, ack_term[:, None], 0), axis=0)  # [A]
    bump3_k = still_lead_k & (t_in3 > term[lid])
    bump3_id = jnp.where(bump3_k, lid, N)
    new_t = term.at[bump3_id].max(t_in3, mode="drop")
    bumped3 = new_t > term
    term, role, voted_for, timeout = bump(bumped3, new_t, term, role,
                                          voted_for, timeout)
    proc = still_lead_k & ~bump3_k                             # [A]

    succ_kj = (ackm & ack_ok[:, None]).T                       # [A, N]
    fail_kj = (ackm & ~ack_ok[:, None]).T
    lead_match = jnp.where(proc[:, None] & succ_kj,
                           jnp.maximum(lead_match,
                                       ack_match[None, :].astype(mdt)),
                           lead_match)
    lead_next = jnp.where(
        proc[:, None] & succ_kj, lead_match + jnp.asarray(1, mdt),
        jnp.where(proc[:, None] & fail_kj,
                  jnp.maximum(jnp.asarray(1, mdt),
                              lead_next - jnp.asarray(1, mdt)),
                  lead_next))

    # ---- P3e commit advance: majority-th largest of each tracked row,
    # via the same fixed-depth binary search as the dense kernel (raft.py
    # P3e) — a [A, N] jnp.sort would be ~300 comparator stages per round
    # at N=100k; log2(E) masked count-reductions are exact and cheap
    # (match <= E — see the dense kernel's bound argument).
    lo = jnp.zeros(A, jnp.int32)
    hi = jnp.full(A, E + 1, jnp.int32)
    for _ in range((E + 1).bit_length()):
        mid = (lo + hi) // 2
        cnt = jnp.sum((lead_match >= mid[:, None].astype(mdt))
                      .astype(jnp.int32), axis=1)
        ok = cnt >= majority
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid)
    med = lo                                                   # [A]
    kmed = jnp.clip(med - 1, 0, L - 1)
    term_at_med = log_term[lid, kmed]
    adv = proc & (med > commit[lid]) & (med > 0) & (term_at_med == term[lid])
    adv_id = jnp.where(adv, lid, N)
    commit = commit.at[adv_id].max(med, mode="drop")

    # ---- P4 timers.
    timer = jnp.where(role == ROLE_L, 0, jnp.where(reset, timer, timer + 1))

    if crash_on:
        # SPEC §6c freeze: down nodes hold their post-volatile-reset
        # state (lead_* slots never reference a down node — the tracked
        # set above excludes them).
        (term, role, voted_for, log_term, log_val, log_len, commit,
         timer, timeout) = _freeze(
            down, frozen, (term, role, voted_for, log_term, log_val,
                           log_len, commit, timer, timeout))

    new = RaftSparseState(seed, term, role, voted_for, log_term, log_val,
                          log_len, commit, timer, timeout, lead_id,
                          lead_match, lead_next, down)
    if not telem:
        return new
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    if elect_on:
        attacked = jam.astype(jnp.int32)
    elif sticky_on:
        attacked = sticky_act.astype(jnp.int32)
    else:
        attacked = jnp.int32(0)
    az = agg_counts(aggst) if switch else agg_counts()
    vec = jnp.stack([jnp.sum(win.astype(jnp.int32)),
                     jnp.sum(apply_.astype(jnp.int32)),
                     jnp.sum(append_rej.astype(jnp.int32)),
                     jnp.sum(commit - st.commit), attacked, *cz, *az])
    if not flight:
        return new, vec
    lat = jnp.stack([bucket_counts(st.timer[cid] + 1, win),
                     bucket_counts(log_len - commit,
                                   (role == ROLE_L) & ~down)])
    return new, vec, lat


def raft_sparse_round_telem(cfg: Config, st: RaftSparseState, r):
    return raft_sparse_round(cfg, st, r, telem=True)


def raft_sparse_round_flight(cfg: Config, st: RaftSparseState, r):
    return raft_sparse_round(cfg, st, r, telem=True, flight=True)


def _extract(st: RaftSparseState) -> dict:
    return {"commit": st.commit, "log_term": st.log_term,
            "log_val": st.log_val, "term": st.term, "role": st.role}


def _pspec(cfg: Config) -> RaftSparseState:
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import NODE_AXIS as ND
    v, m = P(ND), P(ND, None)
    lm = P(None, ND)
    return RaftSparseState(seed=P(), term=v, role=v, voted_for=v, log_term=m,
                           log_val=m, log_len=v, commit=v, timer=v, timeout=v,
                           lead_id=P(), lead_match=lm, lead_next=lm, down=v)


_ENGINE = None


def get_engine():
    global _ENGINE
    if _ENGINE is None:
        from ..network.runner import EngineDef
        _ENGINE = EngineDef("raft-sparse", raft_sparse_init, raft_sparse_round,
                            _extract, _pspec, telemetry_names=RAFT_TELEMETRY,
                            round_telem=raft_sparse_round_telem,
                            latency_names=RAFT_LATENCY,
                            round_flight=raft_sparse_round_flight)
    return _ENGINE
