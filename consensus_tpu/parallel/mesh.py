"""Device-mesh sharding for the TPU engine (SURVEY.md §7 step 8).

The reference scales by running many independent simulator processes on
CPU cores; the TPU-native equivalent (BASELINE.json:5) is one XLA program
partitioned over a `jax.sharding.Mesh` with two logical axes:

  * ``"sweep"`` — independent simulator instances (the batch axis).
    Embarrassingly parallel: no collectives cross it.
  * ``"node"``  — the node population inside one simulator. Sharding this
    axis makes GSPMD partition the per-round quorum reductions
    (vote tallies, prepare/commit counts, promise counts) into local
    partial sums + an ``all-reduce`` over ICI — exactly the "quorum
    tallies psum'd across a device mesh" design in the north star.

We deliberately express sharding as `NamedSharding` constraints and let
GSPMD insert the collectives, rather than hand-writing `shard_map` +
`psum`: the round kernels mix [i, j] edge matrices, per-node vectors and
per-(node, slot) grids, and the compiler's partitioner handles the mixed
contractions (and overlaps the all-reduces with compute) better than a
hand-scheduled version. See docs/SPEC.md §8.
"""
from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SWEEP_AXIS = "sweep"
NODE_AXIS = "node"


def make_mesh(mesh_shape, devices=None) -> Mesh:
    """Build a ("sweep", "node") mesh.

    ``mesh_shape`` is ``(n_sweep,)`` or ``(n_sweep, n_node)``; the product
    must not exceed the available device count. ``(8,)`` shards sweeps over
    8 chips; ``(2, 4)`` runs 2-way sweep-parallel × 4-way node-parallel.
    """
    if devices is None:
        devices = jax.devices()
    shape = tuple(int(s) for s in mesh_shape)
    if len(shape) == 1:
        shape = (shape[0], 1)
    if len(shape) != 2:
        raise ValueError(f"mesh_shape must have 1 or 2 axes, got {mesh_shape}")
    n = math.prod(shape)
    if n > len(devices):
        raise ValueError(f"mesh {shape} needs {n} devices, have {len(devices)}")
    dev = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev, (SWEEP_AXIS, NODE_AXIS))


def batched_spec(spec: P) -> P:
    """Prepend the sweep axis to an unbatched per-leaf PartitionSpec."""
    return P(SWEEP_AXIS, *spec)


def constrain(carry, cfg, mesh: Mesh | None, pspec_tree):
    """Pin the batched carry pytree to its mesh sharding (no-op without a
    mesh). ``pspec_tree`` matches the *unbatched* carry structure; the
    sweep axis is prepended here."""
    if mesh is None:
        return carry
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, batched_spec(s))),
        carry, pspec_tree)


def check_divisible(cfg, mesh: Mesh | None) -> None:
    """Shard sizes must divide the batched axes (no padding semantics —
    padding rows would change RNG-driven decided logs)."""
    if mesh is None:
        return
    ns = mesh.shape[SWEEP_AXIS]
    nn = mesh.shape[NODE_AXIS]
    if cfg.n_sweeps % ns:
        raise ValueError(f"n_sweeps={cfg.n_sweeps} not divisible by sweep axis {ns}")
    if cfg.n_nodes % nn:
        raise ValueError(f"n_nodes={cfg.n_nodes} not divisible by node axis {nn}")
