"""Live run introspection: /metrics + /status over localhost HTTP.

The first brick of sweep-as-a-service (ROADMAP): a stdlib
``http.server`` thread the CLIs start behind ``--serve-port``, serving

  ``/metrics``  Prometheus text exposition of the process metrics
                registry (the same rendering ``--metrics-out x.prom``
                snapshots, plus the flight/timeline gauges as they
                land), scrapeable mid-run;
  ``/status``   one JSON object: the run's static identity (protocol,
                engine, shape, pid) merged with the live
                ``rounds_completed`` / ``sim_eta_s`` gauges the runner
                updates per chunk, plus the supervised RunReport once
                one exists.

``routes`` extends the same server with caller-defined endpoints —
the sweep-service daemon (:mod:`consensus_tpu.service`) mounts its
``/jobs`` API here rather than growing a second HTTP stack, so both
front doors share one handler, one shutdown path, and one bind-error
policy.

Entirely OFF the hot path: the chunk loop only touches the gauges it
already updates; each request reads a locked registry snapshot on the
server thread. Binds 127.0.0.1 only (introspection, not a public
surface); port 0 asks the OS for an ephemeral port — the bound port is
in ``MetricsServer.port`` and on the stderr banner the CLI prints.
"""
from __future__ import annotations

import errno
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from . import metrics

StatusFn = Callable[[], "dict[str, Any]"]
# A mounted route: (method, path, body) -> (http status, content type,
# response bytes). Mounted by path PREFIX (longest match wins), so one
# route can serve a whole subtree ("/jobs" also answers "/jobs/j0001").
RouteFn = Callable[[str, str, bytes], "tuple[int, str, bytes]"]


class PortInUseError(OSError):
    """The requested port is already bound. Raised instead of the raw
    ``OSError`` traceback so every front door (the CLIs' --serve-port,
    the service daemon's --port) reports the same actionable line —
    str(exc) is the user-facing message."""

    def __init__(self, host: str, port: int) -> None:
        super().__init__(
            errno.EADDRINUSE,
            f"cannot bind {host}:{port}: the port is already in use "
            f"(pick another port, or 0 for an ephemeral one)")


class _QuietServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose per-request error hook doesn't spam: a
    scraper disconnecting mid-response (curl timeout, a cancelled
    Prometheus scrape) raises BrokenPipeError out of the handler, and
    socketserver's default ``handle_error`` prints a full traceback to
    stderr — the same noise ``log_message`` is silenced for.
    Introspection must never be louder than the run; but a GENUINE
    handler bug (a non-serializable status value, say) keeps one
    concise diagnostic line — an error channel, not a traceback."""

    def handle_error(self, request: Any, client_address: Any) -> None:
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            return  # the scraper went away; nothing is wrong here
        print(f"serve: request error: {type(exc).__name__}: {exc}",
              file=sys.stderr, flush=True)


class MetricsServer:
    """A daemon-thread HTTP server over the process metrics registry.

    ``status`` supplies the /status payload's run-identity fields; the
    live gauge values are merged in at request time so the endpoint
    never goes through the run loop. ``routes`` mounts additional
    endpoints by path prefix (see :data:`RouteFn`) — GET and POST both
    dispatch through them; built-in paths win over a mounted prefix.
    Use as a context manager or call :meth:`close` (idempotent: the
    server thread is shut down and JOINED exactly once, so a daemon
    exiting through overlapping finally blocks never double-closes a
    dead socket).

    A busy port raises :class:`PortInUseError` (an OSError subclass,
    so existing handlers keep working) with a one-line actionable
    message instead of the raw bind traceback.
    """

    def __init__(self, port: int = 0, status: StatusFn | None = None,
                 host: str = "127.0.0.1",
                 routes: "dict[str, RouteFn] | None" = None) -> None:
        self._status = status
        self._routes = dict(routes or {})
        self._t0 = time.time()
        self._closed = False
        handler = self._make_handler()
        try:
            self._httpd = _QuietServer((host, port), handler)
        except OSError as exc:
            if exc.errno == errno.EADDRINUSE:
                raise PortInUseError(host, port) from exc
            raise
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def status_payload(self) -> dict[str, Any]:
        doc: dict[str, Any] = dict(self._status()) if self._status else {}
        snap = metrics.snapshot()
        for gauge in ("rounds_completed", "sim_eta_s"):
            doc[gauge] = snap.get(gauge, {}).get("value", 0)
        doc["uptime_s"] = round(time.time() - self._t0, 3)
        return doc

    def _route_for(self, path: str) -> RouteFn | None:
        best = None
        for prefix in self._routes:
            if (path == prefix or path.startswith(prefix + "/")) \
                    and (best is None or len(prefix) > len(best)):
                best = prefix
        return None if best is None else self._routes[best]

    def _make_handler(self) -> type:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, method: str, body: bytes) -> None:
                if method == "GET" and self.path == "/metrics":
                    self._respond(
                        200, "text/plain; version=0.0.4; charset=utf-8",
                        metrics.to_prometheus().encode())
                    return
                if method == "GET" and self.path == "/status":
                    self._respond(
                        200, "application/json",
                        (json.dumps(server.status_payload(), indent=2)
                         + "\n").encode())
                    return
                route = server._route_for(self.path)
                if route is None:
                    known = sorted({"/metrics", "/status",
                                    *server._routes})
                    self.send_error(404, "unknown path "
                                    f"(try {', '.join(known)})")
                    return
                code, ctype, out = route(method, self.path, body)
                self._respond(code, ctype, out)

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                self._dispatch("GET", b"")

            def do_POST(self) -> None:  # noqa: N802 (http.server API)
                n = int(self.headers.get("Content-Length") or 0)
                self._dispatch("POST", self.rfile.read(n) if n else b"")

            def log_message(self, fmt: str, *args: Any) -> None:
                pass  # scrapes must not spam the run's stderr

        return Handler

    def close(self) -> None:
        """Shut down and JOIN the server thread (graceful shutdown:
        in-flight responses finish, the socket closes, and the daemon
        thread is reaped before the caller proceeds). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
