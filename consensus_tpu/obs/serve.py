"""Live run introspection: /metrics + /status over localhost HTTP.

The first brick of sweep-as-a-service (ROADMAP): a stdlib
``http.server`` thread the CLIs start behind ``--serve-port``, serving

  ``/metrics``  Prometheus text exposition of the process metrics
                registry (the same rendering ``--metrics-out x.prom``
                snapshots, plus the flight/timeline gauges as they
                land), scrapeable mid-run;
  ``/status``   one JSON object: the run's static identity (protocol,
                engine, shape, pid) merged with the live
                ``rounds_completed`` / ``sim_eta_s`` gauges the runner
                updates per chunk, plus the supervised RunReport once
                one exists.

Entirely OFF the hot path: the chunk loop only touches the gauges it
already updates; each request reads a locked registry snapshot on the
server thread. Binds 127.0.0.1 only (introspection, not a public
surface); port 0 asks the OS for an ephemeral port — the bound port is
in ``MetricsServer.port`` and on the stderr banner the CLI prints.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from . import metrics

StatusFn = Callable[[], "dict[str, Any]"]


class _QuietServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose per-request error hook doesn't spam: a
    scraper disconnecting mid-response (curl timeout, a cancelled
    Prometheus scrape) raises BrokenPipeError out of the handler, and
    socketserver's default ``handle_error`` prints a full traceback to
    stderr — the same noise ``log_message`` is silenced for.
    Introspection must never be louder than the run; but a GENUINE
    handler bug (a non-serializable status value, say) keeps one
    concise diagnostic line — an error channel, not a traceback."""

    def handle_error(self, request: Any, client_address: Any) -> None:
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            return  # the scraper went away; nothing is wrong here
        print(f"serve: request error: {type(exc).__name__}: {exc}",
              file=sys.stderr, flush=True)


class MetricsServer:
    """A daemon-thread HTTP server over the process metrics registry.

    ``status`` supplies the /status payload's run-identity fields; the
    live gauge values are merged in at request time so the endpoint
    never goes through the run loop. Use as a context manager or call
    :meth:`close`.
    """

    def __init__(self, port: int = 0, status: StatusFn | None = None,
                 host: str = "127.0.0.1") -> None:
        self._status = status
        self._t0 = time.time()
        handler = self._make_handler()
        self._httpd = _QuietServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def status_payload(self) -> dict[str, Any]:
        doc: dict[str, Any] = dict(self._status()) if self._status else {}
        snap = metrics.snapshot()
        for gauge in ("rounds_completed", "sim_eta_s"):
            doc[gauge] = snap.get(gauge, {}).get("value", 0)
        doc["uptime_s"] = round(time.time() - self._t0, 3)
        return doc

    def _make_handler(self) -> type:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = metrics.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/status":
                    body = (json.dumps(server.status_payload(), indent=2)
                            + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "unknown path "
                                    "(try /metrics or /status)")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt: str, *args: Any) -> None:
                pass  # scrapes must not spam the run's stderr

        return Handler

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
