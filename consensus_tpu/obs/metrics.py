"""Process-wide metrics registry (docs/OBSERVABILITY.md §"Metrics").

Counters, gauges and histograms with a JSON snapshot and a Prometheus
text rendering. Recording is always on — one dict lookup plus an int add
per observation, at O(chunks) call rates on the hot path — while
*export* is opt-in (the CLI's ``--metrics-out``, the benchmark suite's
per-row embedding).

    from consensus_tpu.obs import metrics
    metrics.counter("checkpoint_saves_total").inc()
    metrics.histogram("dispatch_wall_s").observe(0.012)
    metrics.snapshot()       # {name: {"type": ..., ...}}
    metrics.to_prometheus()  # text exposition format

Snapshot schema (version 1):

  counter   : {"type": "counter", "value": number}
  gauge     : {"type": "gauge", "value": number}
  histogram : {"type": "histogram", "count": int, "sum": float,
               "bounds": [b0 < b1 < ...], "counts": [c0, ..., c_n]}
              — counts has len(bounds)+1 entries (last = overflow
              bucket, observations > bounds[-1]); NON-cumulative, so
              count == sum(counts). The Prometheus rendering converts
              to cumulative le-buckets with the trailing +Inf.
  info      : {"type": "info", "labels": {k: str}}
              — run-identity labels (the Prometheus info-metric
              convention: rendered as `name{k="v",...} 1`, label
              values escaped per the text exposition format).
  labeled_gauge : {"type": "labeled_gauge",
                   "series": [{"labels": {k: str}, "value": number}]}
              — one gauge family, one child per label set (the sweep
              service's per-job rounds_completed/eta gauges); rendered
              as `name{k="v",...} value` per child, series sorted by
              label string so snapshots are deterministic.

Tests (and the benchmark suite, which wants a per-config delta) use
:func:`reset` to zero the default registry.
"""
from __future__ import annotations

import bisect
import contextlib
import threading
from typing import Any, Iterator

SCHEMA_VERSION = 1

# Process-wide recording switch (see paused()). Checked by every
# instrument so a warmup/compile pass can be excluded from the numbers
# a run exports — one module-global read per observation.
_PAUSED = False


@contextlib.contextmanager
def paused() -> Iterator[None]:
    """Temporarily drop all observations (every registry in-process) —
    used around warmup passes so exported histograms measure the run,
    not jit tracing + XLA compilation (docs/OBSERVABILITY.md)."""
    global _PAUSED
    prev, _PAUSED = _PAUSED, True
    try:
        yield
    finally:
        _PAUSED = prev

# Seconds-scale latency buckets: 100 µs .. 5 min, roughly log-spaced.
# Wide on purpose — the same bounds serve a ~ms CPU-backend dispatch and
# a multi-second 100k-node checkpoint write.
DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                   5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0)


class Counter:
    """Monotonically increasing value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: int | float = 0

    def inc(self, v: int | float = 1) -> None:
        if v < 0:
            raise ValueError(f"counters only go up, got inc({v})")
        if not _PAUSED:
            self.value += v

    def to_dict(self) -> dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: int | float = 0

    def set(self, v: int | float) -> None:
        if not _PAUSED:
            self.value = v

    def to_dict(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bucket histogram; ``counts[i]`` holds observations with
    ``v <= bounds[i]`` (first matching bucket), the final slot overflow."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"buckets must be strictly increasing, "
                             f"got {buckets}")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: int | float) -> None:
        if _PAUSED:
            return
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def to_dict(self) -> dict[str, Any]:
        return {"type": "histogram", "count": self.count, "sum": self.sum,
                "bounds": list(self.bounds), "counts": list(self.counts)}


class Info:
    """Run-identity labels (Prometheus info-metric convention): a set
    of string key/value pairs rendered as a constant-1 gauge. Last
    write wins, like :class:`Gauge`."""

    __slots__ = ("labels",)

    def __init__(self) -> None:
        self.labels: dict[str, str] = {}

    def set(self, **labels: Any) -> None:
        if not _PAUSED:
            self.labels = {k: str(v) for k, v in labels.items()}

    def to_dict(self) -> dict[str, Any]:
        return {"type": "info", "labels": dict(sorted(self.labels.items()))}


class LabeledGauge:
    """A gauge FAMILY: one last-write-wins value per label set (the
    Prometheus child-metric convention). Used for per-job fleet gauges
    (``service_job_rounds_completed{job="j0003"}``) where one process
    tracks many concurrent runs — a plain :class:`Gauge` would
    last-write-scramble them. Children are keyed by the sorted label
    items; :meth:`remove` drops a child (e.g. a finished job) so the
    family stays bounded over a long-lived service.

    Writes REBIND ``_series`` to a fresh dict (copy-on-write) instead
    of mutating in place: like Gauge/Info's single reference
    assignment, that keeps a concurrent /metrics scrape's snapshot
    iteration safe without putting a lock on the per-chunk hot path —
    an in-place insert from the worker thread mid-iteration would be
    a 'dict changed size' crash in the scraper."""

    __slots__ = ("_series",)

    def __init__(self) -> None:
        self._series: dict[tuple, tuple[dict[str, str], int | float]] = {}

    @staticmethod
    def _key(labels: dict[str, str]) -> tuple:
        return tuple(sorted(labels.items()))

    def set(self, value: int | float, **labels: Any) -> None:
        if not labels:
            raise ValueError("labeled gauge needs at least one label "
                             "(use a plain gauge otherwise)")
        if not _PAUSED:
            lab = {k: str(v) for k, v in labels.items()}
            self._series = {**self._series, self._key(lab): (lab, value)}

    def get(self, **labels: Any) -> int | float | None:
        lab = {k: str(v) for k, v in labels.items()}
        entry = self._series.get(self._key(lab))
        return None if entry is None else entry[1]

    def remove(self, **labels: Any) -> None:
        lab = {k: str(v) for k, v in labels.items()}
        key = self._key(lab)
        if key in self._series:
            self._series = {k: v for k, v in self._series.items()
                            if k != key}

    def to_dict(self) -> dict[str, Any]:
        return {"type": "labeled_gauge",
                "series": [{"labels": dict(lab), "value": v}
                           for _, (lab, v) in sorted(self._series.items())]}


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped or the exposition line is
    unparseable (the serve endpoint's /metrics hands this text to real
    scrapers, so 'mostly fine' is not fine)."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(labels: dict[str, str]) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))


class Registry:
    """Name → metric. Re-requesting a name returns the same instance;
    requesting it as a different type is an error (no silent shadowing)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}

    def _get(self, name: str, cls: type, *args: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(*args)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets)

    def info(self, name: str) -> Info:
        return self._get(name, Info)

    def labeled_gauge(self, name: str) -> LabeledGauge:
        return self._get(name, LabeledGauge)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {name: m.to_dict()
                    for name, m in sorted(self._metrics.items())}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (cumulative le-buckets)."""
        out = []
        for name, d in self.snapshot().items():
            if d["type"] == "info":
                # Info-metric convention: a constant-1 gauge carrying
                # run identity in (escaped) labels.
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name}{{{_label_str(d['labels'])}}} 1")
                continue
            if d["type"] == "labeled_gauge":
                # One child line per label set; the TYPE line calls the
                # family a gauge (Prometheus has no labeled_gauge type —
                # labels are the child convention, like info above).
                out.append(f"# TYPE {name} gauge")
                for child in d["series"]:
                    out.append(f"{name}{{{_label_str(child['labels'])}}} "
                               f"{child['value']}")
                continue
            out.append(f"# TYPE {name} {d['type']}")
            if d["type"] in ("counter", "gauge"):
                out.append(f"{name} {d['value']}")
                continue
            cum = 0
            for bound, c in zip(d["bounds"], d["counts"]):
                cum += c
                out.append(f'{name}_bucket{{le="{bound}"}} {cum}')
            out.append(f'{name}_bucket{{le="+Inf"}} {d["count"]}')
            out.append(f"{name}_sum {d['sum']}")
            out.append(f"{name}_count {d['count']}")
        return "\n".join(out) + ("\n" if out else "")


REGISTRY = Registry()

# Module-level conveniences bound to the default registry — call sites
# read `metrics.counter("x").inc()`.
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
info = REGISTRY.info
labeled_gauge = REGISTRY.labeled_gauge
reset = REGISTRY.reset
snapshot = REGISTRY.snapshot
to_prometheus = REGISTRY.to_prometheus
