"""Unified observability layer (docs/OBSERVABILITY.md).

Three pillars, all opt-in and all digest-neutral by construction:

  * :mod:`consensus_tpu.obs.trace`   — lightweight host-side spans/events
    with monotonic timestamps, written as JSONL; optionally mirrored
    into ``jax.profiler.TraceAnnotation`` so profiler traces line up
    with our span boundaries.
  * :mod:`consensus_tpu.obs.metrics` — a process-wide registry of
    counters / gauges / histograms, snapshotable to JSON and renderable
    as Prometheus text format.
  * **on-device protocol telemetry** — per-round counter vectors reduced
    inside each engine's scan body (leader elections, quorum hits,
    promises/nacks, ...), surfaced through
    ``RunResult.extras["telemetry"]``. That piece lives in the engines
    and :mod:`consensus_tpu.network.runner`; this package holds only the
    host-side sinks.
  * :mod:`consensus_tpu.obs.serve`   — live run introspection: a
    daemon-thread localhost HTTP server (``--serve-port``) exposing the
    metrics registry as ``/metrics`` (Prometheus text) and run status
    as ``/status`` (docs/OBSERVABILITY.md §"Observatory"). No server
    starts until the CLI asks for one; importing costs only stdlib
    ``http.server``.

Nothing here imports jax at module import time — the trace module
touches ``jax.profiler`` lazily and only when profiler annotation was
explicitly requested.
"""
from . import metrics, serve, timeline, trace  # noqa: F401

__all__ = ["metrics", "serve", "timeline", "trace"]
