"""Timeline analysis of flight-recorder series (docs/OBSERVABILITY.md
§"Flight recorder").

The device side reduces per-round telemetry into a bounded
``[n_sweeps, n_windows, K]`` window ring plus per-engine protocol
latency histograms; this module is the HOST side — it loads those series
from a ``--metrics-out`` snapshot (or a recorder-on checkpoint), derives
the liveness metrics the adversary scenarios are judged by, and renders
text/JSON summaries (``python -m tools.teleview``):

  * **commit throughput per window** — the engine's commit-progress
    counters (:data:`COMMIT_COUNTERS`) per round, per window;
  * **stall windows** — windows with ZERO commit progress (the
    "does LIB stall" / "commit stall" question of 2601.00273);
  * **availability ratio** — fraction of windows with progress, the
    liveness-under-disruption headline number;
  * **recovery time after fault onset** — rounds from the first faulty
    window (crash/view-change/election activity) to the next window
    that commits again;
  * **latency percentiles** — read off the power-of-two bucket
    histograms (``ops/flight.bucket_counts`` semantics: bucket 0 is
    <= 0, bucket i covers [2^(i-1), 2^i), the last is overflow; a
    percentile reports its bucket's LOWER edge — a floor, never an
    invented interpolation).

Deliberately numpy + stdlib only at module import: the metrics-JSON path
never pays a jax import (the checkpoint loader resolves engine counter
names lazily, which does import the engine modules).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

# Which telemetry counters measure COMMIT progress, per engine — the
# one declaration; the runner's live -v progress line rates the union
# (network/runner.PROGRESS_COUNTERS is derived from this dict).
COMMIT_COUNTERS = {
    "raft": ("entries_committed",),
    "raft-sparse": ("entries_committed",),
    "pbft": ("commit_quorums", "commits_adopted"),
    "pbft-bcast": ("commit_quorums", "commits_adopted"),
    "paxos": ("values_learned",),
    "dpos": ("blocks_appended",),
    "hotstuff": ("commits_learned",),
}
# Counters whose first nonzero window marks FAULT ONSET for the
# recovery-time metric: the §6c crash adversary, the SPEC Appendix A
# attack counters (per-producer slot misses, targeted-attack rounds),
# plus the protocol's own disruption signals (elections / view changes
# are what an availability attack looks like from inside the protocol).
FAULT_COUNTERS = ("crashes", "nodes_down", "missed_slots",
                  "suppressed_slots", "attack_rounds", "agg_down_rounds",
                  "stale_serves", "poisoned_serves", "forked_qc",
                  "leader_elections", "view_changes")


@dataclasses.dataclass(frozen=True)
class Timeline:
    """One run's flight-recorder series, loaded host-side."""
    engine: str
    window_rounds: int
    n_windows: int
    n_rounds: int
    bucket_lo: tuple[int, ...]
    windows: dict[str, np.ndarray]    # counter -> i64[n_sweeps, n_windows]
    latency: dict[str, np.ndarray]    # name -> i64[n_sweeps, N_BUCKETS]

    @property
    def n_sweeps(self) -> int:
        return next(iter(self.windows.values())).shape[0]

    def rounds_in_window(self) -> np.ndarray:
        """Per-window round count — every window spans
        ``window_rounds`` rounds except a ragged last one."""
        full = np.full(self.n_windows, self.window_rounds, dtype=np.int64)
        full[-1] = self.n_rounds - (self.n_windows - 1) * self.window_rounds
        return full


def from_flight_dict(fl: dict[str, Any]) -> Timeline:
    """Build a :class:`Timeline` from ``RunResult.extras["flight"]`` (or
    the identical ``"flight"`` block of a ``--metrics-out`` JSON)."""
    return Timeline(
        engine=fl["engine"],
        window_rounds=int(fl["window_rounds"]),
        n_windows=int(fl["n_windows"]),
        n_rounds=int(fl["n_rounds"]),
        bucket_lo=tuple(int(b) for b in fl["bucket_lo"]),
        windows={k: np.asarray(v, dtype=np.int64)
                 for k, v in fl["windows"].items()},
        latency={k: np.asarray(v, dtype=np.int64)
                 for k, v in fl["latency"].items()})


def from_metrics_json(path) -> Timeline:
    """Load the ``"flight"`` block of a ``--metrics-out`` snapshot."""
    with open(path) as fp:
        doc = json.load(fp)
    fl = doc.get("flight")
    if fl is None:
        raise ValueError(
            f"{path}: no 'flight' block — the run was made without "
            "--telemetry-window (the recorder is off by default)")
    return from_flight_dict(fl)


def from_checkpoint(path) -> Timeline:
    """Load the window ring + latency histograms from a RECORDER-ON
    checkpoint (.npz): the ring rides the snapshot as its last two
    leaves when the saved config has ``telemetry_window > 0``.

    A MID-RUN snapshot covers only rounds ``[0, next_round)`` — the
    returned timeline is truncated to the executed windows (its
    ``n_rounds``/``n_windows`` reflect ``next_round``, not the config's
    full horizon), so never-executed windows cannot read as stalls and
    deflate availability.

    Resolving the counter/latency NAMES needs the engine declaration,
    so this path lazily imports the engine modules (and therefore jax)
    — the metrics-JSON path stays import-free."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        cfg_d = meta["config"]
        next_round = int(meta["next_round"])
        if not cfg_d.get("telemetry_window"):
            raise ValueError(
                f"{path}: snapshot was written with the flight recorder "
                "off (telemetry_window = 0) — no series to load")
        n_leaves = len([k for k in z.files if k.startswith("leaf_")])
        win = np.asarray(z[f"leaf_{n_leaves - 2}"])
        lat = np.asarray(z[f"leaf_{n_leaves - 1}"])

    from ..core.config import Config
    from ..network import simulator
    cfg = Config.from_json(json.dumps(
        {k: v for k, v in cfg_d.items() if k != "_cutoffs"}))
    eng = simulator.engine_def(cfg)
    from ..network.runner import n_windows as _nw
    from ..ops.flight import BUCKET_LO
    nw = _nw(cfg)
    if win.shape != (cfg.n_sweeps, nw, len(eng.telemetry_names)) \
            or lat.shape != (cfg.n_sweeps, len(eng.latency_names),
                             len(BUCKET_LO)):
        raise ValueError(
            f"{path}: trailing leaves {win.shape}/{lat.shape} do not "
            "match the flight-recorder schema for the saved config — "
            "not a recorder-on snapshot of this code version")
    # Truncate to the executed prefix: rounds [0, next_round) fill
    # exactly ceil(next_round / W) windows (a checkpoint lands on a
    # chunk boundary, but the last executed window may still be
    # partial when W doesn't divide the chunk size).
    W = cfg.telemetry_window
    n_rounds = min(next_round, cfg.n_rounds)
    nwe = max(1, -(-n_rounds // W))
    return Timeline(
        engine=eng.name, window_rounds=W,
        n_windows=nwe, n_rounds=n_rounds, bucket_lo=BUCKET_LO,
        windows={name: win[:, :nwe, k].astype(np.int64)
                 for k, name in enumerate(eng.telemetry_names)},
        latency={name: lat[:, h, :].astype(np.int64)
                 for h, name in enumerate(eng.latency_names)})


def _commit_series(tl: Timeline) -> np.ndarray:
    """Commit progress per (sweep, window), summed over the engine's
    commit counters."""
    names = COMMIT_COUNTERS.get(tl.engine)
    if names is None:
        raise ValueError(f"no commit counters declared for engine "
                         f"{tl.engine!r} (obs/timeline.COMMIT_COUNTERS)")
    return sum(tl.windows[n] for n in names)


def _bucket_quantile(counts: np.ndarray, bucket_lo: tuple[int, ...],
                     q: float) -> int:
    """The LOWER edge of the bucket holding the q-quantile observation
    (a floor on the true quantile; exact to bucket resolution)."""
    total = int(counts.sum())
    if total == 0:
        return 0
    cum = np.cumsum(counts)
    return int(bucket_lo[int(np.searchsorted(cum, q * total))])


def derive(tl: Timeline) -> dict[str, Any]:
    """Liveness metrics off one timeline (all JSON-serializable).

    ``availability`` is the fraction of windows with commit progress,
    per sweep; ``stall_windows`` the complementary count;
    ``recovery_rounds`` measures, per sweep, from the first
    fault-active window (:data:`FAULT_COUNTERS`) to the next window
    that commits at or after it — -1 when the run never recovers, null
    onset when no fault ever fires.
    """
    commits = _commit_series(tl)                 # [B, n_windows]
    riw = tl.rounds_in_window()                  # [n_windows]
    stall = commits == 0                         # [B, n_windows]
    avail = 1.0 - stall.mean(axis=1)
    rate = commits / riw[None, :]

    fault = np.zeros_like(commits)
    for name in FAULT_COUNTERS:
        if name in tl.windows:
            fault = fault + tl.windows[name]
    onset: list[int | None] = []
    recovery: list[int | None] = []
    for b in range(commits.shape[0]):
        hot = np.nonzero(fault[b] > 0)[0]
        if hot.size == 0:
            onset.append(None)
            recovery.append(None)
            continue
        o = int(hot[0])
        onset.append(o)
        prog = np.nonzero(commits[b, o:] > 0)[0]
        # Rounds from the onset window's START to the END of the first
        # window that committed again — an upper bound at window
        # resolution; -1 = never recovered.
        recovery.append(int(riw[o:o + prog[0] + 1].sum())
                        if prog.size else -1)

    out: dict[str, Any] = {
        "engine": tl.engine,
        "window_rounds": tl.window_rounds,
        "n_windows": tl.n_windows,
        "n_sweeps": tl.n_sweeps,
        "availability": {"per_sweep": [round(float(a), 6) for a in avail],
                         "mean": round(float(avail.mean()), 6)},
        "stall_windows": {"per_sweep": [int(s) for s in stall.sum(axis=1)],
                          "total": int(stall.sum())},
        "commit_rate_per_round": {
            "per_window_mean": [round(float(x), 6)
                                for x in rate.mean(axis=0)],
            "overall": round(float(commits.sum() / (tl.n_rounds
                                                    * tl.n_sweeps)), 6)},
        "fault_onset_window": onset,
        "recovery_rounds": recovery,
        "latency": {
            name: {"count": int(h.sum()),
                   "p50": _bucket_quantile(h.sum(axis=0), tl.bucket_lo, .5),
                   "p90": _bucket_quantile(h.sum(axis=0), tl.bucket_lo, .9),
                   "p99": _bucket_quantile(h.sum(axis=0), tl.bucket_lo, .99)}
            for name, h in tl.latency.items()},
    }
    return out


def lane_fitness(tl: Timeline) -> list[dict[str, Any]]:
    """Per-sweep fitness signals for the adversary search
    (tools/advsearch) — one dict per sweep/lane, flattened from
    :func:`derive` into the four liveness quantities the search scores
    candidates by (availability floor, stall ratio, bounded recovery,
    never-recovered), plus the onset/commit context a finding records.

    ``recovery_rounds`` keeps :func:`derive`'s encoding (None = no
    fault ever fired, -1 = never recovered); ``never_recovered`` lifts
    the worst outcome into its own flag so a fitness function can
    weight it without re-decoding.
    """
    d = derive(tl)
    commits = _commit_series(tl)
    out = []
    for b in range(tl.n_sweeps):
        rec = d["recovery_rounds"][b]
        stalls = d["stall_windows"]["per_sweep"][b]
        m = {
            "availability": d["availability"]["per_sweep"][b],
            "stall_windows": stalls,
            "stall_ratio": round(stalls / tl.n_windows, 6),
            "fault_onset_window": d["fault_onset_window"][b],
            "recovery_rounds": rec,
            "never_recovered": rec == -1,
            "commit_rate": round(float(commits[b].sum()) / tl.n_rounds, 6),
        }
        # SPEC §7c safety-invariant totals, only when the engine's
        # recorder carries them (the BFT vote engines): a nonzero
        # safety_violations total is a SAFETY finding — categorically
        # worse than any liveness dip, and scored as such by the
        # adversary search (tools/advsearch.severity_of).
        for name in ("forked_qc", "conflict_commits", "safety_violations"):
            if name in tl.windows:
                m[name] = int(tl.windows[name][b].sum())
        out.append(m)
    return out


def export_metrics(derived: dict[str, Any], registry=None) -> None:
    """Publish the derived liveness metrics as gauges on the process
    metrics registry (default: the one ``--metrics-out`` snapshots), so
    a dashboard scrape carries the timeline verdicts, not just raw
    series."""
    from . import metrics as obs_metrics
    reg = registry if registry is not None else obs_metrics.REGISTRY
    reg.gauge("timeline_availability_ratio").set(
        derived["availability"]["mean"])
    reg.gauge("timeline_stall_windows_total").set(
        derived["stall_windows"]["total"])
    reg.gauge("timeline_commit_rate_per_round").set(
        derived["commit_rate_per_round"]["overall"])
    rec = [r for r in derived["recovery_rounds"] if r is not None]
    if rec:
        # -1 = some sweep NEVER recovered: the worst liveness outcome
        # must be visible on a scrape, not indistinguishable from a
        # fault-free run (which exports no recovery gauge at all).
        reg.gauge("timeline_recovery_rounds_max").set(
            -1 if any(r < 0 for r in rec) else max(rec))
    for name, d in derived["latency"].items():
        reg.gauge(f"timeline_latency_{name}_p90").set(d["p90"])


def render_text(tl: Timeline, derived: dict[str, Any]) -> str:
    """Compact terminal summary of one timeline."""
    commits = _commit_series(tl)
    lines = [
        f"flight recorder: engine={tl.engine} "
        f"windows={tl.n_windows}x{tl.window_rounds}r "
        f"({tl.n_rounds} rounds, {tl.n_sweeps} sweeps)",
        f"availability {derived['availability']['mean']:.3f} "
        f"(per sweep: "
        f"{' '.join(f'{a:.3f}' for a in derived['availability']['per_sweep'])})"
        f" | stall windows {derived['stall_windows']['total']}"
        f" | commit rate {derived['commit_rate_per_round']['overall']:.3f}"
        f"/round",
    ]
    for b in range(tl.n_sweeps):
        o, r = derived["fault_onset_window"][b], derived["recovery_rounds"][b]
        tail = "no faults" if o is None else (
            f"fault onset w{o}, " + ("never recovered" if r < 0
                                     else f"recovered in <= {r} rounds"))
        lines.append(f"  sweep {b}: commits/window "
                     f"{' '.join(str(int(c)) for c in commits[b])}  [{tail}]")
    for name, d in derived["latency"].items():
        h = tl.latency[name].sum(axis=0)
        lines.append(f"  latency {name}: n={d['count']} p50>={d['p50']} "
                     f"p90>={d['p90']} p99>={d['p99']} rounds "
                     f"(buckets {' '.join(str(int(c)) for c in h)})")
    return "\n".join(lines)
