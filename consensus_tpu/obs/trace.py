"""Span tracing with a JSONL sink (docs/OBSERVABILITY.md §"Trace schema").

Usage::

    from consensus_tpu.obs import trace
    trace.configure("run.trace.jsonl")
    with trace.span("dispatch", r0=0, n_rounds=64) as sp:
        ...                       # sp is a dict; mutate to add attrs
        sp["bytes"] = 123         # recorded at span close
    trace.event("attempt_failed", index=1)
    trace.close()

Design constraints:

  * **Near-zero cost when disabled** (the default): ``span`` checks one
    module global and yields ``None`` without allocating a record, so
    instrumented hot paths (the runner's chunk loop) pay an ``is None``
    test per call when tracing is off.
  * **Monotonic timestamps**: ``t_s`` is ``time.perf_counter()``; the
    first line of every file is a ``meta`` record anchoring that clock
    to wall time (``unix_t0``), so post-processors can reconstruct
    absolute times without the trace depending on a settable clock.
  * **Crash-visible**: every record is one flushed line — a SIGKILL
    mid-run loses at most the span currently open, never written lines
    (the resilience layer's crash tests rely on artifacts surviving).
  * **Profiler alignment**: ``configure(annotate_jax=True)`` wraps every
    span body in ``jax.profiler.TraceAnnotation(name)`` so the host
    lanes of a ``--profile`` trace carry the same boundaries as the
    JSONL spans. jax is imported lazily, only on that path.

Schema (version 1), one JSON object per line:

  meta  : {"type": "meta", "version": 1, "clock": "perf_counter",
           "t0_s": float, "unix_t0": float, "pid": int}
  span  : {"type": "span", "name": str, "t_s": float, "dur_s": float,
           "seq": int, "attrs": {str: scalar}}
  event : {"type": "event", "name": str, "t_s": float, "seq": int,
           "attrs": {str: scalar}}

``seq`` is strictly increasing per file (spans are sequenced at *close*,
so nested spans appear child-before-parent, like a profiler's end
events). ``tools/validate_trace.py`` checks all of this and exits
nonzero on drift.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import IO, Any, Iterator

SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_SINK: IO[str] | None = None   # open file object, or None
_ANNOTATE = False     # mirror spans into jax.profiler.TraceAnnotation


def _scalar(v: Any) -> bool | int | float | str | None:
    """Coerce an attr value to a JSON scalar (numpy ints/floats included);
    anything exotic becomes its repr — a trace line must never fail to
    serialize."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    try:  # numpy scalars expose item()
        return v.item()
    except (AttributeError, ValueError):
        return repr(v)


def configure(path: str | os.PathLike | None = None, *,
              annotate_jax: bool = False) -> None:
    """Install the trace sink. ``path=None`` with ``annotate_jax=True``
    enables profiler annotation without writing JSONL (the ``--profile``
    -only CLI mode). Reconfiguring closes any previous sink."""
    global _SINK, _ANNOTATE
    close()
    _ANNOTATE = bool(annotate_jax)
    if path is None:
        return
    fp = open(path, "w")
    fp.write(json.dumps({
        "type": "meta", "version": SCHEMA_VERSION, "clock": "perf_counter",
        "t0_s": time.perf_counter(), "unix_t0": time.time(),
        "pid": os.getpid()}) + "\n")
    fp.flush()
    with _LOCK:
        _SINK = fp
        _SINK_seq[0] = 0


_SINK_seq = [0]


def close() -> None:
    """Flush and detach the sink; disable profiler annotation."""
    global _SINK, _ANNOTATE
    with _LOCK:
        sink, _SINK = _SINK, None
        _ANNOTATE = False
    if sink is not None:
        sink.flush()
        sink.close()


def enabled() -> bool:
    return _SINK is not None


@contextlib.contextmanager
def suspended() -> Iterator[None]:
    """Temporarily suppress span/event emission (and profiler
    annotation) — used around warmup passes whose dispatches would
    otherwise be indistinguishable from the measured run's
    (docs/OBSERVABILITY.md §"Warmup"). A span OPENED before suspension
    still records at close, so a ``span("warmup")`` wrapping a
    ``suspended()`` block yields exactly one line covering the pass."""
    global _SINK, _ANNOTATE
    with _LOCK:
        sink, _SINK = _SINK, None
        ann, _ANNOTATE = _ANNOTATE, False
    try:
        yield
    finally:
        with _LOCK:
            _SINK, _ANNOTATE = sink, ann


def _emit(rec: dict[str, Any]) -> None:
    with _LOCK:
        sink = _SINK
        if sink is None:
            return
        rec["seq"] = _SINK_seq[0]
        _SINK_seq[0] += 1
        sink.write(json.dumps(rec) + "\n")
        sink.flush()


def event(name: str, **attrs: Any) -> None:
    """Record a point-in-time event (no duration). No-op when disabled."""
    if _SINK is None:
        return
    _emit({"type": "event", "name": name, "t_s": time.perf_counter(),
           "attrs": {k: _scalar(v) for k, v in attrs.items()}})


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[dict[str, Any] | None]:
    """Time a block. Yields the attrs dict (mutate it to attach values
    known only at the end, e.g. byte counts) — or ``None`` when tracing
    is fully disabled, which is the fast path."""
    if _SINK is None and not _ANNOTATE:
        yield None
        return
    ctx = contextlib.nullcontext()
    if _ANNOTATE:
        import jax  # lazy: only --profile runs pay the import

        ctx = jax.profiler.TraceAnnotation(name)
    t0 = time.perf_counter()
    try:
        with ctx:
            yield attrs
    finally:
        if _SINK is not None:
            _emit({"type": "span", "name": name, "t_s": t0,
                   "dur_s": time.perf_counter() - t0,
                   "attrs": {k: _scalar(v) for k, v in attrs.items()}})
