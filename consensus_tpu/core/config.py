"""One Config schema shared by the TPU engine, the C++ oracle, and the CLI.

Mirrors the reference's CLI→Config→Simulator flow (SURVEY.md §1, [B:5]).
All probabilities are converted once, on the host, to integer u32 cutoffs
(:func:`consensus_tpu.core.rng.prob_threshold_u32`) so that the JAX engine
and the C++ oracle compare raw threefry draws against the *same integers* —
float rounding can never make the engines diverge.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any

from .rng import prob_threshold_u32

PROTOCOLS = ("raft", "pbft", "paxos", "dpos", "hotstuff")
ENGINES = ("cpu", "tpu")


@dataclass(frozen=True)
class Config:  # frozen ⇒ hashable ⇒ usable as a jit static argument
    protocol: str = "raft"
    engine: str = "tpu"

    # Population / schedule. For pbft, n_nodes must equal 3f+1.
    n_nodes: int = 5
    n_rounds: int = 64
    n_sweeps: int = 1          # independent simulator instances (batch axis)
    seed: int = 0

    # Log / slot shape (fixed shapes for XLA; SURVEY.md §7 "hard parts").
    log_capacity: int = 128    # raft log length L / pbft+paxos slot count S
    max_entries: int = 100     # raft: client entries a leader may propose

    # Raft election timeouts, in rounds (randomized per (term, node)).
    t_min: int = 3
    t_max: int = 8
    # Raft active-sender cap (SPEC §3b). 0 = dense engine (exact [N, N]
    # bookkeeping); A > 0 = O(A*N) large-population engine: only the top-A
    # candidates/leaders by (term desc, id asc) send per round, and
    # replication bookkeeping lives in A tracked-leader slots.
    max_active: int = 0

    # Adversary rates (converted to u32 cutoffs below).
    drop_rate: float = 0.0       # per (round, directed edge) message drop
    partition_rate: float = 0.0  # per round: bipartition active?
    churn_rate: float = 0.0      # per round: all leaders forced to step down

    # Crash-recover adversary (SPEC §6c; mirrored scalar-for-scalar in
    # cpp/oracle.cpp since the adversary-library PR, so adversarial
    # configs stay byte-differential on engine="cpu"). Per round: each
    # up node crashes with crash_prob (losing volatile state, capped at
    # max_crashed simultaneously-down nodes; 0 = no cap) and each down
    # node recovers with recover_prob, rejoining from its persisted
    # state.
    crash_prob: float = 0.0
    recover_prob: float = 0.0
    max_crashed: int = 0

    # SPEC Appendix A adversary library.
    # §A.1 per-producer DPoS slot faults: round r's scheduled producer p
    # misses its slot (skipped chain-wide, like churn) with miss_rate,
    # drawn per (round, producer) — the per-producer keying is what
    # makes LIB stall under gappy schedules. dpos only; mirrored.
    miss_rate: float = 0.0
    # §A.2 bounded message delay/reorder: a drop on edge i->j at round q
    # may be repaired by a retransmission landing at q+d, d <= this (a
    # pure re-draw against shifted round keys — no queue rides the
    # carry). 0 = off (byte-identical program); capped at 16 (the
    # delayed-open check is a D-deep static loop per edge). All
    # protocols; mirrored.
    max_delay_rounds: int = 0
    # §A.3 targeted Raft attacks (raft/raft-sparse, TPU engine only —
    # NOT mirrored; rejected on engine="cpu"): "none" | "elect"
    # (repeated election disruption: jam all election traffic exactly
    # when a timeout fires) | "sticky" (leader-stickiness abuse:
    # suppress step-down of attack_target by jamming its inbound
    # delivery). attack_rate gates activation per round.
    attack: str = "none"
    attack_rate: float = 1.0
    attack_target: int = 0

    # SPEC §9 network model. "flat" = direct peer-to-peer delivery (the
    # historic model; compiled no-op — the round program is byte-stable
    # modulo these Config fields). "switch" = in-network vote
    # aggregation (PAPERS.md 1605.05619): the vote/quorum responses of
    # raft, raft_sparse, pbft, pbft_bcast, paxos and hotstuff route
    # through n_aggregators aggregator vertices that combine votes
    # in-flight (masked sums for counts, max/min for order-statistic
    # quantities) — receivers see K pre-aggregated values instead of N
    # messages. Rejected for dpos (the producer row doesn't vote).
    # Mirrored scalar-for-scalar in cpp/oracle.cpp (AggNet).
    net_model: str = "flat"
    n_aggregators: int = 0       # K; switch: 1 <= K <= n_nodes, flat: 0
    # STREAM_AGG fault axes, per (round, aggregator): an aggregator
    # fails (its whole segment silently dropped, both directions) with
    # agg_fail_rate, and serves STALE state with agg_stale_rate — its
    # uplink re-draws against a shifted round key r - d,
    # d in [1, agg_max_stale] (a pure re-draw like §A.2 delay; no
    # queue rides the carry).
    agg_fail_rate: float = 0.0
    agg_stale_rate: float = 0.0
    agg_max_stale: int = 1       # stale depth bound, in [1, 8]
    # SPEC §9b poisoned aggregation (the vote-certificate byzantine
    # model): the last agg_byz of the K aggregator vertices are
    # byzantine — per (round, phase-qualified vertex) they serve a
    # FORGED combine claiming full segment support with
    # agg_poison_rate. Independently, each byzantine REPLICA (the
    # n_byzantine set) lies to its switch vertex about its own vote
    # with byz_uplink_rate per round (count paths: claims a vote it
    # never cast; value paths: claims a forged value, killing segment
    # uniformity). Both axes draw STREAM_POISON; mirrored in
    # cpp/oracle.cpp.
    agg_byz: int = 0             # byzantine aggregators (ids >= K - agg_byz)
    agg_poison_rate: float = 0.0
    byz_uplink_rate: float = 0.0

    # SPEC §B per-node view-synchronizer timer skew (pbft, hotstuff —
    # the per-node pacemakers; mirrored): each up node's local view
    # timer jumps ahead by d in [1, max_skew_rounds] rounds with
    # desync_rate per (round, node) (STREAM_DESYNC), firing premature
    # local timeouts that desynchronize views — the PAPERS.md
    # 2601.00273 timer-desync attack class. 0 = off (compiled no-op;
    # the round program is byte-stable modulo these Config fields).
    desync_rate: float = 0.0
    max_skew_rounds: int = 1     # skew depth bound, in [1, 8]

    # SPEC §A.4 correlated DPoS producer suppression (dpos only;
    # mirrored): one draw per (round // suppress_window, producer), so
    # a suppressed producer misses EVERY slot inside the window — the
    # correlated outage iid §A.1 slot-miss keying cannot produce
    # (RESILIENCE.md §8).
    suppress_rate: float = 0.0
    suppress_window: int = 16    # rounds per suppression window (>= 1)

    # PBFT.
    f: int = 1                   # byzantine tolerance; n_nodes = 3f+1
    view_timeout: int = 8        # rounds without progress before view change
    n_byzantine: int = 0         # byzantine nodes (ids >= N - n_byzantine)
    byz_mode: str = "silent"     # "silent" | "equivocate" (SPEC §6)

    # Fault granularity (SPEC §6b). "edge" = per directed edge (§2,
    # exact, O(N²) tallies); "bcast" = per-sender broadcast drops — the
    # large-N PBFT model (pbft only; rejected elsewhere, no silent
    # ignores).
    fault_model: str = "edge"

    # Paxos.
    n_proposers: int = 0         # 0 ⇒ all nodes propose

    # DPoS.
    n_candidates: int = 16
    n_producers: int = 4         # K active producers per epoch
    epoch_len: int = 16          # rounds per epoch

    # Parallelism (TPU engine only; ignored by the oracle).
    mesh_shape: tuple[int, ...] = ()  # e.g. (8,): sweeps/nodes over 8 chips
    scan_chunk: int = 0          # 0 ⇒ single scan; else blocked scan chunk size
    # 0 ⇒ all sweeps batch into one XLA program; else the host runs
    # groups of at most this many sweeps as separate programs and
    # concatenates the carries. Per-sweep seeds (docs/SPEC.md §1) are
    # position-based, so results are bit-identical to the one-program
    # run (tests/test_runner.py). Bounds per-program working-set size —
    # required at e.g. pbft-bcast N=100k where the 8-sweep-batched sort
    # faults the TPU worker (benchmarks/run_benchmarks.py).
    sweep_chunk: int = 0

    # Flight recorder window width in rounds (docs/OBSERVABILITY.md
    # §"Flight recorder"; TPU engine only, needs telemetry). 0 ⇒ off:
    # the compiled round program is bit-for-bit the recorder-free one
    # (tests/test_flight.py + the recorder-off hlocheck fingerprints).
    # W > 0 additionally reduces the per-round telemetry counters into
    # a [ceil(n_rounds/W), K] per-sweep window series and accumulates
    # the per-engine protocol latency histograms, both riding the scan
    # carry and checkpointed with it.
    telemetry_window: int = 0

    def __post_init__(self) -> None:
        if self.protocol not in PROTOCOLS:
            raise ValueError(f"unknown protocol {self.protocol!r}")
        if self.engine not in ENGINES:
            raise ValueError(f"unknown engine {self.engine!r}")
        if min(self.n_nodes, self.n_rounds, self.n_sweeps, self.log_capacity) < 1:
            raise ValueError("n_nodes, n_rounds, n_sweeps, log_capacity must be >= 1")
        if self.protocol in ("pbft", "hotstuff"):
            expect = 3 * self.f + 1
            if self.n_nodes != expect:
                raise ValueError(
                    f"{self.protocol} requires n_nodes == 3f+1 == "
                    f"{expect}, got {self.n_nodes}")
            if self.n_byzantine > self.f:
                raise ValueError("n_byzantine must be <= f")
        if self.n_byzantine < 0 or self.n_byzantine > self.n_nodes:
            raise ValueError("n_byzantine must be in [0, n_nodes]")
        if self.n_byzantine > 0 and self.protocol not in ("pbft", "raft",
                                                          "hotstuff"):
            raise ValueError(
                f"n_byzantine is a pbft/raft/hotstuff adversary "
                f"(SPEC §6/§3c/§7b); {self.protocol} would silently "
                "ignore it")
        # byz_mode='equivocate' is supported for hotstuff since the
        # vote-certificate PR (SPEC §7c): the leader's vote tally is
        # per value-id, so a byzantine leader can serve per-receiver
        # certificates and fork a QC — the old "threshold counts have
        # no per-value tally to poison" rejection is lifted.
        if self.byz_mode not in ("silent", "equivocate"):
            raise ValueError(f"unknown byz_mode {self.byz_mode!r}")
        if self.fault_model not in ("edge", "bcast"):
            raise ValueError(f"unknown fault_model {self.fault_model!r}")
        if self.fault_model == "bcast" and self.protocol != "pbft":
            raise ValueError(
                "fault_model='bcast' (SPEC §6b) is a pbft model; other "
                "protocols would silently ignore it")
        if self.max_crashed < 0 or self.max_crashed > self.n_nodes:
            raise ValueError("max_crashed must be in [0, n_nodes] "
                             "(0 = no cap on simultaneous crashes)")
        if self.miss_rate > 0 and self.protocol != "dpos":
            raise ValueError(
                "miss_rate is the SPEC §A.1 per-producer DPoS slot-fault "
                f"adversary; {self.protocol} has no producer schedule and "
                "would silently ignore it")
        if not (0 <= self.max_delay_rounds <= 16):
            raise ValueError(
                "max_delay_rounds must be in [0, 16] (SPEC §A.2: the "
                "delayed-open check is a D-deep static loop per edge; "
                "0 = off)")
        if self.attack not in ("none", "elect", "sticky"):
            raise ValueError(f"unknown attack {self.attack!r} (SPEC §A.3: "
                             "none | elect | sticky)")
        if self.attack != "none":
            if self.protocol != "raft":
                raise ValueError(
                    "attack != 'none' is a SPEC §A.3 Raft-targeted "
                    f"adversary; {self.protocol} would silently ignore it")
            if self.engine == "cpu":
                raise ValueError(
                    "attack != 'none' is a tpu-engine adversary (SPEC "
                    "§A.3); the C++ oracle does not implement it and "
                    "would silently simulate different trajectories")
            if self.attack == "elect" and self.attack_target != 0:
                raise ValueError(
                    "attack_target is read only by attack='sticky' (SPEC "
                    "§A.3 leader-stickiness); 'elect' jams election "
                    "traffic population-wide and would silently ignore it")
            if not (0 <= self.attack_target < self.n_nodes):
                raise ValueError("attack_target must be in [0, n_nodes)")
        else:
            if self.attack_rate != 1.0 or self.attack_target != 0:
                raise ValueError(
                    "attack_rate/attack_target require attack != 'none' "
                    "(SPEC §A.3) — they would be silently ignored")
        if self.net_model not in ("flat", "switch"):
            raise ValueError(f"unknown net_model {self.net_model!r} "
                             "(SPEC §9: flat | switch)")
        if self.net_model == "switch":
            if self.protocol == "dpos":
                raise ValueError(
                    "net_model='switch' aggregates vote/quorum responses "
                    "(SPEC §9); dpos's producer row doesn't vote — there "
                    "is nothing to aggregate, so the model would be a "
                    "silent no-op")
            if not (1 <= self.n_aggregators <= self.n_nodes):
                raise ValueError(
                    "net_model='switch' requires 1 <= n_aggregators <= "
                    f"n_nodes, got K={self.n_aggregators} N={self.n_nodes}")
            if not (0 <= self.agg_byz <= self.n_aggregators):
                raise ValueError(
                    "agg_byz must be in [0, n_aggregators] (SPEC §9b: "
                    "the byzantine aggregators are the last agg_byz "
                    f"vertex ids), got {self.agg_byz} with "
                    f"K={self.n_aggregators}")
            if self.agg_poison_rate > 0:
                if self.agg_byz == 0:
                    raise ValueError(
                        "agg_poison_rate > 0 requires agg_byz > 0 (SPEC "
                        "§9b: only a byzantine aggregator serves forged "
                        "combines) — it would be silently ignored")
                if self.protocol not in ("pbft", "hotstuff"):
                    raise ValueError(
                        "agg_poison_rate is the SPEC §9b forged-combine "
                        "axis of the BFT vote engines (pbft, hotstuff); "
                        f"{self.protocol} would silently ignore it")
            if self.byz_uplink_rate > 0:
                if self.protocol not in ("pbft", "hotstuff"):
                    raise ValueError(
                        "byz_uplink_rate is the SPEC §9b byzantine-"
                        "uplink axis of the BFT vote engines (pbft, "
                        f"hotstuff); {self.protocol} would silently "
                        "ignore it")
                if self.n_byzantine == 0:
                    raise ValueError(
                        "byz_uplink_rate > 0 requires n_byzantine > 0 "
                        "(SPEC §9b: only a byzantine replica lies to "
                        "its switch vertex) — it would be silently "
                        "ignored")
        else:
            bad = [n for n, v, d in (
                ("n_aggregators", self.n_aggregators, 0),
                ("agg_fail_rate", self.agg_fail_rate, 0.0),
                ("agg_stale_rate", self.agg_stale_rate, 0.0),
                ("agg_max_stale", self.agg_max_stale, 1),
                ("agg_byz", self.agg_byz, 0),
                ("agg_poison_rate", self.agg_poison_rate, 0.0),
                ("byz_uplink_rate", self.byz_uplink_rate, 0.0)) if v != d]
            if bad:
                raise ValueError(
                    f"{', '.join(bad)} require net_model='switch' "
                    "(SPEC §9) — they would be silently ignored")
        if not (1 <= self.agg_max_stale <= 8):
            raise ValueError("agg_max_stale must be in [1, 8] (SPEC §9: "
                             "the stale re-draw is a bounded shift, like "
                             "the §A.2 delay horizon)")
        if self.desync_rate > 0 and self.protocol not in ("pbft",
                                                          "hotstuff"):
            raise ValueError(
                "desync_rate is the SPEC §B view-synchronizer timer-skew "
                f"adversary of the per-node BFT pacemakers; {self.protocol} "
                "has no per-node view timer and would silently ignore it")
        if not (1 <= self.max_skew_rounds <= 8):
            raise ValueError("max_skew_rounds must be in [1, 8] (SPEC §B: "
                             "the skew depth is a bounded jump, like the "
                             "§9 stale horizon)")
        if self.max_skew_rounds != 1 and self.desync_rate == 0:
            raise ValueError(
                "max_skew_rounds requires desync_rate > 0 (SPEC §B) "
                "— it would be silently ignored")
        if self.suppress_rate > 0 and self.protocol != "dpos":
            raise ValueError(
                "suppress_rate is the SPEC §A.4 correlated DPoS "
                f"producer-suppression adversary; {self.protocol} has no "
                "producer schedule and would silently ignore it")
        if self.suppress_window < 1:
            raise ValueError("suppress_window must be >= 1")
        if self.suppress_window != 16 and self.suppress_rate == 0:
            raise ValueError(
                "suppress_window requires suppress_rate > 0 (SPEC §A.4) "
                "— it would be silently ignored")
        if self.t_max <= self.t_min:
            raise ValueError("t_max must exceed t_min")
        if self.max_active < 0:
            raise ValueError("max_active must be >= 0 (0 = dense engine)")
        if self.max_active > self.n_nodes:
            raise ValueError("max_active must be <= n_nodes (the active set "
                             "is a subset of the population, SPEC §3b)")
        if self.sweep_chunk < 0:
            raise ValueError("sweep_chunk must be >= 0 (0 = one program)")
        if self.telemetry_window < 0:
            raise ValueError("telemetry_window must be >= 0 (0 = flight "
                             "recorder off)")
        if self.telemetry_window > 0 and self.engine == "cpu":
            raise ValueError(
                "telemetry_window > 0 is a tpu-engine feature (the flight "
                "recorder rides the scan carry); the C++ oracle has no "
                "telemetry to window and would silently ignore it")
        if self.protocol == "dpos":
            # Candidates are a subset of the validator population and
            # producers a subset of candidates — the C++ oracle rejects
            # anything else (cpp/oracle.cpp DposSim validation); mirror
            # it here so the JAX engine can't silently run a config the
            # oracle refuses.
            if not (1 <= self.n_producers <= self.n_candidates
                    <= self.n_nodes):
                raise ValueError(
                    "dpos requires 1 <= n_producers <= n_candidates "
                    f"<= n_nodes, got K={self.n_producers} "
                    f"C={self.n_candidates} V={self.n_nodes}")
            if self.epoch_len < 1:
                raise ValueError("epoch_len must be >= 1")

    # Integer cutoffs — THE values both engines compare draws against.
    @property
    def drop_cutoff(self) -> int:
        return prob_threshold_u32(self.drop_rate)

    @property
    def partition_cutoff(self) -> int:
        return prob_threshold_u32(self.partition_rate)

    @property
    def churn_cutoff(self) -> int:
        return prob_threshold_u32(self.churn_rate)

    @property
    def crash_cutoff(self) -> int:
        return prob_threshold_u32(self.crash_prob)

    @property
    def recover_cutoff(self) -> int:
        return prob_threshold_u32(self.recover_prob)

    @property
    def miss_cutoff(self) -> int:
        return prob_threshold_u32(self.miss_rate)

    @property
    def attack_cutoff(self) -> int:
        return prob_threshold_u32(self.attack_rate)

    @property
    def agg_fail_cutoff(self) -> int:
        return prob_threshold_u32(self.agg_fail_rate)

    @property
    def agg_stale_cutoff(self) -> int:
        return prob_threshold_u32(self.agg_stale_rate)

    @property
    def agg_poison_cutoff(self) -> int:
        return prob_threshold_u32(self.agg_poison_rate)

    @property
    def byz_uplink_cutoff(self) -> int:
        return prob_threshold_u32(self.byz_uplink_rate)

    @property
    def suppress_cutoff(self) -> int:
        return prob_threshold_u32(self.suppress_rate)

    @property
    def desync_cutoff(self) -> int:
        return prob_threshold_u32(self.desync_rate)

    # Static adversary GATES — the Python-level on/off facts the engines
    # branch on while tracing (the cutoff VALUES only ever feed jnp
    # compares). Engines must read these instead of comparing cutoffs
    # directly so that a knob-batched search program
    # (core/knobs.KnobView) can trace per-candidate cutoff values under
    # a statically-gated base config: the gate stays a Python bool, the
    # value becomes an operand.
    @property
    def crash_on(self) -> bool:
        return self.crash_cutoff > 0

    @property
    def miss_on(self) -> bool:
        return self.miss_cutoff > 0

    @property
    def no_partition(self) -> bool:
        return self.partition_cutoff == 0

    @property
    def switch_on(self) -> bool:
        """SPEC §9 static gate: flat configs must compile the historic
        round program byte-for-byte (tests/test_aggregate.py)."""
        return self.net_model == "switch"

    @property
    def agg_fail_on(self) -> bool:
        return self.agg_fail_cutoff > 0

    @property
    def agg_stale_on(self) -> bool:
        return self.agg_stale_cutoff > 0

    @property
    def agg_poison_on(self) -> bool:
        """SPEC §9b static gate: poison-free switch configs compile the
        PR-15 switch program byte-for-byte."""
        return self.agg_byz > 0 and self.agg_poison_cutoff > 0

    @property
    def uplink_lies_on(self) -> bool:
        return self.n_byzantine > 0 and self.byz_uplink_cutoff > 0

    @property
    def suppress_on(self) -> bool:
        return self.suppress_cutoff > 0

    @property
    def desync_on(self) -> bool:
        """SPEC §B static gate: desync-free configs compile the skew-free
        round program byte-for-byte."""
        return self.desync_cutoff > 0

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["mesh_shape"] = list(self.mesh_shape)
        d["_cutoffs"] = {  # informational; re-derived on load
            "drop": self.drop_cutoff,
            "partition": self.partition_cutoff,
            "churn": self.churn_cutoff,
            "crash": self.crash_cutoff,
            "recover": self.recover_cutoff,
            "miss": self.miss_cutoff,
            "attack": self.attack_cutoff,
            "agg_fail": self.agg_fail_cutoff,
            "agg_stale": self.agg_stale_cutoff,
            "agg_poison": self.agg_poison_cutoff,
            "byz_uplink": self.byz_uplink_cutoff,
            "suppress": self.suppress_cutoff,
            "desync": self.desync_cutoff,
        }
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "Config":
        d: dict[str, Any] = json.loads(s)
        d.pop("_cutoffs", None)
        d["mesh_shape"] = tuple(d.get("mesh_shape", ()))
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})
