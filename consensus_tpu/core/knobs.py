"""Traced-knob Config view — the device side of the adversary search.

The engines read two different kinds of information off a
:class:`~consensus_tpu.core.config.Config` while tracing:

  * **static structure** — shapes, protocol/engine dispatch, the
    adversary GATES (``crash_on``/``miss_on``/``no_partition``, the
    ``attack`` kind string, the ``max_delay_rounds`` loop depth, the
    ``max_crashed`` cap shape). These decide WHAT gets traced and must
    be Python values.
  * **knob VALUES** — the u32 probability cutoffs (``drop_cutoff``,
    ``crash_cutoff``, ...) and ``attack_target``. These only ever feed
    ``jnp`` compares/indexing (``ops/adversary.cutoff`` is a
    ``jnp.uint32`` cast), so they can just as well be *operands* of the
    compiled program as constants baked into it.

:class:`KnobView` exploits that split: it duck-types a Config whose
knob values are JAX tracers while everything else delegates to a static
base Config. ``runner.run_knob_batch`` vmaps engine rounds over
per-lane knob vectors through this view, which is what lets a whole
*generation* of adversary-search candidates (tools/advsearch) share ONE
compiled XLA program per (protocol, static shape) — no per-candidate
recompile.

Soundness: a lane whose traced knob values equal a real Config's
cutoffs computes the identical trajectory (same draws, same u32
compares — tests/test_advsearch.py pins lane-vs-production bit-identity
per engine). A gated-on feature with a zero traced cutoff never fires,
so its lane is value-identical to the feature-off program.
"""
from __future__ import annotations

from typing import Any

from .config import Config

# The traced knob slots, in column order — the one declaration shared
# by KnobView, runner.run_knob_batch's kmat layout, and
# tools/advsearch's candidate encoding. All are u32 cutoffs except
# attack_target (a node id, also u32 on device).
KNOB_COLUMNS = ("drop_cutoff", "partition_cutoff", "churn_cutoff",
                "crash_cutoff", "recover_cutoff", "miss_cutoff",
                "suppress_cutoff", "attack_cutoff", "attack_target",
                # SPEC §9b vote-certificate byzantine knobs: both feed
                # ops/aggregate's `_lt()` u32 compares, so they trace
                # exactly like the delivery cutoffs. Their gates
                # (agg_poison_on / uplink_lies_on) stay static on the
                # base, per the gate/value split above.
                "agg_poison_cutoff", "byz_uplink_cutoff",
                # SPEC §B per-node view-synchronizer timer skew: feeds
                # ops/viewsync's `_lt()` u32 compare; its gate
                # (desync_on) stays static on the base.
                "desync_cutoff")


class KnobView:
    """A Config stand-in with traced knob values over a static base.

    ``base`` supplies every static fact — including the gates, so the
    base must be *gate-representative* for the knobs a lane may vary
    (e.g. ``crash_prob > 0`` on the base whenever any lane traces a
    nonzero ``crash_cutoff``; tools/advsearch's spaces construct such a
    base). ``traced`` maps :data:`KNOB_COLUMNS` names to scalars
    (tracers inside the program); unnamed knobs fall through to the
    base's static values.
    """

    def __init__(self, base: Config, **traced: Any):
        unknown = set(traced) - set(KNOB_COLUMNS)
        if unknown:
            raise ValueError(f"unknown traced knobs {sorted(unknown)} "
                             f"(tracable: {list(KNOB_COLUMNS)})")
        self._base = base
        for name in KNOB_COLUMNS:
            setattr(self, name, traced.get(name, getattr(base, name)))

    def __getattr__(self, name: str) -> Any:
        # Only reached for names not set in __init__ — the static side.
        return getattr(self._base, name)
