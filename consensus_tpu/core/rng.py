"""Counter-based RNG shared bit-exactly by the JAX engine and the C++ oracle.

The reference (`2892931976/consensus-rs`, see SURVEY.md §0 — mount was empty,
reconstructed from BASELINE.json:5) drives its adversary (partitions, drops,
leader churn) and randomized election timeouts from a seeded RNG. For
decided-log byte-equivalence between the TPU engine and the CPU oracle
(BASELINE.json:2), both sides must draw *identical* random streams with
*no shared iteration order*. A counter-based generator is the only sane
choice: random value = pure function of (seed, stream, round, index).

We implement Threefry-2x32 (Salmon et al., SC'11 "Parallel Random Numbers:
As Easy as 1, 2, 3") with the standard 20-round schedule — the same
algorithm JAX uses internally — in three places:

  * here in vectorized numpy (host-side precompute, tests),
  * here in jnp (device-side, traceable under jit/vmap/scan),
  * in ``cpp/oracle.cpp`` (scalar, for the C++ oracle).

All three are validated against each other and against
``jax._src.prng.threefry_2x32`` in ``tests/test_rng.py``.

Stream discipline
-----------------
Every random decision in the simulator is drawn as

    bits = threefry2x32(key=(seed ^ STREAM_C, ctx), ctr=(hi, lo))

where STREAM_C is a per-purpose constant (delivery, timeout, churn, ...),
``ctx`` is a contextual 32-bit value (round or term), and (hi, lo) is a
64-bit index split into two u32 words. Probability thresholds are integer
u32 cutoffs precomputed once in :mod:`consensus_tpu.core.config` so no
float rounding can diverge between engines.
"""
from __future__ import annotations

import numpy as np

# Threefry-2x32 constants (Random123 reference implementation).
_KS_PARITY = np.uint32(0x1BD11BDA)
# Rotation schedule: 4 rounds of R_A interleaved with 4 rounds of R_B, x5.
_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)

# Stream constants. Arbitrary odd 32-bit values; must match cpp/threefry.h
# (machine-checked by tools/lint, check `streams`).
STREAM_DELIVER = np.uint32(0x9E3779B1)  # per (round, edge) message delivery
STREAM_TIMEOUT = np.uint32(0x85EBCA77)  # per (term, node) election timeout
STREAM_CHURN = np.uint32(0xC2B2AE3D)    # per round leader-churn event
STREAM_PARTITION = np.uint32(0x27D4EB2F)  # per round partition side/active
STREAM_STAKE = np.uint32(0x165667B1)    # per validator initial stake (DPoS)
STREAM_VOTE = np.uint32(0xD3A2646C)     # per (epoch, validator) vote target
STREAM_VALUE = np.uint32(0xFD7046C5)    # proposal payload values
STREAM_BYZANTINE = np.uint32(0xB55A4F09)  # reserved: byzantine node pick
STREAM_EQUIV = np.uint32(0x94D049BB)    # per (round, byz sender, receiver) stance
# SPEC §6c crash-recover adversary (mirrored scalar-for-scalar in
# cpp/oracle.cpp since the adversary-library PR — adversarial configs
# stay byte-differential against the oracle).
STREAM_CRASH = np.uint32(0x68E31DA5)    # per (round, node) crash/recover draw
# SPEC Appendix A adversary library.
STREAM_SLOTMISS = np.uint32(0x7F4A7C15)  # per (round, producer) DPoS slot miss
STREAM_DELAY = np.uint32(0x2545F491)     # per (origin round, d, edge) retransmit
STREAM_ATTACK = np.uint32(0xBB67AE85)    # per round targeted-attack activation
# SPEC §9 in-network vote aggregation (net_model="switch"): the
# per-(round, aggregator) fault axes of the programmable-switch model —
# c0 selects the subdraw: 0 = aggregator failure (a down aggregator
# silently drops its whole segment), 1 = stale-serve activation (the
# aggregator re-serves the segment it combined from a shifted round's
# delivery pattern — a pure re-draw, §A.2-style, no queue rides the
# carry), 2 = the stale depth draw d in [1, agg_max_stale]. Mirrored.
STREAM_AGG = np.uint32(0x510E527F)       # per (round, subdraw, aggregator)
# SPEC §9b poisoned in-network aggregation (net_model="switch"): the
# vote-certificate byzantine axes of the switch layer — c0 selects the
# subdraw: 0 = poisoned-serve activation for one (round, aggregator
# vertex) (a byzantine aggregator serves a forged combine claiming full
# segment support), 1 = byzantine-uplink lie for one (round, node) (a
# byzantine replica lies to its switch vertex about its own vote),
# 2 = the forged value a lying node serves (bitcast to i32, the same
# 32-bit payload discipline as STREAM_VALUE blocks).
# c1 carries the aggregator's phase-qualified vertex index (ph*K + a,
# the same identity agg_ids assigns) for c0=0 and the node id for
# c0=1/2. Mirrored scalar-for-scalar in cpp/oracle.cpp.
STREAM_POISON = np.uint32(0x6A09E667)    # per (round, subdraw, vertex_or_node)
# SPEC §A.4 correlated DPoS producer suppression: one draw per
# (window, producer) with window = round // suppress_window, so a
# suppressed producer misses EVERY slot scheduled inside the window —
# the targeted (correlated) stream RESILIENCE.md §8 records iid
# slot-miss keying cannot emulate. dpos only; mirrored.
STREAM_SUPPRESS = np.uint32(0x1F83D9AB)  # per (window, subdraw, producer)
# SPEC §B per-node view-synchronizer timer skew: one activation draw and
# one depth draw per (round, node) — c0 selects the subdraw: 0 = skew
# activation (fires when the draw < desync_cutoff), 1 = the skew depth
# d in [1, max_skew_rounds] added to the node's local view timer. BFT
# engines only (pbft, hotstuff — the per-node pacemakers); a compiled
# no-op at the desync_rate=0 default. Mirrored scalar-for-scalar in
# cpp/oracle.cpp.
STREAM_DESYNC = np.uint32(0x5BE0CD19)    # per (round, subdraw, node)
# Host-side adversary-search orchestration (tools/advsearch): candidate
# sampling, mutation and eval-seed draws. Never drawn on device or in
# the oracle — registered so search runs replay exactly from one seed
# without colliding with any simulation stream.
STREAM_SEARCH = np.uint32(0x3C6EF372)   # per (generation, subdraw, index)

# --- machine-checked stream registry (tools/lint, check `streams`) ---------
#
# For each stream: what each of the three absorb slots (ctx, c0, c1) of
# `random_u32(seed^stream, ctx, c0, c1)` keys. `None` means the slot is
# PINNED — every call site must pass a literal constant there, because
# varying a pinned slot reuses counter space another draw owns and
# silently correlates independent adversary events. "subdraw" slots are
# literal sub-stream selectors (e.g. STREAM_CRASH c0: 0 = crash draw,
# 1 = recover draw). Adding a stream = add the constant above, its
# entry here, and the cpp/threefry.h mirror (or STREAM_TPU_ONLY);
# docs/STATIC_ANALYSIS.md walks through it.
STREAM_KEYS = {
    "STREAM_DELIVER": ("round", "src", "dst"),        # via the §2 mixer
    "STREAM_TIMEOUT": ("term", None, "node"),
    "STREAM_CHURN": ("round", None, None),
    "STREAM_PARTITION": ("round", "subdraw", "node"),  # c0: 0=active 1=side
    "STREAM_STAKE": (None, None, "validator"),
    "STREAM_VOTE": ("epoch", None, "validator"),
    "STREAM_VALUE": ("round_or_view", "subdraw", "node_or_slot"),
    "STREAM_BYZANTINE": ("reserved", "reserved", "reserved"),
    "STREAM_EQUIV": ("round", "sender", "receiver"),
    "STREAM_CRASH": ("round", "subdraw", "node"),      # c0: 0=crash 1=recover
    "STREAM_SLOTMISS": ("round", "subdraw", "producer"),  # c0: 0 (reserved)
    "STREAM_DELAY": ("origin_round", "delay", "edge"),  # via the §A.2 mixer
    "STREAM_ATTACK": ("round", None, None),
    "STREAM_AGG": ("round", "subdraw", "aggregator"),  # c0: 0=fail 1=stale 2=depth
    "STREAM_POISON": ("round", "subdraw", "vertex_or_node"),  # c0: 0=serve 1=lie 2=val
    "STREAM_SUPPRESS": ("window", "subdraw", "producer"),  # c0: 0 (reserved)
    "STREAM_DESYNC": ("round", "subdraw", "node"),  # c0: 0=activation 1=depth
    "STREAM_SEARCH": ("generation", "subdraw", "index"),
}

# Streams the C++ oracle deliberately does NOT mirror (cpp/threefry.h):
# the SPEC §A.3 targeted Raft attacks are TPU-engine-only — Config
# rejects attack != "none" on the cpu engine rather than silently
# simulating different trajectories. (§6c STREAM_CRASH *is* mirrored
# since the adversary-library PR.) STREAM_SEARCH is host-orchestration
# only (tools/advsearch) — it keys no simulated trajectory, so the
# oracle has nothing to mirror.
STREAM_TPU_ONLY = frozenset({"STREAM_ATTACK", "STREAM_SEARCH"})

# Streams drawn through the SPEC §2 murmur-style mixer (delivery_u32_*,
# delay_u32_*), never through the threefry entry points — the two
# generators share a key constant but not counter space, so a threefry
# draw keyed on a mixer stream would be a new, unregistered stream in
# disguise.
STREAM_MIXER_ONLY = frozenset({"STREAM_DELIVER", "STREAM_DELAY"})


def _rotl32_np(x: np.ndarray, r: int) -> np.ndarray:
    x = x.astype(np.uint32, copy=False)
    return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)


def threefry2x32_np(k0, k1, c0, c1):
    """Threefry-2x32, 20 rounds. All args uint32 scalars or arrays.

    Returns ``(y0, y1)`` uint32 arrays, broadcast over inputs.
    """
    with np.errstate(over="ignore"):  # uint32 wraparound is the algorithm
        k0 = np.asarray(k0, dtype=np.uint32)
        k1 = np.asarray(k1, dtype=np.uint32)
        x0 = np.asarray(c0, dtype=np.uint32).copy()
        x1 = np.asarray(c1, dtype=np.uint32).copy()
        x0, x1, k0, k1 = np.broadcast_arrays(x0, x1, k0, k1)
        x0, x1 = x0.astype(np.uint32).copy(), x1.astype(np.uint32).copy()

        ks0, ks1 = k0, k1
        ks2 = (ks0 ^ ks1 ^ _KS_PARITY).astype(np.uint32)

        x0 = (x0 + ks0).astype(np.uint32)
        x1 = (x1 + ks1).astype(np.uint32)

        ks = (ks0, ks1, ks2)
        for block in range(5):
            rots = _ROT_A if block % 2 == 0 else _ROT_B
            for r in rots:
                x0 = (x0 + x1).astype(np.uint32)
                x1 = _rotl32_np(x1, r) ^ x0
            x0 = (x0 + ks[(block + 1) % 3]).astype(np.uint32)
            x1 = (x1 + ks[(block + 2) % 3] + np.uint32(block + 1)).astype(np.uint32)
        return x0, x1


def random_u32_np(seed: int, stream: np.uint32, ctx, c0, c1):
    """Draw uint32 words: key=(lo32(seed)^stream, ctx), ctr=(c0, c1).

    ``ctx``, ``c0``, ``c1`` (uint32) may be arrays; broadcasts. Returns the
    first output word y0. See docs/SPEC.md §1 for the stream table.
    """
    k0 = np.uint32(np.uint64(seed) & np.uint64(0xFFFFFFFF)) ^ np.uint32(stream)
    k1 = np.asarray(ctx, dtype=np.uint32)
    y0, _ = threefry2x32_np(k0, k1, np.asarray(c0, np.uint32), np.asarray(c1, np.uint32))
    return y0


# --- jnp twin ---------------------------------------------------------------

import jax.numpy as jnp


def _rotl32_jnp(x, r: int):
    return (jnp.left_shift(x, np.uint32(r)) | jnp.right_shift(x, np.uint32(32 - r)))


def threefry2x32_jnp(k0, k1, c0, c1):
    """Traceable twin of :func:`threefry2x32_np`. uint32 in/out."""
    k0 = jnp.asarray(k0, dtype=jnp.uint32)
    k1 = jnp.asarray(k1, dtype=jnp.uint32)
    x0 = jnp.asarray(c0, dtype=jnp.uint32)
    x1 = jnp.asarray(c1, dtype=jnp.uint32)

    ks0, ks1 = k0, k1
    ks2 = ks0 ^ ks1 ^ jnp.uint32(_KS_PARITY)

    x0 = x0 + ks0
    x1 = x1 + ks1

    ks = (ks0, ks1, ks2)
    for block in range(5):
        rots = _ROT_A if block % 2 == 0 else _ROT_B
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl32_jnp(x1, r) ^ x0
        x0 = x0 + ks[(block + 1) % 3]
        x1 = x1 + ks[(block + 2) % 3] + jnp.uint32(block + 1)
    return x0, x1


def random_u32_jnp(seed, stream, ctx, c0, c1):
    """Traceable twin of :func:`random_u32_np`. ``seed`` may be a traced
    uint32 array (per-sweep seeds under vmap); ctx/c0/c1 broadcast."""
    seed32 = jnp.asarray(seed).astype(jnp.uint32)
    k0 = seed32 ^ jnp.uint32(int(np.uint32(stream)))
    k1 = jnp.asarray(ctx, dtype=jnp.uint32)
    y0, _ = threefry2x32_jnp(k0, k1, jnp.asarray(c0, jnp.uint32), jnp.asarray(c1, jnp.uint32))
    return y0


# --- delivery mixer ---------------------------------------------------------
#
# The per-edge delivery drop draw is the single highest-volume random
# decision in the simulator: N^2 draws per round per sweep (8.6e9 u32
# words for the flagship raft-1024x1024x8 run). At that volume the
# 20-round Threefry schedule is ~25% of the whole TPU round kernel
# (benchmarks/profile_raft.py ablation, 2026-07-29). SPEC §2 therefore
# draws STREAM_DELIVER words from a MurmurHash3-style absorb/finalize
# mixer (Appleby, public domain; ~15 VPU ops/edge after hoisting vs ~110
# for threefry). Every other stream (timeout, churn, partition, value,
# stake, vote, byzantine, equivocation) is O(N) or O(1) per round and
# stays on Threefry. The mixer is implemented three times (numpy here,
# jnp below, scalar C++ in cpp/threefry.h) and cross-validated in
# tests/test_rng.py + tests/test_oracle_bindings.py; its avalanche
# quality is sanity-checked in tests/test_rng.py (bit-flip balance).
#
# Chain (all u32, wrapping):
#   h = absorb(absorb(absorb(lo32(seed) ^ STREAM_DELIVER, r), i), j)
#   delivery_u32 = fmix(h)
# absorb(h, c) = rotl(h ^ (rotl(c*0xCC9E2D51, 15) * 0x1B873593), 13) * 5
#                + 0xE6546B64
# fmix(h): h ^= h>>16; h *= 0x85EBCA6B; h ^= h>>13; h *= 0xC2B2AE35;
#          h ^= h>>16  (murmur3 finalizer — full avalanche)

_MIX_C1 = np.uint32(0xCC9E2D51)
_MIX_C2 = np.uint32(0x1B873593)
_MIX_C3 = np.uint32(0xE6546B64)
_FMIX_A = np.uint32(0x85EBCA6B)
_FMIX_B = np.uint32(0xC2B2AE35)


def mix_absorb_np(h, c):
    with np.errstate(over="ignore"):
        h = np.asarray(h, np.uint32)
        k = (np.asarray(c, np.uint32) * _MIX_C1).astype(np.uint32)
        k = (_rotl32_np(k, 15) * _MIX_C2).astype(np.uint32)
        h, k = np.broadcast_arrays(h, k)
        h = _rotl32_np(h.astype(np.uint32) ^ k, 13)
        return (h * np.uint32(5) + _MIX_C3).astype(np.uint32)


def mix_fin_np(h):
    with np.errstate(over="ignore"):
        h = np.asarray(h, np.uint32)
        h = h ^ (h >> np.uint32(16))
        h = (h * _FMIX_A).astype(np.uint32)
        h = h ^ (h >> np.uint32(13))
        h = (h * _FMIX_B).astype(np.uint32)
        return h ^ (h >> np.uint32(16))


def delivery_u32_np(seed, r, i, j):
    """SPEC §2 per-edge delivery draw (numpy). Broadcasts over all args."""
    k0 = ((np.asarray(seed, np.uint64) & np.uint64(0xFFFFFFFF))
          .astype(np.uint32) ^ STREAM_DELIVER)
    h = mix_absorb_np(k0, r)
    return mix_fin_np(mix_absorb_np(mix_absorb_np(h, i), j))


def mix_absorb_jnp(h, c):
    h = jnp.asarray(h, jnp.uint32)
    k = jnp.asarray(c, jnp.uint32) * jnp.uint32(_MIX_C1)
    k = _rotl32_jnp(k, 15) * jnp.uint32(_MIX_C2)
    h = _rotl32_jnp(h ^ k, 13)
    return h * jnp.uint32(5) + jnp.uint32(_MIX_C3)


def mix_fin_jnp(h):
    h = h ^ jnp.right_shift(h, jnp.uint32(16))
    h = h * jnp.uint32(_FMIX_A)
    h = h ^ jnp.right_shift(h, jnp.uint32(13))
    h = h * jnp.uint32(_FMIX_B)
    return h ^ jnp.right_shift(h, jnp.uint32(16))


def delivery_u32_jnp(seed, r, i, j):
    """Traceable twin of :func:`delivery_u32_np`. ``seed`` may be traced.

    Call sites that evaluate many edges should hoist the prefix:
    ``mix_absorb_jnp`` over (seed-key, r) is per-round, over i per-row —
    only the j-absorb and the finalizer are per-edge.
    """
    k0 = jnp.asarray(seed).astype(jnp.uint32) ^ jnp.uint32(int(STREAM_DELIVER))
    h = mix_absorb_jnp(k0, r)
    return mix_fin_jnp(mix_absorb_jnp(mix_absorb_jnp(h, i), j))


def delay_u32_np(seed, q, d, i, j):
    """SPEC §A.2 delayed-retransmission draw (numpy): one u32 per
    (origin round q, delay d, edge i→j), via the same murmur-style
    mixer as :func:`delivery_u32_np` but keyed on STREAM_DELAY and
    absorbing FOUR values — (q, d, i, j) — so delayed copies of one
    flight at different d are independent and never collide with the
    base delivery stream. Broadcasts over all args."""
    k0 = ((np.asarray(seed, np.uint64) & np.uint64(0xFFFFFFFF))
          .astype(np.uint32) ^ STREAM_DELAY)
    h = mix_absorb_np(mix_absorb_np(k0, q), d)
    return mix_fin_np(mix_absorb_np(mix_absorb_np(h, i), j))


def delay_u32_jnp(seed, q, d, i, j):
    """Traceable twin of :func:`delay_u32_np`. ``seed`` may be traced;
    the (seed, q, d) absorbs hoist themselves through broadcasting at
    edge-mask call sites (scalars per round and per d)."""
    k0 = jnp.asarray(seed).astype(jnp.uint32) ^ jnp.uint32(int(STREAM_DELAY))
    h = mix_absorb_jnp(mix_absorb_jnp(k0, q), d)
    return mix_fin_jnp(mix_absorb_jnp(mix_absorb_jnp(h, i), j))


def prob_threshold_u32(p: float) -> int:
    """Integer cutoff for probability ``p``: draw < cutoff ⇔ event fires.

    Computed once on the host; both engines compare raw u32 draws against
    this integer, so no float ever enters the hot path.
    """
    if p <= 0.0:
        return 0
    if p >= 1.0:
        # u32 comparison is strict `draw < cutoff`; 0xFFFFFFFF fires with
        # probability 1 - 2^-32. Both engines use the identical comparison,
        # so cross-engine agreement is exact regardless.
        return 0xFFFFFFFF
    return min(int(p * 4294967296.0), 0xFFFFFFFF)
