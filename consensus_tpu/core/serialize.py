"""Canonical decided-log serialization — the byte-equivalence contract.

The acceptance test of the whole framework is *byte*-equivalence of decided
logs between the TPU engine and the C++ oracle (BASELINE.json:2,5;
SURVEY.md §4.3). Both sides therefore serialize through one fixed spec:

    header:  magic "CTPU" | version u8=1 | protocol u8 | n_sweeps u32 | n_nodes u32
    body:    for sweep b in 0..n_sweeps:        (row-major, little-endian)
               for node n in 0..n_nodes:
                 count u32
                 count × record { a u32, b u32 }

Record meaning per protocol (a, b):
    raft : (term of committed entry, entry value)     — in log order, k < commit
    pbft : (slot index, decided value)                — decided slots, ascending
    paxos: (slot index, learned value)                — learned slots, ascending
    dpos : (round index, producer id of chain block)  — in chain order

The C++ oracle (cpp/oracle.cpp) emits the identical layout; equality is
checked on raw bytes and reported as a SHA-256 digest (O(1) to compare,
SURVEY.md §5 "metrics").
"""
from __future__ import annotations

import hashlib
import struct

import numpy as np

MAGIC = b"CTPU"
VERSION = 1
PROTOCOL_IDS = {"raft": 0, "pbft": 1, "paxos": 2, "dpos": 3}


def serialize_decided(protocol: str, counts: np.ndarray,
                      rec_a: np.ndarray, rec_b: np.ndarray) -> bytes:
    """Serialize per-(sweep, node) decided logs.

    counts: [B, N] int — number of records for each node.
    rec_a, rec_b: [B, N, L] int — record fields; only the first counts[b, n]
    entries of each row are meaningful.
    """
    counts = np.asarray(counts)
    rec_a = np.asarray(rec_a)
    rec_b = np.asarray(rec_b)
    if counts.ndim != 2 or rec_a.ndim != 3 or rec_b.ndim != 3:
        raise ValueError("counts must be [B,N]; records [B,N,L]")
    B, N = counts.shape
    out = bytearray()
    out += MAGIC
    out += struct.pack("<BBII", VERSION, PROTOCOL_IDS[protocol], B, N)
    ca = counts.astype(np.int64)
    a32 = rec_a.astype(np.uint32)
    b32 = rec_b.astype(np.uint32)
    for b in range(B):
        for n in range(N):
            c = int(ca[b, n])
            out += struct.pack("<I", c)
            if c:
                inter = np.empty(2 * c, dtype=np.uint32)
                inter[0::2] = a32[b, n, :c]
                inter[1::2] = b32[b, n, :c]
                out += inter.tobytes()  # numpy is little-endian on all targets here
    return bytes(out)


def pack_sparse(mask: np.ndarray, vals: np.ndarray):
    """Turn dense decided arrays [B, N, S] into (counts, slots, vals) with
    slots ascending — the canonical order for pbft/paxos records."""
    mask = np.asarray(mask, dtype=bool)
    vals = np.asarray(vals)
    B, N, S = mask.shape
    counts = mask.sum(axis=2).astype(np.uint32)
    L = int(counts.max()) if counts.size else 0
    slots = np.zeros((B, N, max(L, 1)), dtype=np.uint32)
    out_vals = np.zeros((B, N, max(L, 1)), dtype=np.uint32)
    for b in range(B):
        for n in range(N):
            idx = np.nonzero(mask[b, n])[0]
            slots[b, n, : idx.size] = idx
            out_vals[b, n, : idx.size] = vals[b, n, idx]
    return counts, slots, out_vals


def digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()
