"""Canonical decided-log serialization — the byte-equivalence contract.

The acceptance test of the whole framework is *byte*-equivalence of decided
logs between the TPU engine and the C++ oracle (BASELINE.json:2,5;
SURVEY.md §4.3). Both sides therefore serialize through one fixed spec:

    header:  magic "CTPU" | version u8=1 | protocol u8 | n_sweeps u32 | n_nodes u32
    body:    for sweep b in 0..n_sweeps:        (row-major, little-endian)
               for node n in 0..n_nodes:
                 count u32
                 count × record { a u32, b u32 }

Record meaning per protocol (a, b):
    raft : (term of committed entry, entry value)     — in log order, k < commit
    pbft : (slot index, decided value)                — decided slots, ascending
    paxos: (slot index, learned value)                — learned slots, ascending
    dpos : (round index, producer id of chain block)  — in chain order
    hotstuff: (height, decided value)                 — committed prefix, ascending

The C++ oracle (cpp/oracle.cpp) emits the identical layout; equality is
checked on raw bytes and reported as a SHA-256 digest (O(1) to compare,
SURVEY.md §5 "metrics").
"""
from __future__ import annotations

import hashlib
import struct

import numpy as np

MAGIC = b"CTPU"
VERSION = 1
PROTOCOL_IDS = {"raft": 0, "pbft": 1, "paxos": 2, "dpos": 3,
                "hotstuff": 4}


def serialize_decided(protocol: str, counts: np.ndarray,
                      rec_a: np.ndarray, rec_b: np.ndarray) -> bytes:
    """Serialize per-(sweep, node) decided logs.

    counts: [B, N] int — number of records for each node.
    rec_a, rec_b: [B, N, L] int — record fields; only the first counts[b, n]
    entries of each row are meaningful.

    Fully vectorized (no per-node Python loop): at benchmark scale the
    host-side serializer must not rival device time (VERDICT r1 weak #4).
    The byte stream is one u32 array — counts at each row's start offset,
    the interleaved (a, b) record pairs in the gaps — emitted little-endian.
    """
    counts = np.asarray(counts)
    rec_a = np.asarray(rec_a)
    rec_b = np.asarray(rec_b)
    if counts.ndim != 2 or rec_a.ndim != 3 or rec_b.ndim != 3:
        raise ValueError("counts must be [B,N]; records [B,N,L]")
    B, N = counts.shape
    L = rec_a.shape[2]
    R = B * N
    header = MAGIC + struct.pack("<BBII", VERSION, PROTOCOL_IDS[protocol], B, N)
    if R == 0:
        return header

    c = counts.reshape(R).astype(np.int64)
    if np.any(c < 0) or np.any(c > L):
        raise ValueError("counts out of range [0, L]")
    # Row r occupies 1 + 2*c[r] u32 words starting at start[r].
    words = 1 + 2 * c
    start = np.concatenate(([0], np.cumsum(words)[:-1]))
    total = int(words.sum())

    out = np.empty(total, dtype="<u4")
    is_count = np.zeros(total, dtype=bool)
    is_count[start] = True
    out[is_count] = c

    # Record words fill the gaps between counts, in row-major record
    # order. Gather O(nnz): each record's (row, within-row k) index pair,
    # never a dense [R, 2L] interleave (which would cost ~2.5x the input
    # footprint at the paxos-10kx10k scale).
    nnz = int(c.sum())
    if nnz:
        rec_off = np.concatenate(([0], np.cumsum(c)[:-1]))
        rows = np.repeat(np.arange(R, dtype=np.int64), c)
        k = np.arange(nnz, dtype=np.int64) - np.repeat(rec_off, c)
        rec = np.empty(2 * nnz, dtype="<u4")
        rec[0::2] = rec_a.reshape(R, L)[rows, k].astype(np.uint32)
        rec[1::2] = rec_b.reshape(R, L)[rows, k].astype(np.uint32)
        out[~is_count] = rec
    return header + out.tobytes()


def pack_sparse(mask: np.ndarray, vals: np.ndarray):
    """Turn dense decided arrays [B, N, S] into (counts, slots, vals) with
    slots ascending — the canonical order for pbft/paxos records.

    Vectorized via one np.nonzero: its row-major output order IS the
    canonical order (ascending slot within each (sweep, node) row), so the
    within-row position of each hit is its global rank minus its row's
    exclusive-prefix count. Memory is O(nnz), not O(B*N*S*log) — at the
    Paxos 10k x 10k scale an argsort-based pack would cost ~800 MB.
    """
    mask = np.asarray(mask, dtype=bool)
    vals = np.asarray(vals)
    B, N, S = mask.shape
    counts = mask.sum(axis=2).astype(np.uint32)
    L = int(counts.max()) if counts.size else 0
    slots = np.zeros((B, N, max(L, 1)), dtype=np.uint32)
    out_vals = np.zeros((B, N, max(L, 1)), dtype=np.uint32)

    ib, inode, islot = np.nonzero(mask)
    if ib.size:
        c_flat = counts.reshape(B * N).astype(np.int64)
        offsets = np.concatenate(([0], np.cumsum(c_flat)[:-1]))
        row = ib * N + inode
        pos = np.arange(ib.size, dtype=np.int64) - offsets[row]
        slots[ib, inode, pos] = islot
        out_vals[ib, inode, pos] = vals[ib, inode, islot]
    return counts, slots, out_vals


def digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()
