"""Driver benchmark: one JSON line on stdout, always (rc 0 even on failure).

Flagship config: the NORTH-STAR scale — a 100k-node x 64-round x 8-sweep
Raft run under the SPEC §3b active-sender cap (BASELINE.json:5 defines
the ≥10M steps/sec/chip target on "100k-node Raft+PBFT sweeps"; the
dense 1k×1k config remains benchmarked in benchmarks/RESULTS.json).
Metric is node-round-steps/sec (BASELINE.json:2); ``vs_baseline`` is the
ratio against the 10M steps/sec/chip target (the reference publishes no
numbers of its own, BASELINE.json:13).

Robustness (VERDICT.md round 1, weak #1): the TPU backend (axon tunnel)
can hang or be UNAVAILABLE. Backend init is therefore probed in a
*subprocess* with a hard timeout and retried with backoff; on persistent
failure the benchmark falls back to the XLA CPU backend on a smaller
round count, labels the metric accordingly, and still emits valid JSON —
the driver's one perf capture per round is never lost to a stack trace.

Usage: python bench.py [--nodes N] [--rounds R] [--sweeps B]
                       [--probe-timeout S] [--probe-retries K]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from consensus_tpu.utils.platform import ensure_platform, watchdog


NORTH_STAR_STEPS_PER_SEC = 10_000_000.0


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--sweeps", type=int, default=8)
    ap.add_argument("--max-active", type=int, default=8,
                    help="SPEC §3b active-sender cap (0 = dense engine)")
    ap.add_argument("--log-capacity", type=int, default=128)
    ap.add_argument("--drop-rate", type=float, default=0.01)
    ap.add_argument("--churn-rate", type=float, default=0.001)
    ap.add_argument("--repeats", type=int, default=3)
    # Probe budget ~11 min total (6 x 75s probes + 15/30/45/60/75s
    # backoffs): two of four driver rounds lost their only TPU capture to
    # a transiently hung tunnel (VERDICT r4 weak #1/next #9) — a longer
    # honest effort is cheaper than a lost round.
    ap.add_argument("--probe-timeout", type=float, default=75.0)
    ap.add_argument("--probe-retries", type=int, default=6)
    ap.add_argument("--run-timeout", type=float, default=1800.0,
                    help="hard deadline for the whole benchmark; on expiry "
                         "an error JSON is emitted and the process exits 0 "
                         "(guards against a tunnel that drops mid-run and "
                         "hangs in native code, where no except: can fire)")
    ap.add_argument("--cpu-fallback-rounds", type=int, default=64,
                    help="round count when falling back to the CPU backend "
                         "(steps/sec is a rate; fewer rounds keep wall time "
                         "bounded without changing the metric's meaning)")
    args = ap.parse_args()
    args.repeats = max(1, args.repeats)

    plat_tag = ensure_platform("auto", probe_timeout=args.probe_timeout,
                               retries=args.probe_retries)
    fallback_context = {}
    if plat_tag.startswith("cpu"):
        # Still produce a number, on a smaller shape; the metric name
        # says so explicitly (honest labeling), and the last on-chip
        # measurement from the committed artifact rides along so a
        # fallback round stays readable without git archaeology.
        args.rounds = min(args.rounds, args.cpu_fallback_rounds)
        args.nodes = min(args.nodes, 4096)
        log(f"CPU fallback; rounds -> {args.rounds}, nodes -> {args.nodes}")
        fallback_context = last_witnessed_tpu()
    else:
        log(f"accelerator ok, platform={plat_tag}")

    cap = f"-cap{args.max_active}" if args.max_active else ""
    metric = (f"raft-{args.nodes}node-{args.rounds}round{cap} "
              f"node-round-steps/sec [{plat_tag}]")

    def on_timeout():
        log(f"FAILED: exceeded --run-timeout {args.run_timeout:.0f}s "
            "(backend hang mid-run?)")
        emit({"metric": metric, "value": 0.0, "unit": "steps/sec",
              "vs_baseline": 0.0,
              "error": f"hang: benchmark exceeded {args.run_timeout:.0f}s"})

    try:
        with watchdog(args.run_timeout, on_timeout):
            run_benchmark(args, metric, fallback_context)
    except Exception as exc:  # noqa: BLE001 — the failure mode must be data
        log(f"FAILED: {type(exc).__name__}: {exc}")
        emit({"metric": metric, "value": 0.0, "unit": "steps/sec",
              "vs_baseline": 0.0,
              "error": f"{type(exc).__name__}: {exc}"[:500],
              **fallback_context})


def last_witnessed_tpu() -> dict:
    """Context fields from the committed on-chip artifact (the flagship
    `raft-100k` row of benchmarks/RESULTS.json), for CPU-fallback output."""
    import pathlib
    try:
        data = json.loads((pathlib.Path(__file__).parent / "benchmarks" /
                           "RESULTS.json").read_text())
        if not str(data.get("platform", "")).startswith(("tpu", "axon")):
            return {}
        for row in data.get("rows", []):
            if row.get("name") == "raft-100k" and "tpu" in row:
                return {"last_tpu_steps_per_sec":
                            round(float(row["tpu"]["steps_per_sec"]), 1),
                        "last_tpu_source": "benchmarks/RESULTS.json raft-100k"}
    except Exception:  # noqa: BLE001 — best-effort context; a malformed
        pass           # artifact must never cost the benchmark round
    return {}


def run_benchmark(args, metric: str, extra: dict | None = None) -> None:
    import jax
    import numpy as np

    from consensus_tpu.core.config import Config
    from consensus_tpu.network import runner

    dev = jax.devices()[0]
    log(f"device={dev}, platform={dev.platform}")

    cfg = Config(
        protocol="raft", engine="tpu",
        n_nodes=args.nodes, n_rounds=args.rounds, n_sweeps=args.sweeps,
        log_capacity=args.log_capacity,
        max_entries=max(1, args.log_capacity - 16),
        max_active=args.max_active,
        drop_rate=args.drop_rate, churn_rate=args.churn_rate, seed=42,
    )
    steps = cfg.n_sweeps * cfg.n_nodes * cfg.n_rounds
    from consensus_tpu.network import simulator
    eng = simulator.engine_def(cfg)

    t0 = time.perf_counter()
    carry = runner.run_device(cfg, eng)  # compile + warm up
    log(f"warmup (incl. compile) {time.perf_counter() - t0:.1f}s")

    # Timed: the round loop + a minimal host sync. The full final-state
    # pull (~MBs of logs over the remote tunnel) happens once below, for
    # the sanity check — it is a one-time epilogue, not part of the
    # per-round throughput the metric defines (BASELINE.json:2).
    # Each repeat dispatches a DIFFERENT seed vector (offset by
    # (i+1)*n_sweeps): the tunnel caches identical dispatches (ADVICE
    # r5 / docs/PERF.md r5), and the branchless kernels make throughput
    # seed-invariant. The sanity check reads the kept warmup carry.
    import dataclasses
    best = float("inf")
    for i in range(args.repeats):
        seeds = runner.make_seeds(dataclasses.replace(
            cfg, seed=cfg.seed + (i + 1) * cfg.n_sweeps))
        t0 = time.perf_counter()
        runner.run_device(cfg, eng, seeds=seeds)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        log(f"run {i}: {dt:.3f}s = {steps / dt / 1e6:.2f}M steps/s")
    out = {k: np.asarray(v) for k, v in eng.extract(carry).items()}

    # Sanity: the simulation must actually decide entries, or the number
    # is meaningless — report it as an error *in the JSON*, not a crash.
    committed = int(out["commit"].max())
    log(f"max committed entries = {committed}")
    value = steps / best
    result = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "steps/sec",
        "vs_baseline": round(value / NORTH_STAR_STEPS_PER_SEC, 4),
        # The machine-parseable trajectory row tools/ledger.py ingests
        # directly (the metric string above stays for humans and older
        # consumers; the ledger no longer scrapes it when this block is
        # present).
        "trajectory": {
            "schema": 1,
            "timestamp": time.time(),
            "platform": dev.platform,
            "protocol": cfg.protocol,
            "nodes": cfg.n_nodes,
            "rounds": cfg.n_rounds,
            "sweeps": cfg.n_sweeps,
            "max_active": cfg.max_active,
            "steps": steps,
            "wall_s": round(best, 6),
            "repeats": args.repeats,
            "max_committed": committed,
        },
        **(extra or {}),
    }
    if committed == 0:
        result["error"] = "degenerate run: nothing committed"
    emit(result)


if __name__ == "__main__":
    main()
