"""Driver benchmark: one JSON line on stdout.

Flagship config: the Raft 1k-node × 1k-round batched log-match sweep
(BASELINE.md config 2) on the real TPU chip. Metric is
node-round-steps/sec (BASELINE.json:2); ``vs_baseline`` is the ratio
against the driver's north-star target of 10M steps/sec/chip
(BASELINE.json:5 — the reference publishes no numbers of its own,
BASELINE.json:13, so the target is the only defined baseline).

Usage: python bench.py [--nodes N] [--rounds R] [--sweeps B] [--json-only]
"""
from __future__ import annotations

import argparse
import json
import sys
import time


NORTH_STAR_STEPS_PER_SEC = 10_000_000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1024)
    ap.add_argument("--rounds", type=int, default=1024)
    ap.add_argument("--sweeps", type=int, default=8)
    ap.add_argument("--log-capacity", type=int, default=128)
    ap.add_argument("--drop-rate", type=float, default=0.01)
    ap.add_argument("--churn-rate", type=float, default=0.001)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()
    args.repeats = max(1, args.repeats)

    import jax

    from consensus_tpu.core.config import Config
    from consensus_tpu.engines.raft import raft_run

    dev = jax.devices()[0]
    print(f"bench: device={dev}, platform={dev.platform}", file=sys.stderr)

    cfg = Config(
        protocol="raft", engine="tpu",
        n_nodes=args.nodes, n_rounds=args.rounds, n_sweeps=args.sweeps,
        log_capacity=args.log_capacity,
        max_entries=max(1, args.log_capacity - 16),
        drop_rate=args.drop_rate, churn_rate=args.churn_rate, seed=42,
    )
    steps = cfg.n_sweeps * cfg.n_nodes * cfg.n_rounds

    t0 = time.perf_counter()
    raft_run(cfg)  # compile + warm up
    print(f"bench: warmup (incl. compile) {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    best = float("inf")
    for i in range(args.repeats):
        t0 = time.perf_counter()
        out = raft_run(cfg)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        print(f"bench: run {i}: {dt:.3f}s = {steps / dt / 1e6:.2f}M steps/s",
              file=sys.stderr)

    # Sanity: the simulation must actually decide entries, or the number
    # is meaningless — fail loudly rather than report idle throughput.
    committed = int(out["commit"].max())
    print(f"bench: max committed entries = {committed}", file=sys.stderr)
    if committed == 0:
        print("bench: FAILED — nothing committed; config is degenerate",
              file=sys.stderr)
        sys.exit(1)

    value = steps / best
    print(json.dumps({
        "metric": "raft-1k-node-1k-round node-round-steps/sec",
        "value": round(value, 1),
        "unit": "steps/sec",
        "vs_baseline": round(value / NORTH_STAR_STEPS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
