# Repo-level entry points. The whole gate is ONE command:
#
#   make check     # consensus-lint + hlocheck + costcheck + ruff + mypy
#                  # + clang-tidy + scenario smoke + advsearch smoke
#                  # + sweepd service smoke + tier-1
#   make ledger    # cross-run perf ledger + regression verdict
#
# (tools/check.py gates hlocheck on jax and ruff/mypy/clang-tidy on
# availability and prints a per-layer summary; see
# docs/STATIC_ANALYSIS.md.)

PY ?= python

check:
	$(PY) tools/check.py

lint:
	$(PY) -m tools.lint

hlocheck:
	$(PY) -m tools.hlocheck

costcheck:
	$(PY) -m tools.costmodel

ledger:
	$(PY) tools/ledger.py --check

tidy:
	$(MAKE) -C cpp tidy

scenario-smoke:
	$(PY) tools/check.py --only scenarios

advsearch-smoke:
	$(PY) tools/check.py --only advsearch

service-smoke:
	$(PY) tools/check.py --only service

san-test:
	$(MAKE) -C cpp san-test

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly

.PHONY: check lint hlocheck costcheck ledger tidy san-test scenario-smoke \
	advsearch-smoke service-smoke test
